#!/usr/bin/env bash
# Local CI driver: the analog of the reference's `scripts/ci.bash` (runs
# every suite, collects CSVs, renders plots — `scripts/ci.bash:7-90`).
# Usage: scripts/ci.bash [outdir]   (FULL=1 for reference-scale workloads)
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=${1:-ci-out}
mkdir -p "$OUT"

echo "== tests =="
python -m pytest tests/ -q

echo "== examples =="
for f in examples/*.py; do python "$f"; done

echo "== flagship bench =="
python bench.py --replicas 256 --keys 1024 --steps 8 --repeats 2 \
  --min-time 0.3 | tee "$OUT/bench.json"

echo "== bench suite =="
# rows land straight in $OUT: the default would wipe the committed
# measurement CSVs in benches/out (run_all.sh's OUT override, r5)
OUT="$OUT" DUR=${DUR:-1.0} FULL=${FULL:-} bash benches/run_all.sh

echo "== plots =="
python benches/plot.py --csv "$OUT/scaleout_benchmarks.csv" \
  --out "$OUT" || echo "(no scaleout CSV to plot)"

echo "CI OK — artifacts in $OUT/"
