#!/usr/bin/env python
"""ThreadSanitizer stress for the native engine (run with NR_TPU_TSAN=1).

The reference ships no race detection (SURVEY.md §5); this script runs
the engine's concurrency surfaces under `-fsanitize=thread`:

1. NR flat combining: many threads, batched writes + reads, one log;
2. CNR per-log collection: cross-log batches exercising the publication
   record seqlock (hash-tagged slots, out-of-order response completion);
3. the distributed rwlock via the single-log read path;
4. relaxed multikey reads racing writers.

TSAN reports go to stderr; the script exits non-zero if the engine
diverged. Usage:

    NR_TPU_TSAN=1 python scripts/tsan_stress.py [seconds-per-phase]

Note: a `data race` report on `PubRecord::opcodes/args` between the
owner's (seqlock-odd) publication writes and a combiner's speculative
scan would be the EXPECTED seqlock pattern (reads validated and
discarded on seq mismatch) — real findings are races on ring cells,
cursors, or response slots.
"""

import os
import subprocess
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

if os.environ.get("NR_TPU_TSAN") != "1":
    sys.exit("set NR_TPU_TSAN=1 (the sanitized build) before running")

if "libtsan" not in os.environ.get("LD_PRELOAD", ""):
    # a dlopen'd -fsanitize=thread library hits the static-TLS limit
    # ("cannot allocate memory in static TLS block"); the runtime must be
    # preloaded before the interpreter starts — re-exec with LD_PRELOAD
    tsan = subprocess.run(
        ["g++", "-print-file-name=libtsan.so"],
        capture_output=True, text=True,
    ).stdout.strip()
    env = dict(os.environ, LD_PRELOAD=tsan,
               TSAN_OPTIONS=os.environ.get("TSAN_OPTIONS", "")
               + " report_bugs=1")
    os.execvpe(sys.executable, [sys.executable] + sys.argv, env)

from node_replication_tpu.native import (  # noqa: E402
    MODEL_HASHMAP,
    MODEL_SORTEDSET,
    NativeEngine,
)

DUR = float(sys.argv[1]) if len(sys.argv) > 1 else 2.0


def drive(e, n_threads, mixed_logs, keyspace):
    stop = threading.Event()
    errs = []

    def worker(g):
        try:
            tok = e.register(g % e.n_replicas)
            n = 0
            while not stop.is_set():
                ops = [
                    (1, (g * 131 + n + j) % keyspace, n + j)
                    for j in range(16)
                ]
                e.execute_mut_batch(ops, tok)
                e.execute((1, (g + n) % keyspace), tok)
                # batched read path: read-lock held across the batch,
                # racing other threads' combiners (r5)
                e.execute_batch(
                    [(1, (g + n + j) % keyspace) for j in range(8)], tok
                )
                if mixed_logs:
                    # multikey relaxed read racing the writers
                    e.execute((2, 0, keyspace), tok)
                n += 16
        except Exception as ex:  # pragma: no cover
            errs.append(ex)

    ts = [threading.Thread(target=worker, args=(g,),
                           name=f"tsan-worker-{g}")
          for g in range(n_threads)]
    for t in ts:
        t.start()
    time.sleep(DUR)
    stop.set()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]
    e.sync()
    assert e.replicas_equal(), "replicas diverged under stress"


def main():
    print(f"phase 1: NR flat combining ({DUR}s)", flush=True)
    with NativeEngine(MODEL_HASHMAP, 512, n_replicas=2,
                      log_capacity=1 << 14) as e:
        drive(e, n_threads=6, mixed_logs=False, keyspace=512)

    print(f"phase 2: CNR cross-log batches ({DUR}s)", flush=True)
    with NativeEngine(MODEL_HASHMAP, 512, n_replicas=2,
                      log_capacity=1 << 14, nlogs=4) as e:
        drive(e, n_threads=6, mixed_logs=False, keyspace=512)

    print(f"phase 3: CNR + relaxed multikey reads ({DUR}s)", flush=True)
    with NativeEngine(MODEL_SORTEDSET, 512, n_replicas=2,
                      log_capacity=1 << 14, nlogs=4) as e:
        drive(e, n_threads=6, mixed_logs=True, keyspace=512)

    # r5: the comparison maps have their own concurrency protocols —
    # the lockfree map's packed-slot CAS probes and the evmap left-right
    # pin/flip/drain/replay cycle (reads race plain table stores unless
    # the drain is airtight; the r5 review found a re-pin hole here)
    print(f"phase 4: comparison maps (lockfree, evmap) ({DUR}s)",
          flush=True)
    from node_replication_tpu.native import bench_cmp

    for system in ("lockfree", "evmap"):
        total, _ = bench_cmp(system, 8, 30, 4096, 32, int(DUR * 1000), 3)
        assert total > 0, system

    print("tsan stress OK (see stderr for sanitizer reports)", flush=True)


if __name__ == "__main__":
    main()
