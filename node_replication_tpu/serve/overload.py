"""Overload plane: adaptive admission, priority shedding, brownout.

The static `queue_depth` bound (PR 3) degrades binarily under
sustained overload: every request either queues toward a deadline miss
or sheds as `Overloaded`, and nothing upstream ever slows the primary.
The classic overload-control results (CoDel's queue-DELAY control,
SEDA's adaptive admission — PAPERS.md) all key the control signal to
*measured latency*, not queue length: a standing queue is the failure,
not the depth number. This module is that control plane:

- **AIMD admission** (`OverloadGovernor`): each replica's admission
  limit adapts every combiner round. The control signal is the round's
  measured *queue delay* — how long the oldest request of the batch
  waited between admission and batch assembly (exactly the sojourn
  time CoDel controls). Delay above `target_delay_s` (or backpressure
  past its high watermark, or the live `serve.request.latency_s`
  histogram's p99 crossing the configured deadline) halves the limit
  (multiplicative decrease); a clean round with no backpressure adds
  `increase` slots (additive increase). Between the watermarks the
  limit HOLDS — lag that is present but below the ceiling stops
  growth without collapsing admission.
- **Priority shedding** (`CRITICAL`/`NORMAL`/`BULK` on `submit`):
  when the adaptive limit is reached, an arriving higher-priority
  request EVICTS the newest queued lower-priority one (its future
  rejects with `Overloaded`) instead of shedding itself — so BULK
  traffic always sheds first and a CRITICAL op is shed only when the
  queue holds nothing but CRITICAL ops. The invariant is *measured*,
  not assumed: `priority_inversions` counts any CRITICAL shed that
  happened while a lower-priority op sat queued (structurally zero;
  the sim property and the bench gate assert it stays zero).
- **Brownout reads**: past the brownout watermark (queue-delay EWMA >
  `brownout_enter` × target, with hysteresis on exit) reads degrade to
  the bounded-staleness path instead of paying read-sync — the
  on-primary analog of `repl/follower.read(max_lag_pos=...)`
  (`NodeReplicated.execute_stale` dispatches against the replica's
  current state; the frontend first checks `read_lag(rid)` against
  `brownout_max_lag` and falls back to the synced path when the
  replica is too far behind, so a brownout read can never exceed its
  staleness bound — `max_brownout_lag` records the worst lag actually
  served).
- **End-to-end backpressure** (`LagSource`): downstream lag feeds the
  controller through low/high watermark pairs — the WAL's fsync lag
  (`durable/wal.py:fsync_lag`, auto-registered by the frontend when a
  WAL is attached), the replication shipper's ship lag
  (`ReplicationShipper.install_backpressure`), and a follower's apply
  lag (`Follower.lag`). Below `low`: no pressure. Between: the
  admission limit stops growing. At/above `high`: multiplicative
  decrease every round, so semi-sync replication (`ack_barrier`) can
  never build an unbounded ship backlog — the primary slows instead.

The governor is deliberately lock-light: workers update it once per
combiner round under one small lock; the submit hot path reads the
per-replica limit with a single GIL-atomic dict lookup.
"""

from __future__ import annotations

import dataclasses
import threading

from node_replication_tpu.analysis.locks import make_lock
from typing import Callable

from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.trace import get_tracer

#: priority classes for `ServeFrontend.submit(op, priority=...)`.
#: Lower value = more important; shedding order is strictly reversed
#: (BULK first, CRITICAL last).
CRITICAL = 0
NORMAL = 1
BULK = 2
PRIORITIES = (CRITICAL, NORMAL, BULK)
PRIORITY_NAMES = ("critical", "normal", "bulk")


@dataclasses.dataclass(frozen=True)
class OverloadConfig:
    """Adaptive-admission tuning (`ServeConfig(overload=...)`).

    - `target_delay_s` — the queue-delay setpoint: admitted requests
      should wait about this long for batch assembly. The AIMD loop
      shrinks admission whenever a round's measured delay exceeds it.
    - `min_limit` / `increase` / `decrease` — the AIMD schedule:
      `limit = max(min_limit, limit * decrease)` on a congested round,
      `limit = min(queue_depth, limit + increase)` on a clean one.
    - `brownout_enter` / `brownout_exit` — hysteresis watermarks on
      the queue-delay EWMA, as multiples of `target_delay_s`: brownout
      engages above `enter`, disengages below `exit` (exit < enter so
      the mode cannot flap round-to-round).
    - `brownout_max_lag` — the staleness bound (log positions) a
      brownout read may serve at; a replica lagging further falls back
      to the synced read path.
    - `deadline_p99` — when the metrics registry is live and the
      frontend has a default deadline, a `serve.request.latency_s`
      p99 above `deadline_p99 × deadline` also counts as congestion
      (the p99-vs-deadline signal from the existing obs histograms).
    """

    target_delay_s: float = 0.010
    min_limit: int = 4
    increase: int = 4
    decrease: float = 0.5
    brownout_enter: float = 2.0
    brownout_exit: float = 0.75
    brownout_max_lag: int = 4096
    ewma_alpha: float = 0.3
    deadline_p99: float = 1.0

    def __post_init__(self):
        if self.target_delay_s <= 0:
            raise ValueError("target_delay_s must be > 0")
        if self.min_limit < 1:
            raise ValueError("min_limit must be >= 1")
        if self.increase < 1:
            raise ValueError("increase must be >= 1")
        if not 0.0 < self.decrease < 1.0:
            raise ValueError("decrease must be in (0, 1)")
        if self.brownout_exit >= self.brownout_enter:
            raise ValueError(
                "brownout_exit must be < brownout_enter (hysteresis)"
            )
        if self.brownout_max_lag < 0:
            raise ValueError("brownout_max_lag must be >= 0")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")


@dataclasses.dataclass(frozen=True)
class LagSource:
    """One downstream lag feed with its low/high watermarks.

    `fn()` returns the current lag (log positions, or any monotone
    unit the watermarks share). Pressure is the clamped fraction
    `(lag - low) / (high - low)`: 0 below `low` (no influence), in
    (0, 1) between (admission growth pauses), >= 1 at/above `high`
    (admission shrinks multiplicatively every round)."""

    name: str
    fn: Callable[[], int]
    low: int
    high: int

    def __post_init__(self):
        if not 0 <= self.low < self.high:
            raise ValueError(
                f"lag source {self.name!r} needs 0 <= low < high "
                f"(got {self.low}, {self.high})"
            )

    def pressure(self) -> float:
        lag = float(self.fn())
        return (lag - self.low) / (self.high - self.low)


class OverloadGovernor:
    """Per-frontend adaptive-admission state (one AIMD loop per
    replica, one shared brownout mode + backpressure view).

    The frontend constructs one when `ServeConfig.overload` is set,
    registers each served replica, calls `on_round` from every worker
    after its batch, and consults `limit(rid)` at admission and
    `brownout()` on the read path. All methods are thread-safe."""

    def __init__(self, cfg: OverloadConfig, queue_depth: int,
                 deadline_s: float | None = None,
                 pipeline_depth: int = 0):
        self.cfg = cfg
        self._depth = int(queue_depth)
        self._deadline_s = deadline_s
        #: serve-pipeline overlap depth (`ServeConfig.pipeline_depth`).
        #: The controller needs no special casing for it — the
        #: queue-delay signal it keys on is measured at batch ASSEMBLY
        #: (`ServeFrontend._sweep_batch`), so a pipelined round's
        #: in-flight time never double-counts into the sojourn signal;
        #: pipelining simply shrinks the measured delay, and the AIMD
        #: loop converts that into admission headroom. Recorded here
        #: so `stats()` (and the bench CSVs) can attribute a run's
        #: limits to its overlap mode.
        self.pipeline_depth = int(pipeline_depth)
        self._lock = make_lock("OverloadGovernor._lock")
        self._limits: dict[int, float] = {}
        self._gauges: dict[int, object] = {}
        self._sources: list[LagSource] = []
        self._ewma: float = 0.0
        self._brownout = False
        self._brownout_reads = 0
        self._max_brownout_lag = 0

        reg = get_registry()
        self._m_delay = reg.histogram("serve.queue_delay_s")
        self._m_brownout = reg.counter("serve.brownout.entered")
        self._m_brownout_reads = reg.counter("serve.brownout.reads")
        self._m_evicted = reg.counter("serve.evicted")
        self._m_shed_prio = [
            reg.counter(f"serve.shed.{n}") for n in PRIORITY_NAMES
        ]
        self._g_pressure = reg.gauge("serve.backpressure")
        self._m_lat = reg.histogram("serve.request.latency_s")

    # --------------------------------------------------------- topology

    def register_replica(self, rid: int) -> None:
        """Start replica `rid` at the full static depth (the controller
        only *removes* admission under measured congestion — a cold
        start must not shed)."""
        with self._lock:
            self._limits.setdefault(rid, float(self._depth))
            self._gauges.setdefault(
                rid, get_registry().gauge(f"serve.admit_limit.r{rid}")
            )

    def add_source(self, source: LagSource) -> None:
        """Attach a downstream lag feed (see module docstring for the
        built-in wirings). Sources are polled once per `on_round`."""
        with self._lock:
            if any(s.name == source.name for s in self._sources):
                raise ValueError(
                    f"lag source {source.name!r} already attached"
                )
            self._sources.append(source)

    # -------------------------------------------------------- hot reads

    def limit(self, rid: int) -> int:
        """Current admission bound for replica `rid` (falls back to
        the static depth for a replica never registered)."""
        # nrcheck: unshared — GIL-atomic dict read; admission hot path
        lim = self._limits.get(rid)
        return self._depth if lim is None else int(lim)

    def brownout(self) -> bool:
        # nrcheck: unshared — GIL-atomic flag read; admission hot path
        return self._brownout

    # ------------------------------------------------------ control loop

    def backpressure(self) -> float:
        """Max pressure over the attached lag sources (0 = none,
        >= 1 = past a high watermark). Polled outside the lock — a
        source callback touching the wrapper must not deadlock a
        concurrent `on_round`."""
        with self._lock:
            sources = list(self._sources)
        pressure = 0.0
        for s in sources:
            pressure = max(pressure, s.pressure())
        return max(0.0, pressure)

    def on_round(self, rid: int, queue_delay_s: float,
                 n_ops: int) -> int:
        """One AIMD update from replica `rid`'s combiner round whose
        oldest request waited `queue_delay_s`. Returns the new limit
        (also published to the `serve.admit_limit.r{rid}` gauge)."""
        cfg = self.cfg
        pressure = self.backpressure()
        congested = (
            queue_delay_s > cfg.target_delay_s or pressure >= 1.0
        )
        if (not congested and self._deadline_s is not None
                and queue_delay_s > cfg.target_delay_s / 2):
            # the p99-vs-deadline signal: only meaningful once the
            # live histogram has enough samples to estimate a tail,
            # and only when the CURRENT round's delay corroborates —
            # the histogram is cumulative (process-global, never
            # decays), so without the corroboration gate one past
            # overload episode would read as congestion forever and
            # pin the limit at the floor long after recovery
            reg = get_registry()
            if reg.enabled and self._m_lat.count >= 64:
                p99 = self._m_lat.percentile(0.99)
                congested = p99 > cfg.deadline_p99 * self._deadline_s
        self._m_delay.observe(queue_delay_s)
        self._g_pressure.set(pressure)
        with self._lock:
            lim = self._limits.get(rid, float(self._depth))
            if congested:
                lim = max(float(cfg.min_limit), lim * cfg.decrease)
            elif pressure <= 0.0:
                lim = min(float(self._depth), lim + cfg.increase)
            # else: between watermarks — hold
            self._limits[rid] = lim
            a = cfg.ewma_alpha
            self._ewma = (1.0 - a) * self._ewma + a * queue_delay_s
            flipped = self._update_brownout_locked(pressure)
            gauge = self._gauges.get(rid)
            ewma = self._ewma
        if gauge is not None:
            gauge.set(lim)
        tracer = get_tracer()
        if flipped is not None:
            if flipped:
                self._m_brownout.inc()
            tracer.emit("serve-brownout", on=int(flipped),
                        ewma_delay_s=ewma, pressure=pressure)
        if tracer.enabled:
            tracer.emit("serve-admit-limit", rid=rid, limit=int(lim),
                        delay_s=queue_delay_s, pressure=pressure,
                        n=n_ops)
        return int(lim)

    def _update_brownout_locked(self, pressure: float) -> bool | None:
        """Hysteresis flip; returns the new mode on a transition,
        None when unchanged. Caller holds `_lock`."""
        cfg = self.cfg
        hot = (self._ewma > cfg.brownout_enter * cfg.target_delay_s
               or pressure >= 1.0)
        cool = (self._ewma < cfg.brownout_exit * cfg.target_delay_s
                and pressure < 1.0)
        if not self._brownout and hot:
            self._brownout = True
            return True
        if self._brownout and cool:
            self._brownout = False
            return False
        return None

    # ------------------------------------------------------- accounting

    def note_shed(self, priority: int, evicted: bool = False) -> None:
        """Metrics for one shed (or eviction) of a `priority`-class
        op. Plain-int accounting lives in `_SubmissionQueue` (the
        single source of truth the frontend aggregates — incl. the
        priority-inversion invariant counter); the governor only
        publishes the obs instruments."""
        self._m_shed_prio[priority].inc()
        if evicted:
            self._m_evicted.inc()

    def note_brownout_read(self, lag: int) -> None:
        self._m_brownout_reads.inc()
        with self._lock:
            self._brownout_reads += 1
            if lag > self._max_brownout_lag:
                self._max_brownout_lag = int(lag)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.emit("serve-brownout-read", lag=int(lag))

    def stats(self) -> dict:
        """Controller state incl. a live backpressure poll (the poll
        runs the source callbacks outside the governor lock — see
        `backpressure`). Shed/eviction/inversion counts are NOT here:
        `_SubmissionQueue` owns those and `ServeFrontend.stats()`
        aggregates them."""
        with self._lock:
            out = {
                "limits": {r: int(v)
                           for r, v in sorted(self._limits.items())},
                "pipeline_depth": self.pipeline_depth,
                "ewma_delay_s": self._ewma,
                "brownout": self._brownout,
                "brownout_reads": self._brownout_reads,
                "max_brownout_lag": self._max_brownout_lag,
                "sources": [s.name for s in self._sources],
            }
        out["backpressure"] = self.backpressure()
        return out
