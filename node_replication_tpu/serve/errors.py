"""Typed serve-layer errors.

The serving frontend (`serve/frontend.py`) communicates every
production edge as a TYPED exception so callers can route on it
(retry, shed, fail fast) instead of string-matching. The hierarchy is
flat under `ServeError`:

- `Overloaded` — admission control rejected the request because the
  replica's bounded submission queue is full (load shedding, the
  backpressure signal). Transient by design: `serve/client.py` retries
  it with backoff.
- `DeadlineExceeded` — the request's deadline passed before its batch
  executed; the op was dropped WITHOUT touching the log (a queued
  request is cancellable right up to batch assembly).
- `FrontendClosed` — submitted after `close()`, or still queued when a
  non-draining close tore the queue down. Permanent: retrying cannot
  help.
- `ReplicaFailed` — the replica serving this request died (worker
  exception, injected fault, quarantine fence). Retryable when the op
  provably never reached the log (`maybe_executed=False`):
  `serve/client.py:call_with_retry` then transparently re-routes the
  op to a healthy replica. When the failure struck after the append
  (`maybe_executed=True`) the op WILL replay and only its response was
  lost — resubmitting could duplicate it, so the client must decide
  (the log is the source of truth; a read can disambiguate).
- `StaleRead` — a bounded-staleness read (`read(min_pos=...)`, the
  `repl/` follower read path) found the serving replica's applied
  position still behind the requested bound after the allowed wait.
  The read had no effect; retry later or loosen the bound.
- `NotPrimary` — a write submitted to a read-only (follower-mode)
  frontend (`repl/follower.py`); writes belong on the primary until a
  promotion (`enable_writes`) re-homes write serving here.
- `CircuitOpen` — the CLIENT-side circuit breaker
  (`serve/client.py:CircuitBreaker`) refused the call before it
  reached the frontend: enough consecutive transient failures opened
  the circuit and the cool-down has not elapsed. The op was never
  submitted (zero log effect by construction); retry after
  `retry_after_s`, when the breaker's half-open probe window opens.
- `WrongShard` — the fleet-sharding plane (`shard/`): an op whose key
  routes to a different shard under the current `ShardMap`, or a
  submit carrying a stale map version. The op was rejected before any
  log effect; refresh the map and re-route
  (`serve/client.py:call_with_retry` does so when the frontend
  exposes `refresh_map()`).
- `ShardUnavailable` — the op's shard cannot serve right now (its
  primary died, its backend connection dropped, or its promotion is
  in flight). Transient by design when `maybe_executed=False`;
  `call_with_retry` backs off and retries, and the router re-routes
  once the shard's `PromotionManager` re-homes it.
- `TxnConflict` — an op touched a key locked by a prepared-but-
  undecided cross-shard transaction (`shard/txn.py`). Zero log
  effect; retryable by design (`call_with_retry` backs off — the
  lock clears as soon as the transaction resolves).
- `TxnAborted` — a cross-shard transaction aborted during prepare.
  Presumed-abort 2PC guarantees ZERO log effect on every
  participant, so retrying the WHOLE transaction is exactly-once
  safe (the coordinator's caller decides; per-op retry machinery
  never sees this).
- `TxnInDoubt` — the coordinator lost a participant AFTER the
  durable decision was published (or could not finish phase 2). The
  transaction's outcome is decided and will be enforced by
  recovery — but this caller cannot prove it applied yet. Never
  auto-retried; resolve by decision lookup
  (`TxnCoordinator.recover` / participant `resolve_in_doubt`) or a
  read.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class for every serve-frontend error."""


class Overloaded(ServeError):
    """Admission queue full: the request was shed at the door.

    Carries the replica id and the admission limit observed at
    rejection so callers (and the bench's shed-rate accounting) can
    report where the pressure is. With the overload plane on
    (`ServeConfig.overload`), `depth` is the ADAPTIVE limit of the
    moment (<= the static queue depth), `priority` names the shed
    op's class, and `evicted=True` marks an op that WAS admitted but
    was evicted from the queue by a higher-priority arrival — in
    every case the op never reached the log, so retrying is always
    safe.
    """

    def __init__(self, rid: int, depth: int,
                 priority: int | None = None, evicted: bool = False):
        how = "evicted by a higher-priority arrival" if evicted \
            else "request shed"
        prio = "" if priority is None else f" (priority {priority})"
        super().__init__(
            f"replica {rid} admission queue full ({depth} "
            f"admitted){prio}; {how}"
        )
        self.rid = rid
        self.depth = depth
        self.priority = priority
        self.evicted = evicted


class DeadlineExceeded(ServeError):
    """The request's deadline passed while it waited in the queue.

    The op was dropped during batch assembly and never appended to the
    log, so it has no effect — a late caller observes a clean timeout,
    not a maybe-executed write.
    """

    def __init__(self, rid: int, late_by_s: float):
        super().__init__(
            f"request deadline exceeded on replica {rid} "
            f"({late_by_s * 1e3:.2f}ms late); op dropped before append"
        )
        self.rid = rid
        self.late_by_s = late_by_s


class FrontendClosed(ServeError):
    """The frontend is closed (or closed non-draining with this request
    still queued); no further requests are accepted."""

    def __init__(self, detail: str = "frontend closed"):
        super().__init__(detail)


class ReplicaFailed(ServeError):
    """The serving replica died under this request (`fault/`).

    `maybe_executed=False` (the in-flight-batch and queued-request
    failover paths, which fire BEFORE the batch touches the log)
    guarantees the op had no effect — resubmitting is exactly-once
    safe, and `call_with_retry` does so automatically, re-routed to a
    healthy replica. `maybe_executed=True` means the failure struck
    after the append: the op will replay (the log survives the
    replica), only its response was lost — automatic retry is refused
    because it could duplicate the op.
    """

    def __init__(self, rid: int, cause: BaseException | None = None,
                 maybe_executed: bool = False):
        detail = f" ({type(cause).__name__}: {cause})" if cause else ""
        effect = (
            "op may have reached the log; response lost"
            if maybe_executed else "op never reached the log"
        )
        super().__init__(
            f"replica {rid} failed{detail}; {effect}"
        )
        self.rid = rid
        self.cause = cause
        self.maybe_executed = maybe_executed

    @property
    def retryable(self) -> bool:
        return not self.maybe_executed


class StaleRead(ServeError):
    """A bounded-staleness read could not be served within its bound.

    The serving replica's applied position (`applied_pos`) still
    trails the requested minimum (`min_pos`) after the caller's
    allowed wait — the follower is lagging the feed further than the
    client tolerates (`repl/follower.py` translates `max_lag_pos`
    into this absolute bound). The read dispatched nothing; the
    client can retry, loosen the bound, or route to the primary.
    """

    def __init__(self, rid: int, applied_pos: int, min_pos: int):
        super().__init__(
            f"replica {rid} applied position {applied_pos} trails the "
            f"requested staleness bound {min_pos}"
        )
        self.rid = rid
        self.applied_pos = applied_pos
        self.min_pos = min_pos


class NotPrimary(ServeError):
    """A write reached a read-only (follower-mode) frontend.

    Followers serve bounded-staleness reads only; every write belongs
    on the primary. A promotion (`ServeFrontend.enable_writes`, driven
    by `repl/promote.py`) flips the frontend into write serving —
    until then the op was never admitted and retrying AGAINST THE
    PRIMARY is always safe.
    """

    def __init__(self, rid: int):
        super().__init__(
            f"replica {rid} is serving read-only (follower mode); "
            f"route writes to the primary or promote this follower"
        )
        self.rid = rid


class CircuitOpen(ServeError):
    """The client-side circuit breaker is open: the call was refused
    BEFORE submission (`serve/client.py:CircuitBreaker`).

    Enough consecutive transient failures (`Overloaded`, retryable
    `ReplicaFailed`) tripped the breaker; until the cool-down elapses
    every call fails fast here instead of adding load to a frontend
    that is already shedding. The op was never submitted — zero log
    effect by construction — so retrying after `retry_after_s` is
    always safe (`call_with_retry` does so, with backoff, and the
    breaker lets a single half-open probe through first).
    """

    def __init__(self, retry_after_s: float, failures: int):
        super().__init__(
            f"circuit open after {failures} consecutive transient "
            f"failures; retry in {retry_after_s * 1e3:.0f}ms"
        )
        self.retry_after_s = retry_after_s
        self.failures = failures


class WrongShard(ServeError):
    """The op's key does not belong to the shard it reached — or the
    caller's `ShardMap` version disagrees with the shard's.

    The fleet-level congruence contract (`shard/ring.py:ShardMap`,
    lifted from `models/partitioned.py`): shard `s` of `N` owns every
    key `k` with `k % N == s`, and routers and shards must agree on
    the SAME map version before any ack. A key mismatch means a
    caller bypassed the router; a version mismatch means a stale map
    on one side (a re-published map after a promotion the other side
    has not loaded yet). Either way the op was rejected BEFORE any
    log effect — refresh the map (`durable_publish`'d, so a reload
    always observes a complete file) and re-route; `call_with_retry`
    does both when the frontend exposes `refresh_map()`.
    """

    def __init__(self, key: int, shard: int, expected_shard: int,
                 map_version: int, peer_version: int | None = None):
        if peer_version is not None and peer_version != map_version:
            why = (f"map version {peer_version} does not match the "
                   f"shard's version {map_version}")
        else:
            why = (f"key {key} routes to shard {expected_shard} "
                   f"under map v{map_version}")
        super().__init__(
            f"shard {shard}: {why}; op rejected before any log effect"
        )
        self.key = key
        self.shard = shard
        self.expected_shard = expected_shard
        self.map_version = map_version
        self.peer_version = peer_version


class ShardUnavailable(ServeError):
    """The op's shard cannot serve it right now (`shard/router.py`).

    Raised when a shard's backend is down — its primary process died,
    the connection dropped mid-exchange, or a promotion is re-homing
    its writes. `maybe_executed` has `ReplicaFailed` semantics: False
    means the sub-batch provably never reached the shard's log, so a
    resubmit is exactly-once safe (`call_with_retry` retries it with
    backoff, re-routed once the router repoints the shard); True means
    the connection died AFTER the ops were sent — they may commit and
    replay, so only the caller can decide (a read disambiguates).

    Cross-shard batches are NOT atomic (the CNR contract): when a
    multi-shard batch raises this, sub-batches on OTHER shards may
    have committed and acked independently.
    """

    def __init__(self, shard: int, cause: BaseException | None = None,
                 maybe_executed: bool = False):
        detail = f" ({type(cause).__name__}: {cause})" if cause else ""
        effect = (
            "sub-batch may have reached the shard's log; response lost"
            if maybe_executed
            else "sub-batch never reached the shard's log"
        )
        super().__init__(f"shard {shard} unavailable{detail}; {effect}")
        self.shard = shard
        self.cause = cause
        self.maybe_executed = maybe_executed

    @property
    def retryable(self) -> bool:
        return not self.maybe_executed


class TxnConflict(ServeError):
    """The op's key is locked by a prepared-but-undecided cross-shard
    transaction (`shard/txn.py:TxnParticipant`).

    A prepared intent blocks CONFLICTING KEYS, not the shard: every
    other key serves normally, and this op was rejected before any
    log effect. Retrying with backoff is always safe — the lock
    clears the moment the transaction's decision arrives (or, for a
    dead coordinator generation, when presumed abort releases it);
    `call_with_retry` classifies this exactly like `Overloaded`.
    """

    def __init__(self, key: int, txn: str):
        super().__init__(
            f"key {key} is locked by prepared transaction {txn}; "
            f"op rejected before any log effect"
        )
        self.key = key
        self.txn = txn
        self.maybe_executed = False  # rejected at the door, always

    @property
    def retryable(self) -> bool:
        return True


class TxnAborted(ServeError):
    """The cross-shard transaction aborted during prepare
    (`shard/txn.py:TxnCoordinator`).

    Presumed-abort 2PC's clean failure: some participant voted no
    (conflict, wrong shard, unavailable) before any decision was
    published, every prepared intent was (or will be, by presumed
    abort) dropped, and NO participant applied anything — the whole
    transaction had zero log effect, so resubmitting the whole
    transaction is exactly-once safe. The caller retries; the per-op
    retry machinery never sees this error.
    """

    def __init__(self, txn: str, cause: BaseException | None = None):
        detail = f" ({type(cause).__name__}: {cause})" if cause else ""
        super().__init__(
            f"transaction {txn} aborted during prepare{detail}; "
            f"zero log effect on every participant"
        )
        self.txn = txn
        self.cause = cause


class TxnInDoubt(ServeError):
    """The transaction's durable decision exists but this caller
    could not confirm phase 2 completed on every participant.

    The `maybe_executed=True` of the transaction layer: the decision
    record (`durable/txnlog.py:DecisionLog`) is the truth and
    recovery WILL enforce it — participants re-resolve by decision
    lookup, the restarted coordinator re-drives commits — but right
    now some sub-batch may or may not have applied. Never
    auto-retried (a blind resubmit could double-apply); the caller
    resolves via `TxnCoordinator.recover()`, participant
    `resolve_in_doubt()`, or a read of the affected keys.
    """

    def __init__(self, txn: str, decision: str | None = None,
                 cause: BaseException | None = None):
        detail = f" ({type(cause).__name__}: {cause})" if cause else ""
        dec = f"decision={decision!r}" if decision else "undecided"
        super().__init__(
            f"transaction {txn} in doubt ({dec}){detail}; recovery "
            f"will enforce the durable decision — do not blindly retry"
        )
        self.txn = txn
        self.decision = decision
        self.cause = cause
