"""Client-side retry-with-backoff over the serve frontend.

`Overloaded` is the frontend's TRANSIENT backpressure signal: the op
was shed at admission and never touched the log, so resubmitting is
always safe (exactly-once is preserved — a shed op has no effect to
duplicate). This module layers the standard client response on top:
capped exponential backoff with full jitter, giving the combiner time
to drain between attempts instead of hammering the admission lock.

`ReplicaFailed` (failover mode, `fault/`) is retried ONLY when the
frontend proved the op never reached the log
(`maybe_executed=False`) — and the retry transparently RE-ROUTES to a
healthy replica (`frontend.healthy_rids()`), so a client survives its
replica dying mid-conversation without seeing anything but latency. A
`maybe_executed=True` failure propagates: the op will replay from the
log and resubmitting could duplicate it.

`DeadlineExceeded` and `FrontendClosed` are NOT retried here —
deadline'd work is stale by definition and a closed frontend is
permanent; both propagate to the caller.

Two budgets bound a call, both enforced here:

- `max_attempts` bounds total submissions (first try included);
- `total_deadline_s` bounds total elapsed time ACROSS attempts — a
  retry whose backoff would outlive the remaining budget re-raises
  the transient error instead of sleeping into a guaranteed timeout
  (so no backoff ever runs past the budget), each attempt's per-call
  `timeout` is clamped to the remainder, and a budget found already
  spent re-raises the LAST transient error rather than submitting an
  op doomed to time out. Without it, per-attempt timeouts compose
  into an unbounded worst case (`max_attempts × (timeout +
  backoff)`), which is no deadline at all from the caller's point of
  view.
"""

from __future__ import annotations

import dataclasses
import random

from node_replication_tpu.serve.errors import Overloaded, ReplicaFailed
from node_replication_tpu.utils.clock import get_clock


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with full jitter + a total budget.

    Attempt i (0-based) sleeps `uniform(0, min(base * 2**i, cap))` —
    the AWS "full jitter" schedule, which decorrelates a thundering
    herd of shed clients better than fixed backoff. `max_attempts`
    bounds total submissions (first try included); attempt
    `max_attempts` re-raises the final `Overloaded`. `total_deadline_s`
    (None = unbounded, the pre-budget behavior) is the wall budget for
    the WHOLE call — attempts, backoffs, and result waits together.
    """

    max_attempts: int = 8
    base_backoff_s: float = 0.001
    max_backoff_s: float = 0.100
    total_deadline_s: float | None = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.total_deadline_s is not None and self.total_deadline_s <= 0:
            raise ValueError("total_deadline_s must be > 0 (or None)")

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        cap = min(self.base_backoff_s * (2 ** attempt),
                  self.max_backoff_s)
        return rng.uniform(0.0, cap)


def call_with_retry(
    frontend,
    op: tuple,
    rid: int = 0,
    policy: RetryPolicy | None = None,
    deadline_s: float | None = None,
    timeout: float | None = None,
    rng: random.Random | None = None,
    on_shed=None,
):
    """Closed-loop `frontend.call` that retries `Overloaded` (with
    backoff) and retryable `ReplicaFailed` (with backoff AND a
    re-route to a healthy replica), inside the policy's attempt and
    total-deadline budgets. `on_shed(attempt, delay_s)` (optional)
    observes each `Overloaded` rejection — the bench uses it to count
    retries without threading state through. Returns the op's
    response; re-raises the last transient error when either budget is
    exhausted."""
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    clock = get_clock()
    t_end = (
        None if policy.total_deadline_s is None
        else clock.now() + policy.total_deadline_s
    )
    last_transient: Exception | None = None
    for attempt in range(policy.max_attempts):
        eff_timeout = timeout
        if t_end is not None:
            rem = t_end - clock.now()
            if rem <= 0 and last_transient is not None:
                # the budget was spent while backing off (scheduler
                # jitter can oversleep): submitting now would only
                # reach a guaranteed TimeoutError — and the op might
                # still execute, which a resubmitting caller could
                # duplicate. Surface the known transient state.
                raise last_transient
            # per-attempt result wait never outlives the total budget
            eff_timeout = rem if timeout is None else min(timeout, rem)
        try:
            return frontend.call(op, rid=rid, deadline_s=deadline_s,
                                 timeout=eff_timeout)
        except (Overloaded, ReplicaFailed) as e:
            if isinstance(e, ReplicaFailed) and e.maybe_executed:
                # the op may already be in the log (it WILL replay;
                # only its response was lost) — resubmitting could
                # duplicate it, so exactly-once forbids auto-retry
                raise
            last_transient = e
            exhausted = attempt + 1 >= policy.max_attempts
            delay = (
                0.0 if exhausted else policy.backoff_s(attempt, rng)
            )
            if t_end is not None and not exhausted:
                budget = t_end - clock.now()
                if budget <= delay:
                    # the total deadline budget is spent (or the drawn
                    # backoff would outlive it): retrying could not
                    # complete in time, so the budget exhausts the
                    # policy exactly like the attempt cap does —
                    # re-raise now instead of sleeping into a
                    # guaranteed timeout
                    exhausted = True
                    delay = 0.0
            if isinstance(e, Overloaded) and on_shed is not None:
                # the final, exhausted rejection is observed too —
                # shed accounting must see every attempt
                on_shed(attempt, delay)
            if exhausted:
                raise
            if isinstance(e, ReplicaFailed):
                # transparent failover: re-route the resubmission to a
                # healthy replica when the frontend can name one
                healthy = getattr(frontend, "healthy_rids", None)
                if healthy is not None:
                    alt = [r for r in healthy() if r != e.rid]
                    if alt:
                        rid = alt[attempt % len(alt)]
            if delay > 0:
                clock.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
