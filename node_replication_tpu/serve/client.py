"""Client-side retry-with-backoff + circuit breaking over the serve
frontend.

`Overloaded` is the frontend's TRANSIENT backpressure signal: the op
was shed at admission (or evicted from the queue by a higher-priority
arrival) and never touched the log, so resubmitting is always safe
(exactly-once is preserved — a shed op has no effect to duplicate).
This module layers the standard client responses on top:

- capped exponential backoff with full jitter (`RetryPolicy`,
  `call_with_retry`), giving the combiner time to drain between
  attempts instead of hammering the admission lock;
- a **circuit breaker** (`CircuitBreaker`): after enough CONSECUTIVE
  transient failures the breaker opens and every call fails fast with
  typed `CircuitOpen` — no submission, no admission-lock contention,
  no log effect — until the cool-down elapses; then exactly one
  half-open PROBE is allowed through, whose outcome closes the
  circuit (success) or re-opens it for another cool-down (failure).
  This is the client half of graceful degradation: a fleet of
  breaker-wrapped clients converts a retry storm into a trickle of
  probes, which is what lets the server-side AIMD controller
  (`serve/overload.py`) actually recover.

`ReplicaFailed` (failover mode, `fault/`) is retried ONLY when the
frontend proved the op never reached the log
(`maybe_executed=False`) — and the retry transparently RE-ROUTES to a
healthy replica (`frontend.healthy_rids()`), so a client survives its
replica dying mid-conversation without seeing anything but latency. A
`maybe_executed=True` failure propagates: the op will replay from the
log and resubmitting could duplicate it.

`DeadlineExceeded` and `FrontendClosed` are NOT retried here —
deadline'd work is stale by definition and a closed frontend is
permanent; both propagate to the caller.

The shard plane (`shard/router.py`) rides the same loop when the
"frontend" is a `ShardRouter`: `ShardUnavailable` with
`maybe_executed=False` retries with backoff (the sub-batch provably
never reached the shard's log), `maybe_executed=True` propagates
(same exactly-once reasoning as `ReplicaFailed`), and `WrongShard`
triggers the router's `refresh_map()` before the retry so a
promotion's re-published map re-homes the resubmission mid-loop.

Every retry is observable by CAUSE: the
`serve.retry.{overloaded,replica_failed,circuit_open,
shard_unavailable,wrong_shard}` counters and the `serve-retry` trace
event (cause + attempt + delay) keep overload retries
distinguishable from failover retries in `obs/report`.

Two budgets bound a call, both enforced here:

- `max_attempts` bounds total submissions (first try included);
- `total_deadline_s` bounds total elapsed time ACROSS attempts — a
  retry whose backoff would outlive the remaining budget re-raises
  the transient error instead of sleeping into a guaranteed timeout
  (so no backoff ever runs past the budget), each attempt's per-call
  `timeout` is clamped to the remainder, and a budget found already
  spent re-raises the LAST transient error rather than submitting an
  op doomed to time out. Without it, per-attempt timeouts compose
  into an unbounded worst case (`max_attempts × (timeout +
  backoff)`), which is no deadline at all from the caller's point of
  view.
"""

from __future__ import annotations

import dataclasses
import random
import threading

from node_replication_tpu.analysis.locks import make_lock

from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.serve.errors import (
    CircuitOpen,
    Overloaded,
    ReplicaFailed,
    ShardUnavailable,
    TxnConflict,
    WrongShard,
)
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with full jitter + a total budget.

    Attempt i (0-based) sleeps `uniform(0, min(base * 2**i, cap))` —
    the AWS "full jitter" schedule, which decorrelates a thundering
    herd of shed clients better than fixed backoff. `max_attempts`
    bounds total submissions (first try included); attempt
    `max_attempts` re-raises the final `Overloaded`. `total_deadline_s`
    (None = unbounded, the pre-budget behavior) is the wall budget for
    the WHOLE call — attempts, backoffs, and result waits together.
    """

    max_attempts: int = 8
    base_backoff_s: float = 0.001
    max_backoff_s: float = 0.100
    total_deadline_s: float | None = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.total_deadline_s is not None and self.total_deadline_s <= 0:
            raise ValueError("total_deadline_s must be > 0 (or None)")

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        cap = min(self.base_backoff_s * (2 ** attempt),
                  self.max_backoff_s)
        return rng.uniform(0.0, cap)


#: breaker states (`CircuitBreaker.state`)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Client-side circuit breaker with half-open probing.

    Share one instance across a client's calls (it is thread-safe;
    one breaker per frontend per client process is the intended
    grain). Wire it through `call_with_retry(breaker=...)`, or drive
    it manually from an open-loop submitter:

        breaker.before_call()        # raises CircuitOpen while open
        try:
            resp = frontend.call(op)
        except (Overloaded, ...):
            breaker.record_failure()
            raise
        breaker.record_success()

    Semantics: `failure_threshold` CONSECUTIVE transient failures flip
    CLOSED -> OPEN; while open, `before_call` fails fast with typed
    `CircuitOpen` (the op is never submitted — zero log effect by
    construction). After `cooldown_s` the next `before_call` admits
    exactly ONE probe (OPEN -> HALF_OPEN); its `record_success` closes
    the circuit, its `record_failure` re-opens it for another full
    cool-down. Counted in `serve.circuit.{opened,probes}` and emitted
    as `serve-circuit` transitions.
    """

    def __init__(self, failure_threshold: int = 8,
                 cooldown_s: float = 0.25):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if cooldown_s <= 0:
            raise ValueError("cooldown_s must be > 0")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        # nrcheck: lock-order CircuitBreaker._lock -> Counter._lock — trip/recover counters bump under the breaker lock
        self._lock = make_lock("CircuitBreaker._lock")
        self._state = CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probing = False
        self._probe_deadline = 0.0  # lease: a lost probe expires
        reg = get_registry()
        self._m_opened = reg.counter("serve.circuit.opened")
        self._m_probes = reg.counter("serve.circuit.probes")
        self._m_fastfail = reg.counter("serve.circuit.fast_failed")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def before_call(self) -> None:
        """Gate one call attempt. Raises `CircuitOpen` while the
        circuit is open (or while another probe is already in flight
        during half-open)."""
        now = get_clock().now()
        with self._lock:
            if self._state == CLOSED:
                return
            if self._state == OPEN:
                if now < self._open_until:
                    self._m_fastfail.inc()
                    raise CircuitOpen(self._open_until - now,
                                      self._failures)
                self._state = HALF_OPEN
                self._probing = False
                get_tracer().emit("serve-circuit", state=HALF_OPEN)
            # HALF_OPEN: one probe at a time; concurrent callers fail
            # fast until the probe resolves the circuit either way.
            # The probe holds a LEASE (one cool-down long): a probe
            # whose caller never reported back — crashed mid-call, or
            # failed with something outside the breaker's accounting —
            # must not wedge the circuit half-open forever, so an
            # expired lease lets the next caller take the probe over.
            if self._probing and now < self._probe_deadline:
                self._m_fastfail.inc()
                raise CircuitOpen(self._probe_deadline - now,
                                  self._failures)
            self._probing = True
            self._probe_deadline = now + self.cooldown_s
            self._m_probes.inc()

    def record_success(self) -> None:
        with self._lock:
            was = self._state
            self._state = CLOSED
            self._failures = 0
            self._probing = False
        if was != CLOSED:
            get_tracer().emit("serve-circuit", state=CLOSED)

    def record_failure(self) -> None:
        """One transient failure (shed / retryable replica failure).
        Consecutive failures open the circuit; a half-open probe's
        failure re-opens it immediately."""
        now = get_clock().now()
        opened = False
        with self._lock:
            self._failures += 1
            failures = self._failures
            self._probing = False
            if (self._state == HALF_OPEN
                    or (self._state == CLOSED
                        and self._failures >= self.failure_threshold)):
                self._state = OPEN
                self._open_until = now + self.cooldown_s
                opened = True
        if opened:
            self._m_opened.inc()
            get_tracer().emit("serve-circuit", state=OPEN,
                              failures=failures,
                              cooldown_s=self.cooldown_s)

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "open_for_s": max(
                    0.0, self._open_until - get_clock().now()
                ) if self._state == OPEN else 0.0,
            }


_RETRY_CAUSES = {
    Overloaded: "overloaded",
    ReplicaFailed: "replica_failed",
    CircuitOpen: "circuit_open",
    # the shard plane (`shard/router.py`): both are rejections with
    # zero log effect (WrongShard by construction; ShardUnavailable
    # when maybe_executed=False), so the retry is exactly-once safe
    ShardUnavailable: "shard_unavailable",
    WrongShard: "wrong_shard",
    # the txn plane (`shard/txn.py`): a key locked by a prepared-but-
    # undecided transaction; zero log effect, and the lock clears the
    # moment the decision arrives — Overloaded-shaped backoff applies.
    # TxnAborted/TxnInDoubt are deliberately ABSENT: they are whole-
    # transaction outcomes the coordinator's caller routes on, never
    # per-op transients.
    TxnConflict: "txn_conflict",
}


def _note_retry(e: Exception, attempt: int, rid: int,
                delay: float) -> None:
    """Per-cause retry accounting: `serve.retry.<cause>` counter +
    `serve-retry` event, so overload retries stay distinguishable
    from failover retries in `obs/report`."""
    cause = _RETRY_CAUSES[type(e)]
    get_registry().counter(f"serve.retry.{cause}").inc()
    tracer = get_tracer()
    if tracer.enabled:
        tracer.emit("serve-retry", cause=cause, attempt=attempt,
                    rid=rid, delay_s=delay)


def call_with_retry(
    frontend,
    op: tuple,
    rid: int = 0,
    policy: RetryPolicy | None = None,
    deadline_s: float | None = None,
    timeout: float | None = None,
    rng: random.Random | None = None,
    on_shed=None,
    priority: int | None = None,
    breaker: CircuitBreaker | None = None,
):
    """Closed-loop `frontend.call` that retries `Overloaded` (with
    backoff), retryable `ReplicaFailed` (with backoff AND a re-route
    to a healthy replica), and — when a `breaker` is wired —
    `CircuitOpen` (with backoff riding out the cool-down), inside the
    policy's attempt and total-deadline budgets. `on_shed(attempt,
    delay_s)` (optional) observes each `Overloaded` rejection — the
    bench uses it to count retries without threading state through.
    `priority` forwards to `frontend.submit` when given (the overload
    plane's priority classes). Returns the op's response; re-raises
    the last transient error when either budget is exhausted."""
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    clock = get_clock()
    t_end = (
        None if policy.total_deadline_s is None
        else clock.now() + policy.total_deadline_s
    )
    kwargs = {} if priority is None else {"priority": priority}
    last_transient: Exception | None = None
    for attempt in range(policy.max_attempts):
        eff_timeout = timeout
        if t_end is not None:
            rem = t_end - clock.now()
            if rem <= 0 and last_transient is not None:
                # the budget was spent while backing off (scheduler
                # jitter can oversleep): submitting now would only
                # reach a guaranteed TimeoutError — and the op might
                # still execute, which a resubmitting caller could
                # duplicate. Surface the known transient state.
                raise last_transient
            # per-attempt result wait never outlives the total budget
            eff_timeout = rem if timeout is None else min(timeout, rem)
        try:
            if breaker is not None:
                breaker.before_call()
            try:
                resp = frontend.call(op, rid=rid, deadline_s=deadline_s,
                                     timeout=eff_timeout, **kwargs)
            except BaseException:
                # EVERY non-success outcome counts as a failure —
                # DeadlineExceeded and TimeoutError are overload
                # symptoms too, and a half-open probe must never end
                # without reporting back (a silent exit would strand
                # the circuit until the probe lease expires)
                if breaker is not None:
                    breaker.record_failure()
                raise
            if breaker is not None:
                breaker.record_success()
            return resp
        except (Overloaded, ReplicaFailed, CircuitOpen,
                ShardUnavailable, WrongShard, TxnConflict) as e:
            if isinstance(e, (ReplicaFailed, ShardUnavailable)) \
                    and e.maybe_executed:
                # the op may already be in the log (it WILL replay;
                # only its response was lost) — resubmitting could
                # duplicate it, so exactly-once forbids auto-retry
                raise
            last_transient = e
            exhausted = attempt + 1 >= policy.max_attempts
            delay = (
                0.0 if exhausted else policy.backoff_s(attempt, rng)
            )
            if isinstance(e, CircuitOpen) and not exhausted:
                # backing off less than the remaining cool-down would
                # only buy another fast-fail; wait it out (jittered
                # past the boundary so probes do not synchronize)
                delay = max(delay, e.retry_after_s)
            if t_end is not None and not exhausted:
                budget = t_end - clock.now()
                if budget <= delay:
                    # the total deadline budget is spent (or the drawn
                    # backoff would outlive it): retrying could not
                    # complete in time, so the budget exhausts the
                    # policy exactly like the attempt cap does —
                    # re-raise now instead of sleeping into a
                    # guaranteed timeout
                    exhausted = True
                    delay = 0.0
            if isinstance(e, Overloaded) and on_shed is not None:
                # the final, exhausted rejection is observed too —
                # shed accounting must see every attempt
                on_shed(attempt, delay)
            if exhausted:
                raise
            _note_retry(e, attempt, rid, delay)
            if isinstance(e, ReplicaFailed):
                # transparent failover: re-route the resubmission to a
                # healthy replica when the frontend can name one
                healthy = getattr(frontend, "healthy_rids", None)
                if healthy is not None:
                    alt = [r for r in healthy() if r != e.rid]
                    if alt:
                        rid = alt[attempt % len(alt)]
            if isinstance(e, (WrongShard, ShardUnavailable)):
                # shard-plane re-route: a promotion re-published the
                # ShardMap with a bumped version; adopting it re-homes
                # the resubmission (keys are PINNED to shards by the
                # congruence map, so re-routing means a new map, never
                # a different shard)
                refresh = getattr(frontend, "refresh_map", None)
                if refresh is not None:
                    refresh()
            if delay > 0:
                clock.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
