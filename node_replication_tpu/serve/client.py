"""Client-side retry-with-backoff over the serve frontend.

`Overloaded` is the frontend's TRANSIENT backpressure signal: the op
was shed at admission and never touched the log, so resubmitting is
always safe (exactly-once is preserved — a shed op has no effect to
duplicate). This module layers the standard client response on top:
capped exponential backoff with full jitter, giving the combiner time
to drain between attempts instead of hammering the admission lock.

`DeadlineExceeded` and `FrontendClosed` are NOT retried here —
deadline'd work is stale by definition and a closed frontend is
permanent; both propagate to the caller.
"""

from __future__ import annotations

import dataclasses
import random
import time

from node_replication_tpu.serve.errors import Overloaded


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with full jitter.

    Attempt i (0-based) sleeps `uniform(0, min(base * 2**i, cap))` —
    the AWS "full jitter" schedule, which decorrelates a thundering
    herd of shed clients better than fixed backoff. `max_attempts`
    bounds total submissions (first try included); attempt
    `max_attempts` re-raises the final `Overloaded`.
    """

    max_attempts: int = 8
    base_backoff_s: float = 0.001
    max_backoff_s: float = 0.100

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        cap = min(self.base_backoff_s * (2 ** attempt),
                  self.max_backoff_s)
        return rng.uniform(0.0, cap)


def call_with_retry(
    frontend,
    op: tuple,
    rid: int = 0,
    policy: RetryPolicy | None = None,
    deadline_s: float | None = None,
    timeout: float | None = None,
    rng: random.Random | None = None,
    on_shed=None,
):
    """Closed-loop `frontend.call` that retries `Overloaded` with
    backoff. `on_shed(attempt, delay_s)` (optional) observes each
    rejection — the bench uses it to count retries without threading
    state through. Returns the op's response; re-raises the last
    `Overloaded` when the policy is exhausted."""
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    for attempt in range(policy.max_attempts):
        try:
            return frontend.call(op, rid=rid, deadline_s=deadline_s,
                                 timeout=timeout)
        except Overloaded:
            exhausted = attempt + 1 >= policy.max_attempts
            delay = (
                0.0 if exhausted else policy.backoff_s(attempt, rng)
            )
            if on_shed is not None:
                # the final, exhausted rejection is observed too —
                # shed accounting must see every attempt
                on_shed(attempt, delay)
            if exhausted:
                raise
            if delay > 0:
                time.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
