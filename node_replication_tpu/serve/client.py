"""Client-side retry-with-backoff over the serve frontend.

`Overloaded` is the frontend's TRANSIENT backpressure signal: the op
was shed at admission and never touched the log, so resubmitting is
always safe (exactly-once is preserved — a shed op has no effect to
duplicate). This module layers the standard client response on top:
capped exponential backoff with full jitter, giving the combiner time
to drain between attempts instead of hammering the admission lock.

`ReplicaFailed` (failover mode, `fault/`) is retried ONLY when the
frontend proved the op never reached the log
(`maybe_executed=False`) — and the retry transparently RE-ROUTES to a
healthy replica (`frontend.healthy_rids()`), so a client survives its
replica dying mid-conversation without seeing anything but latency. A
`maybe_executed=True` failure propagates: the op will replay from the
log and resubmitting could duplicate it.

`DeadlineExceeded` and `FrontendClosed` are NOT retried here —
deadline'd work is stale by definition and a closed frontend is
permanent; both propagate to the caller.
"""

from __future__ import annotations

import dataclasses
import random
import time

from node_replication_tpu.serve.errors import Overloaded, ReplicaFailed


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with full jitter.

    Attempt i (0-based) sleeps `uniform(0, min(base * 2**i, cap))` —
    the AWS "full jitter" schedule, which decorrelates a thundering
    herd of shed clients better than fixed backoff. `max_attempts`
    bounds total submissions (first try included); attempt
    `max_attempts` re-raises the final `Overloaded`.
    """

    max_attempts: int = 8
    base_backoff_s: float = 0.001
    max_backoff_s: float = 0.100

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        cap = min(self.base_backoff_s * (2 ** attempt),
                  self.max_backoff_s)
        return rng.uniform(0.0, cap)


def call_with_retry(
    frontend,
    op: tuple,
    rid: int = 0,
    policy: RetryPolicy | None = None,
    deadline_s: float | None = None,
    timeout: float | None = None,
    rng: random.Random | None = None,
    on_shed=None,
):
    """Closed-loop `frontend.call` that retries `Overloaded` (with
    backoff) and retryable `ReplicaFailed` (with backoff AND a
    re-route to a healthy replica). `on_shed(attempt, delay_s)`
    (optional) observes each `Overloaded` rejection — the bench uses
    it to count retries without threading state through. Returns the
    op's response; re-raises the last transient error when the policy
    is exhausted."""
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    for attempt in range(policy.max_attempts):
        try:
            return frontend.call(op, rid=rid, deadline_s=deadline_s,
                                 timeout=timeout)
        except (Overloaded, ReplicaFailed) as e:
            if isinstance(e, ReplicaFailed) and e.maybe_executed:
                # the op may already be in the log (it WILL replay;
                # only its response was lost) — resubmitting could
                # duplicate it, so exactly-once forbids auto-retry
                raise
            exhausted = attempt + 1 >= policy.max_attempts
            delay = (
                0.0 if exhausted else policy.backoff_s(attempt, rng)
            )
            if isinstance(e, Overloaded) and on_shed is not None:
                # the final, exhausted rejection is observed too —
                # shed accounting must see every attempt
                on_shed(attempt, delay)
            if exhausted:
                raise
            if isinstance(e, ReplicaFailed):
                # transparent failover: re-route the resubmission to a
                # healthy replica when the frontend can name one
                healthy = getattr(frontend, "healthy_rids", None)
                if healthy is not None:
                    alt = [r for r in healthy() if r != e.rid]
                    if alt:
                        rid = alt[attempt % len(alt)]
            if delay > 0:
                time.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
