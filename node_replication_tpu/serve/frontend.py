"""Concurrent batching frontend over a replicated-log wrapper.

Turns `NodeReplicated` / `MultiLogReplicated` into a servable system:
many OS-thread clients submit ops; each replica has a BOUNDED
submission queue and one dedicated worker — the statically-elected
combiner for that replica (the reference elects a combiner per
contention window with a CAS, `nr/src/replica.rs:508-540`; here
election is the queue→worker ownership, decided once) — that drains
the queue into an adaptive batch and executes it as a single
flat-combining round via `execute_mut_batch` (one append + one replay
pass under the wrapper's reentrant combiner lock, `core/replica.py`).

Production edges, each with a typed signal (`serve/errors.py`):

- **admission control** — the per-replica queue is bounded
  (`ServeConfig.queue_depth`); a full queue sheds the request with
  `Overloaded` BEFORE it costs anything. Memory held per replica is
  therefore `O(queue_depth + batch_max_ops)`, never load-proportional.
- **deadlines** — a request may carry an absolute deadline; batch
  assembly drops expired requests with `DeadlineExceeded` *before*
  appending, so a timed-out op is guaranteed to have had no effect.
- **backpressure** — clients see `Overloaded` the moment service lags
  admission; `serve/client.py` layers retry-with-backoff (and a
  circuit breaker) on top for closed-loop callers.
- **overload plane** (`ServeConfig.overload`, `serve/overload.py`) —
  the static bound becomes an ADAPTIVE limit: an AIMD controller per
  replica keyed to measured queue delay, strict-priority shedding
  (`submit(priority=)`: BULK evicts first, CRITICAL last, with the
  inversion counter proving it), brownout reads (degrade to the
  bounded-staleness `execute_stale` path instead of shedding), and
  downstream-lag watermarks (WAL fsync lag, `repl/` ship/apply lag)
  that throttle admission before any backlog grows unbounded.
- **graceful drain** — `close()` stops admission, flushes every queued
  op through the combiner, resolves all futures, and joins the
  workers; `close(drain=False)` rejects the backlog instead.
- **failover** (`ServeConfig.failover=True`, the `fault/` lifecycle
  integration) — a worker whose batch round throws retires its replica
  instead of limping: in-flight requests are completed exceptionally
  with typed `ReplicaFailed` (retryable when the batch provably never
  reached the log, so `call_with_retry` transparently re-routes),
  queued requests are re-homed onto a healthy replica's queue, and the
  `on_replica_failed` callback hands the corpse to the lifecycle
  manager (`fault/repair.py`) for quarantine + repair-by-replay;
  `restart_replica` readmits the repaired replica with a fresh queue
  and worker. Off (default), a failed batch rejects its own futures
  and the worker keeps serving — the pre-fault behavior.
- **durable acks** (`ServeConfig(durability="batch"|"always")`, the
  `durable/` integration) — a batch's futures resolve only after its
  WAL records are fsynced (one fsync per batch in `"batch"` mode —
  group commit riding the existing batching; per-append in
  `"always"`), so a response a client has seen survives kill -9.
  `ServeFrontend.from_recovery(dir, dispatch, ...)` reopens
  mid-traffic state after a crash: newest valid snapshot + WAL-tail
  replay, bit-identical, WAL re-attached, serving resumed.

- **mesh fleets** (`NodeReplicated(mesh=...)`, the `parallel/`
  integration) — a fleet whose replica axis is sharded across the TPU
  mesh serves through the same queues and workers: each combiner
  worker's replica shard lives on one device (a fleet larger than any
  single chip's HBM), the worker→device map is recorded at
  construction (`stats()["mesh"]`, `device_of_rid`), and batch rounds
  run the wrapper's cross-device collective tiers transparently.

Reads bypass the write queue entirely: `read()` dispatches against the
caller's replica through the wrapper's read-sync path (`execute`),
which waits only for this replica to pass the completed tail — read
latency stays off the write batch, per the reference's read-only path
(`nr/src/replica.rs:404-410`).

Wire protocol with the wrapper is just the two batch entry points
(`execute_mut_batch`, `execute`), so the frontend serves NR and CNR
alike and survives `grow_fleet` — `grow()` adds replicas AND spins up
their queues/workers while traffic is in flight.
"""

from __future__ import annotations

import dataclasses
import logging
import threading

from node_replication_tpu.analysis.locks import (
    make_condition,
    make_lock,
)
from collections import deque
from typing import Callable, Sequence

from node_replication_tpu.core.replica import ReplicaFencedError
from node_replication_tpu.fault.inject import FaultError, fault_hook
from node_replication_tpu.obs.metrics import COUNT_BUCKETS, get_registry
from node_replication_tpu.serve.errors import (
    DeadlineExceeded,
    FrontendClosed,
    NotPrimary,
    Overloaded,
    ReplicaFailed,
    StaleRead,
)
from node_replication_tpu.serve.future import ServeFuture
from node_replication_tpu.serve.overload import (
    CRITICAL,
    NORMAL,
    PRIORITIES,
    PRIORITY_NAMES,
    LagSource,
    OverloadConfig,
    OverloadGovernor,
)
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer

logger = logging.getLogger("node_replication_tpu")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Frontend tuning knobs (all per replica).

    - `queue_depth` — admission bound; the (queue_depth+1)-th pending
      request is shed with `Overloaded`.
    - `batch_max_ops` — size trigger: a batch executes as soon as this
      many ops are staged.
    - `batch_linger_s` — deadline trigger: once the first op of a batch
      arrives, the worker waits at most this long for the batch to
      fill (0 = drain whatever is queued immediately). The linger is
      adaptive: it is skipped entirely whenever the queue already holds
      a full batch, so a saturated queue never pays added latency.
    - `default_deadline_s` — relative deadline applied to every request
      that does not pass its own (None = no deadline).
    - `drain_timeout_s` — how long `close(drain=True)` waits for the
      workers to flush before giving up and rejecting the remainder.
    - `failover` — retire a replica whose batch round throws (typed
      `ReplicaFailed` to in-flight callers, queued requests re-homed,
      `on_replica_failed` lifecycle callback) instead of rejecting the
      batch and limping on. See the module docstring and `fault/`.
    - `overload` — the adaptive overload plane (`serve/overload.py`):
      an `OverloadConfig` turns on the per-replica AIMD admission
      controller (limit adapts to measured queue delay each combiner
      round), brownout reads (past the watermark, reads degrade to the
      bounded-staleness `execute_stale` path instead of shedding), and
      downstream-lag backpressure (the WAL's fsync lag auto-registers
      when a WAL is attached; `repl/` ship/apply lag via
      `install_backpressure`/`add_backpressure_source`). None
      (default) keeps the static `queue_depth` bound only. Priority
      classes on `submit()` and strict-priority shedding (BULK evicts
      first, CRITICAL last) are active either way — without a
      governor they order shedding at the static bound.
    - `wal_lag_low` / `wal_lag_high` — watermarks (log positions) for
      the auto-registered WAL fsync-lag backpressure source (only
      read when `overload` is set and a WAL is attached).
    - `pipeline_depth` — serve-pipeline overlap depth (default 0 =
      today's fully serial worker, the safety switch). At depth 1 the
      per-replica worker splits into an ASSEMBLY stage (drain queue,
      sweep deadlines, build the batch, `begin_mut_batch`) and a
      COMPLETION stage (`finish_mut_batch`, durable-ack barrier,
      resolve futures), with at most ONE round in flight per replica:
      round N+1's host work overlaps round N's device work. Capped at
      1 — a second in-flight round would interleave response delivery
      across rounds (breaking future ordering), make post-append
      failure attribution (`maybe_executed`) ambiguous, and split the
      WAL group-commit unit; depth 1 already hides the host work, so
      deeper pipelines buy latency risk for nothing.
    - `durability` — the durable-ack contract against the wrapper's
      attached write-ahead log (`durable/wal.py`). `"none"` (default):
      acks are in-memory only (the pre-durability semantics, WAL or
      not). `"batch"`: after each combiner round the worker fsyncs the
      WAL ONCE and only then resolves the batch's futures — a response
      a client has seen is on disk, amortizing one fsync over the
      whole batch. `"always"`: the WAL itself fsyncs inside every
      append (policy `always`), so durability precedes even response
      delivery inside the wrapper; the worker adds nothing. Both
      durable modes REQUIRE a WAL attached at frontend construction
      (`ValueError` otherwise — a silent non-durable "durable" mode
      would be a lie to every client).
    """

    queue_depth: int = 256
    batch_max_ops: int = 64
    batch_linger_s: float = 0.002
    default_deadline_s: float | None = None
    drain_timeout_s: float = 30.0
    failover: bool = False
    pipeline_depth: int = 0
    durability: str = "none"
    overload: OverloadConfig | None = None
    wal_lag_low: int = 1024
    wal_lag_high: int = 8192
    #: fleet observability (`obs/export.py`): a port (0 = ephemeral)
    #: starts a `MetricsExporter` serving this process's registry
    #: snapshot + trace tail + frontend stats on a side socket
    #: (`frontend.exporter.address`); None (default) starts NOTHING —
    #: zero added work on any path, not even a branch
    obs_port: int | None = None
    #: exporter identity label (defaults to $NR_TPU_NODE_ID or
    #: `<role>-<pid>`); only read when `obs_port` is set
    obs_node_id: str | None = None
    #: host-path sampling profiler (`obs/profile.py`): a rate in Hz
    #: starts a `SamplingProfiler` with the frontend (per-role folded
    #: stacks, duty-cycle gauge, host-budget input; attached to the
    #: exporter's `profile-fetch` when `obs_port` is also set); None
    #: (default) builds NOTHING — the object does not exist, zero
    #: hot-path branches
    profile_hz: float | None = None

    def __post_init__(self):
        if self.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if self.batch_max_ops < 1:
            raise ValueError("batch_max_ops must be >= 1")
        if self.batch_linger_s < 0:
            raise ValueError("batch_linger_s must be >= 0")
        if self.pipeline_depth not in (0, 1):
            raise ValueError(
                f"pipeline_depth must be 0 (serial) or 1 (one round "
                f"in flight); got {self.pipeline_depth} — deeper "
                f"pipelines would interleave response delivery across "
                f"rounds and break maybe_executed attribution"
            )
        if self.durability not in ("none", "batch", "always"):
            raise ValueError(
                f"unknown durability {self.durability!r} "
                f"(none | batch | always)"
            )
        if not 0 <= self.wal_lag_low < self.wal_lag_high:
            raise ValueError(
                "wal lag watermarks need 0 <= low < high"
            )
        if self.profile_hz is not None and not self.profile_hz > 0:
            raise ValueError(
                f"profile_hz must be > 0 (or None to not build a "
                f"profiler at all); got {self.profile_hz}"
            )
        if (self.overload is not None
                and self.overload.target_delay_s
                <= self.batch_linger_s):
            # the AIMD signal (oldest wait at batch assembly) includes
            # the deliberate linger at light load; a target at or
            # below it would read an idle frontend as congested and
            # pin admission at the floor
            raise ValueError(
                f"overload.target_delay_s "
                f"({self.overload.target_delay_s}) must exceed "
                f"batch_linger_s ({self.batch_linger_s}): the "
                f"queue-delay signal includes the linger"
            )


@dataclasses.dataclass
class _Request:
    op: tuple
    future: ServeFuture
    priority: int = NORMAL


class _ReplicaDown(Exception):
    """Internal worker-loop signal: this batch round killed the
    replica (failover mode); the loop retires it and exits.

    Carries the batch's unresolved requests so the LOOP can reject
    them AFTER `_fail_replica` has marked the replica failed and
    spawned the lifecycle callback — a client that wakes on its
    `ReplicaFailed` must observe the failover already in motion
    (`wait_idle` on the manager, `healthy_rids` on the frontend),
    never a pre-failover limbo."""

    def __init__(self, cause: BaseException, pending: list[_Request],
                 maybe_executed: bool):
        super().__init__(str(cause))
        self.cause = cause
        self.pending = pending
        self.maybe_executed = maybe_executed


class _OfferResult:
    """Outcome of one admission attempt (`_SubmissionQueue.offer`).

    `expired` and `evicted` carry requests the queue REMOVED under its
    lock; the frontend rejects their futures after releasing it (a
    future's done-callbacks run user code — never under the queue
    lock). `inversion` marks the invariant breach the priority plane
    exists to prevent: a CRITICAL shed while a lower-priority op sat
    queued (structurally impossible via eviction; measured anyway)."""

    __slots__ = ("admitted", "expired", "evicted", "inversion")

    def __init__(self, admitted, expired, evicted, inversion=False):
        self.admitted = admitted
        self.expired = expired
        self.evicted = evicted
        self.inversion = inversion


class _Staged:
    """One assembled-and-begun round in the assembly→completion
    handoff (`ServeConfig.pipeline_depth > 0`): the wrapper's pending
    round handle plus everything the completion stage needs to
    deliver it (live requests, sweep accounting, the assembly-time
    queue-delay already fed to the governor)."""

    __slots__ = ("pending", "live", "missed", "taken", "t0", "delay")

    def __init__(self, pending, live, missed, taken, t0, delay):
        self.pending = pending
        self.live = live
        self.missed = missed
        self.taken = taken
        self.t0 = t0
        self.delay = delay


class _PipelineChannel:
    """Capacity-1 handoff between one replica's assembly and
    completion stages, plus the one-round-in-flight barrier.

    A round is *busy* from `put` (assembly has begun it) until the
    completion stage's `device_done` — which fires right after
    `finish_mut_batch` returns, BEFORE the durable-ack barrier and
    future resolution. That early signal is where the pipeline's
    overlap lives: the assembly stage's `wait_clear` wakes while
    round N's completion host work (fsync, ship barrier, callbacks,
    accounting) is still running, drains the queue that filled during
    round N, and begins round N+1 — whose device work (append, or the
    whole fused kernel) then runs under round N's remaining host work
    and round N+1's own assembly. The wrapper-level invariant holds
    throughout: `begin(N+1)` happens only after `finish(N)` returned,
    so at most one split round is ever open per replica.

    On a completion-stage death (`round_done(exc)`), the channel is
    poisoned: `wait_clear` returns the killer, and a `put` racing the
    death is refused (returning the killer) so the assembly stage can
    tear its already-begun round down honestly instead of stranding
    it in a slot nobody will drain. All waits route through the
    injectable clock (`utils/clock.py`) so simulated runs stay
    deterministic."""

    __slots__ = ("_lock", "_slot", "_busy", "_closed", "_dead")

    def __init__(self):
        self._lock = make_condition("_PipelineChannel._lock")
        self._slot: _Staged | None = None
        self._busy = False
        self._closed = False
        self._dead: BaseException | None = None

    def wait_clear(self) -> BaseException | None:
        """Block until the in-flight round's device half is done (or
        the channel is poisoned); returns the completion stage's
        killing exception (None when clear and alive)."""
        clock = get_clock()
        with self._lock:
            while self._busy and self._dead is None:
                clock.wait(self._lock)
            return self._dead

    def put(self, staged: _Staged) -> BaseException | None:
        """Hand one begun round to the completion stage. Returns None
        on success, or the channel-poisoning exception when the
        completion stage died between the caller's `wait_clear` and
        now — the round is already begun (post-append), so the caller
        must tear it down, not retry it."""
        with self._lock:
            if self._dead is not None:
                return self._dead
            self._slot = staged
            self._busy = True
            self._lock.notify_all()
            return None

    def take(self) -> _Staged | None:
        """Completion stage: next round, or None once closed and
        drained (the stage's exit signal)."""
        clock = get_clock()
        with self._lock:
            while self._slot is None and not self._closed:
                clock.wait(self._lock)
            staged = self._slot
            self._slot = None
            return staged

    def device_done(self) -> None:
        """Completion stage: `finish_mut_batch` returned — the round's
        device work is complete and the wrapper slot is free, so the
        assembly stage may begin the next round while delivery
        continues."""
        with self._lock:
            self._busy = False
            self._lock.notify_all()

    def round_done(self, exc: BaseException | None = None) -> None:
        """Completion stage: the round died (with `exc`: poison the
        channel so the assembly stage stops) or ended without reaching
        `device_done`. Called AFTER `_fail_replica` on the failure
        path, so a woken assembly stage observes the failover already
        in motion."""
        with self._lock:
            self._busy = False
            if exc is not None:
                self._dead = exc
            self._lock.notify_all()

    def drain_slot(self) -> _Staged | None:
        """Pop a staged round nobody will serve (completion-death
        teardown): the assembly stage may have begun and handed off
        round N+1 while round N was mid-delivery."""
        with self._lock:
            staged = self._slot
            self._slot = None
            return staged

    def close(self) -> None:
        """Assembly stage exit: no more rounds will be put; the
        completion stage drains the in-flight one and exits."""
        with self._lock:
            self._closed = True
            self._lock.notify_all()


class _SubmissionQueue:
    """Bounded, priority-aware MPSC admission queue for one replica.

    Many client threads `offer`; one worker `take_batch`es. All state
    lives under one condition (`_lock`): depth check + enqueue is a
    single critical section, so admission control cannot over-admit
    under contention. Counters (accepted / shed / completed / missed)
    live here too so `stats()` needs no frontend-level lock.

    Priority discipline: one FIFO deque per class (CRITICAL / NORMAL /
    BULK). Batches drain strictly by class; at a full queue an
    arriving request EVICTS the newest queued request of a strictly
    lower class rather than shedding itself, so BULK always sheds
    first and a CRITICAL op sheds only into a queue of CRITICALs.
    Deadline-expired requests are swept OUT at admission time (they
    were dead weight holding admission slots — the pre-fix behavior
    kept them until batch assembly, so a queue full of corpses shed
    live traffic).
    """

    __slots__ = ("_lock", "_items", "_depth", "_closed", "_in_service",
                 "accepted", "shed", "completed", "deadline_missed",
                 "evicted", "shed_by_prio", "priority_inversions",
                 "_reg", "_m_wait", "_m_linger")

    def __init__(self, depth: int):
        self._lock = make_condition("_SubmissionQueue._lock")
        # queue-wait accounting (host-budget input): how long the
        # worker sat on the condition before the first op arrived, and
        # how long it lingered for the batch to fill. One `enabled`
        # branch per take_batch when metrics are off (obs/metrics.py
        # cost rule); handles are created once, not per call.
        self._reg = get_registry()
        self._m_wait = self._reg.histogram("serve.queue.wait_s")
        self._m_linger = self._reg.histogram("serve.queue.linger_s")
        self._items: tuple[deque[_Request], ...] = tuple(
            deque() for _ in PRIORITIES
        )
        self._depth = depth
        self._closed = False
        self._in_service = 0  # ops taken by the worker, not yet finished
        self.accepted = 0
        self.shed = 0
        self.completed = 0
        self.deadline_missed = 0
        self.evicted = 0
        self.shed_by_prio = [0 for _ in PRIORITIES]
        self.priority_inversions = 0

    def _depth_unlocked(self) -> int:
        return sum(len(d) for d in self._items)

    def _sweep_expired_unlocked(self, now: float) -> list[_Request]:
        """Remove deadline-expired queued requests (all classes) and
        return them for rejection — the eager sweep that keeps corpses
        from occupying admission slots until batch assembly.

        Cost discipline: each class is walked ONLY while its head is
        expired — per-class FIFO arrival makes the head the oldest
        deadline whenever requests share a `deadline_s` (the common
        case), so the gate is O(1) per offer and each swept request is
        removed exactly once (amortized O(1) per admission). An
        unconditional full walk here measurably strangled the queue
        lock under flood arrivals — submitters sweeping O(depth) per
        offer starved the worker's `take_batch` on the same condition.
        A corpse hiding behind a younger head (mixed per-request
        deadlines) still drops at batch assembly, the pre-fix
        behavior."""
        expired: list[_Request] = []
        for d in self._items:
            while d:
                dl = d[0].future.deadline
                if dl is None or now <= dl:
                    break
                expired.append(d.popleft())
        if expired:
            # nrlint: disable=lock-discipline — caller (offer) holds it
            self.deadline_missed += len(expired)
        return expired

    def offer(self, req: _Request, limit: int,
              now: float) -> _OfferResult:
        """Admit, evict-to-admit, or shed, against the (possibly
        adaptive) `limit`. Expired queued requests are swept first
        whenever the queue is at its limit."""
        with self._lock:
            if self._closed:
                raise FrontendClosed()
            expired: list[_Request] = []
            if self._depth_unlocked() >= limit:
                expired = self._sweep_expired_unlocked(now)
            if self._depth_unlocked() < limit:
                self._items[req.priority].append(req)
                self.accepted += 1
                self._lock.notify()
                return _OfferResult(True, expired, None)
            # full at the adaptive limit: strict-priority shedding —
            # evict the NEWEST queued request of the LOWEST class
            # strictly below this one (BULK goes first)
            for p in range(len(PRIORITIES) - 1, req.priority, -1):
                if self._items[p]:
                    evicted = self._items[p].pop()
                    self._items[req.priority].append(req)
                    self.accepted += 1
                    self.evicted += 1
                    self.shed += 1
                    self.shed_by_prio[evicted.priority] += 1
                    self._lock.notify()
                    return _OfferResult(True, expired, evicted)
            self.shed += 1
            self.shed_by_prio[req.priority] += 1
            inversion = req.priority == CRITICAL and any(
                self._items[p]
                for p in range(CRITICAL + 1, len(PRIORITIES))
            )
            if inversion:
                self.priority_inversions += 1
            return _OfferResult(False, expired, None, inversion)

    def readmit(self, req: _Request) -> bool:
        """Enqueue a request re-homed from a FAILED replica's queue
        WITHOUT counting a second admission — the original queue
        already counted it `accepted` (and its counters fold into the
        frontend aggregates), so `offer` here would double-count.
        Bounded by the STATIC depth (re-homing is not subject to the
        adaptive limit — the op was already admitted once). False when
        closed or full (not a shed: the caller rejects with
        `ReplicaFailed`, not `Overloaded`)."""
        with self._lock:
            if self._closed or self._depth_unlocked() >= self._depth:
                return False
            self._items[req.priority].append(req)
            self._lock.notify()
            return True

    def take_batch(
        self, max_ops: int, linger_s: float
    ) -> list[_Request] | None:
        """Block for the next batch; None = closed and fully drained.
        Waits for the first op, then lingers up to `linger_s` for the
        batch to fill — unless a full batch is already queued or the
        queue is closing (drain fast). Drains strictly by priority
        class (CRITICAL first), FIFO within each class."""
        clock = get_clock()
        with self._lock:
            t_wait = (
                clock.now()
                if self._reg.enabled and not self._depth_unlocked()
                and not self._closed else None
            )
            while not self._depth_unlocked() and not self._closed:
                clock.wait(self._lock)
            if t_wait is not None:
                self._m_wait.observe(clock.now() - t_wait)
            if not self._depth_unlocked():
                return None  # closed and empty: worker exits
            if (linger_s > 0 and self._depth_unlocked() < max_ops
                    and not self._closed):
                t_end = clock.now() + linger_s
                while (self._depth_unlocked() < max_ops
                       and not self._closed):
                    rem = t_end - clock.now()
                    if rem <= 0:
                        break
                    clock.wait(self._lock, rem)
                if self._reg.enabled:
                    # t_end - linger_s is the linger start; no extra
                    # clock call was spent on the disabled path
                    self._m_linger.observe(
                        clock.now() - (t_end - linger_s)
                    )
            batch: list[_Request] = []
            for d in self._items:
                while d and len(batch) < max_ops:
                    batch.append(d.popleft())
            # additive: a pipelined frontend can have one round in
            # flight AND the next batch taken for assembly, and
            # wait_idle must see both (serial mode only ever holds one)
            self._in_service += len(batch)
            return batch

    def batch_done(self, completed: int, missed: int,
                   taken: int) -> None:
        """Retire one taken batch (`taken` = its size at `take_batch`,
        whatever later happened to its requests). Clamped: the worker
        loop's last-resort guard cannot know whether the failed round
        already retired itself."""
        with self._lock:
            self._in_service = max(0, self._in_service - taken)
            self.completed += completed
            self.deadline_missed += missed
            self._lock.notify_all()  # wake wait_idle


    def depth(self) -> int:
        with self._lock:
            return self._depth_unlocked()

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no op is queued or in service (drain barrier)."""
        clock = get_clock()
        t_end = (
            None if timeout is None else clock.now() + timeout
        )
        with self._lock:
            while self._depth_unlocked() or self._in_service:
                rem = (
                    None if t_end is None else t_end - clock.now()
                )
                if rem is not None and rem <= 0:
                    return False
                clock.wait(self._lock, rem)
            return True

    def close(self, drain: bool) -> list[_Request]:
        """Stop admission. `drain=True` leaves queued ops for the
        worker to flush; `drain=False` returns them for rejection."""
        with self._lock:
            self._closed = True
            leftovers: list[_Request] = []
            if not drain:
                for d in self._items:
                    leftovers.extend(d)
                    d.clear()
            self._lock.notify_all()
            return leftovers

    def stats(self) -> dict:
        with self._lock:
            return {
                "queued": self._depth_unlocked(),
                "queued_by_priority": {
                    PRIORITY_NAMES[p]: len(self._items[p])
                    for p in PRIORITIES
                },
                "in_service": self._in_service,
                "accepted": self.accepted,
                "shed": self.shed,
                "shed_by_priority": {
                    PRIORITY_NAMES[p]: self.shed_by_prio[p]
                    for p in PRIORITIES
                },
                "evicted": self.evicted,
                "priority_inversions": self.priority_inversions,
                "completed": self.completed,
                "deadline_missed": self.deadline_missed,
            }


class ServeFrontend:
    """Request frontend over a `NodeReplicated`/`MultiLogReplicated`.

    One bounded queue + one worker (the elected combiner) per replica.
    Use as a context manager for guaranteed drain-on-exit:

        with ServeFrontend(nr) as fe:
            fut = fe.submit((HM_PUT, k, v), rid=0)
            ...
            assert fut.result() == 0

    `auto_start=False` builds the frontend paused (requests queue up,
    nothing executes) — deterministic admission/deadline tests and
    warm-up staging; call `start()` to begin service.
    """

    def __init__(
        self,
        nr,
        config: ServeConfig | None = None,
        rids: Sequence[int] | None = None,
        auto_start: bool = True,
        read_only: bool = False,
    ):
        if not hasattr(nr, "execute_mut_batch"):
            raise TypeError(
                f"{type(nr).__name__} has no execute_mut_batch; the "
                f"frontend serves NodeReplicated/MultiLogReplicated"
            )
        self._nr = nr
        self.cfg = config or ServeConfig()
        if self.cfg.pipeline_depth > 0 and not hasattr(
                nr, "begin_mut_batch"):
            raise TypeError(
                f"{type(nr).__name__} has no begin_mut_batch/"
                f"finish_mut_batch; pipelined serving needs the "
                f"split-round protocol (core/replica.py)"
            )
        # durable-ack wiring (`durable/`): both durable modes need the
        # WAL present NOW — discovering its absence at the first batch
        # would resolve futures that were promised durability
        if self.cfg.durability != "none":
            wal = getattr(nr, "wal", None)
            if wal is None:
                raise ValueError(
                    f"durability={self.cfg.durability!r} requires a "
                    f"WAL attached to the wrapper (attach_wal)"
                )
            if (self.cfg.durability == "always"
                    and wal.policy != "always"):
                raise ValueError(
                    "durability='always' needs WAL fsync policy "
                    f"'always' (WAL has {wal.policy!r}); with a "
                    "weaker policy acks would outrun fsync"
                )
        # fsync barrier per batch only in "batch" mode ("always" is
        # already durable inside the wrapper's append)
        self._durable_sync = self.cfg.durability == "batch"
        #: adaptive overload plane (`serve/overload.py`); None = the
        #: static queue_depth bound only (the pre-overload behavior)
        self.governor: OverloadGovernor | None = None
        if self.cfg.overload is not None:
            self.governor = OverloadGovernor(
                self.cfg.overload, self.cfg.queue_depth,
                deadline_s=self.cfg.default_deadline_s,
                pipeline_depth=self.cfg.pipeline_depth,
            )
            if hasattr(nr, "wal"):
                # end-to-end backpressure, leg 1: the journal's
                # unfsynced backlog throttles admission before it can
                # grow unbounded (repl/ ship+apply lag register via
                # install_backpressure / add_backpressure_source).
                # The WAL is resolved at POLL time, not construction:
                # attach_wal after the frontend is built (the normal
                # PR-5 flow under durability="none") must still wire
                # this leg — a construction-time snapshot would leave
                # it silently dead. No WAL attached = lag 0.
                def _wal_fsync_lag():
                    wal = getattr(self._nr, "wal", None)
                    return 0 if wal is None else wal.fsync_lag()

                self.governor.add_source(LagSource(
                    "wal-fsync", _wal_fsync_lag,
                    self.cfg.wal_lag_low, self.cfg.wal_lag_high,
                ))
        # guards _queues/_workers/_read_tokens/_closed topology changes
        # (grow, close); the hot submit path reads the dicts lock-free
        # (GIL-atomic lookups; workers are keyed once at creation).
        # Declared nestings the analyzer cannot type (`self._nr` is a
        # duck-typed wrapper; queues live in a dict):
        # nrcheck: lock-order ServeFrontend._lock -> NodeReplicated._lock — close/grow/stats call into the wrapper under the frontend lock
        # nrcheck: lock-order ServeFrontend._lock -> MultiLogReplicated._lock — same nesting through the CNR wrapper
        # nrcheck: lock-order ServeFrontend._lock -> _SubmissionQueue._lock — queue close/drain runs under the frontend lock
        self._lock = make_lock("ServeFrontend._lock")
        self._closed = False
        self._started = False
        self._queues: dict[int, _SubmissionQueue] = {}
        self._workers: dict[int, threading.Thread] = {}
        # pipelined serving (`pipeline_depth > 0`): per-replica
        # completion-stage threads + handoff channels; empty in serial
        # mode so nothing below pays for the feature being off
        self._completers: dict[int, threading.Thread] = {}
        self._channels: dict[int, _PipelineChannel] = {}
        self._read_tokens: dict[int, object] = {}
        self._depth_gauges: dict[int, object] = {}
        # failover state: failed rid -> the exception that killed its
        # worker; counters folded from retired (replaced) queues so
        # aggregate stats survive a restart's queue swap
        self._failed: dict[int, BaseException] = {}
        self._retired: dict[str, int] = {}
        self._retired_prio: dict[str, int] = {}
        self._rehomed = 0
        #: lifecycle callback `fn(rid, exc)` — the `fault/` manager
        #: installs itself here to quarantine + repair + restart
        self.on_replica_failed: Callable[[int, BaseException], None] | None = None
        #: set by `from_recovery` (durable/recovery.py:RecoveryReport)
        self.recovery_report = None
        # follower mode (`repl/`): writes reject with NotPrimary until
        # a promotion flips the frontend via enable_writes()
        self._read_only = bool(read_only)
        #: replication ack barrier `fn(durable_pos)` — the `repl/`
        #: shipper installs `shipper.barrier` here so a durable-ack
        #: batch resolves only after its records are SHIPPED to the
        #: follower feed as well as fsynced (ship-before-ack: the
        #: semi-synchronous mode whose acks survive primary loss
        #: because a promoted follower provably holds them). A tree
        #: root extends it with downstream receipt:
        #: `repl/transport.py:make_tree_barrier(shipper, server)`
        #: additionally waits until every direct relay's poll cursor
        #: confirms the records — an ack then survives the primary
        #: being SIGKILLed even though the feed dies with it, because
        #: every subtree already holds the bytes.
        self.ack_barrier: Callable[[int], None] | None = None

        reg = get_registry()
        self._m_submitted = reg.counter("serve.submitted")
        self._m_completed = reg.counter("serve.completed")
        self._m_shed = reg.counter("serve.shed")
        self._m_miss = reg.counter("serve.deadline_miss")
        self._m_batches = reg.counter("serve.batches")
        self._m_rehomed = reg.counter("serve.rehomed")
        self._m_batch_size = reg.histogram("serve.batch.size",
                                           buckets=COUNT_BUCKETS)
        self._m_batch_dur = reg.histogram("serve.batch.duration_s")
        self._m_req_lat = reg.histogram("serve.request.latency_s")
        # requests that expired while their round was in flight and
        # still resolved successfully (the completion-stage second
        # sweep): delivered — first resolution wins, the op executed —
        # but counted so SLO accounting stays honest
        self._m_late = reg.counter("serve.deadline_late_success")

        #: mesh fleet (`NodeReplicated(mesh=...)`): worker-per-replica
        #: → device map. Each combiner worker owns a replica whose
        #: state shard lives on ONE device of the mesh, so a fleet
        #: bigger than any single chip's HBM serves through the same
        #: queue/worker machinery — the map records which chip each
        #: worker's rounds land on (stats()["mesh"], obs gauges via
        #: announce_placement at wrapper construction).
        self.device_of_rid: dict[int, str] = {}

        with self._lock:
            for rid in (rids if rids is not None
                        else range(nr.n_replicas)):
                rid = int(rid)
                if rid in self._queues:
                    raise ValueError(f"replica {rid} served twice")
                self._store_replica(rid, self._new_replica(rid))
                self._record_device(rid)

        #: fleet observability side port (`ServeConfig.obs_port`,
        #: `obs/export.py`): the node's scrape endpoint, labeled by
        #: role — a read-only (follower-mode) frontend announces
        #: itself as such so the fleet dashboard draws the tree right
        self.exporter = None
        if self.cfg.obs_port is not None:
            from node_replication_tpu.obs.export import MetricsExporter

            self.exporter = MetricsExporter(
                node_id=self.cfg.obs_node_id,
                role="follower" if self._read_only else "primary",
                port=self.cfg.obs_port,
            )
            self.exporter.add_stats("serve", self.stats)
        #: host sampling profiler (`ServeConfig.profile_hz`,
        #: `obs/profile.py`): same existence discipline as the
        #: exporter — None by default, so profiling costs nothing
        #: unless a rate was asked for
        self.profiler = None
        if self.cfg.profile_hz is not None:
            from node_replication_tpu.obs.profile import SamplingProfiler

            self.profiler = SamplingProfiler(hz=self.cfg.profile_hz)
            self.profiler.start()
            if self.exporter is not None:
                # remote capture serves the frontend's profiler; its
                # lifecycle stays here (exporter.close won't stop it)
                self.exporter.attach_profiler(self.profiler)
        if auto_start:
            self.start()

    def _record_device(self, rid: int) -> None:
        if getattr(self._nr, "mesh", None) is not None:
            self.device_of_rid[rid] = str(self._nr.replica_device(rid))

    # ------------------------------------------------------------ lifecycle

    @classmethod
    def from_recovery(
        cls,
        directory: str,
        dispatch,
        config: "ServeConfig | None" = None,
        rids: Sequence[int] | None = None,
        auto_start: bool = True,
        nr_kwargs: dict | None = None,
    ) -> "ServeFrontend":
        """Reopen serving state after a crash: `recover_fleet` rebuilds
        the wrapper from `directory` (newest valid snapshot + WAL tail
        replayed through the same dispatch scan — bit-identical to the
        pre-crash fleet), re-attaches the WAL at the recovered tail,
        and this builds a frontend over it so traffic resumes where the
        fsync-acked history ends. The WAL's fsync policy follows
        `config.durability` (`"none"`/`"batch"`/`"always"`); the
        `RecoveryReport` is exposed as `frontend.recovery_report`.
        A missing/empty directory boots (and starts journaling) a
        fresh fleet — `from_recovery` is therefore also the canonical
        cold-start entry for a durable serve deployment."""
        from node_replication_tpu.durable.recovery import recover_fleet

        config = config or ServeConfig()
        # WAL fsync policy mirrors the ack contract; "none" durability
        # still journals (batch-style, caller/close syncs only)
        policy = (
            config.durability if config.durability != "none"
            else "batch"
        )
        nr, report = recover_fleet(
            directory, dispatch, policy=policy, attach=True,
            nr_kwargs=nr_kwargs,
        )
        fe = cls(nr, config, rids=rids, auto_start=auto_start)
        fe.recovery_report = report
        return fe

    @property
    def nr(self):
        """The wrapped `NodeReplicated`/`MultiLogReplicated` (read
        access for recovery verification and ops tooling; mutate it
        only through the frontend)."""
        return self._nr

    def _spawn_workers(self, rid: int, q: "_SubmissionQueue"):
        """Worker thread(s) for one replica: the serial combiner loop,
        or (`pipeline_depth > 0`) the assembly + completion stage pair
        joined by a capacity-1 handoff channel. Returns
        `(worker, completer, channel)` — the latter two None in serial
        mode. The CALLER stores them into the topology dicts under
        `_lock`; threads start only via `start()`."""
        if self.cfg.pipeline_depth > 0:
            chan = _PipelineChannel()
            asm = threading.Thread(
                target=self._assembly_loop, args=(rid, q, chan),
                name=f"serve-asm-r{rid}", daemon=True,
            )
            cpl = threading.Thread(
                target=self._completion_loop, args=(rid, q, chan),
                name=f"serve-cpl-r{rid}", daemon=True,
            )
            return asm, cpl, chan
        t = threading.Thread(
            target=self._worker_loop, args=(rid, q),
            name=f"serve-worker-r{rid}", daemon=True,
        )
        return t, None, None

    def _new_replica(self, rid: int):
        """Build the queue/worker(s)/token/gauge set for one replica;
        the CALLER stores them into the topology dicts under `_lock`
        (so every write to the guarded dicts is visibly locked). The
        workers start only via `start()`."""
        q = _SubmissionQueue(self.cfg.queue_depth)
        t, cpl, chan = self._spawn_workers(rid, q)
        token = self._nr.register(rid)
        gauge = get_registry().gauge(f"serve.queue_depth.r{rid}")
        if self.governor is not None:
            self.governor.register_replica(rid)
        return q, t, cpl, chan, token, gauge

    def _store_replica(self, rid: int, built) -> None:
        """Store one `_new_replica` result into the topology dicts;
        caller holds `_lock`."""
        q, t, cpl, chan, token, gauge = built
        # both callers (the constructor and grow()) hold _lock, which
        # is non-reentrant — re-acquiring here would deadlock
        self._queues[rid] = q  # nrlint: disable=lock-discipline — caller holds _lock
        self._workers[rid] = t  # nrlint: disable=lock-discipline — caller holds _lock
        if cpl is not None:
            self._completers[rid] = cpl  # nrlint: disable=lock-discipline — caller holds _lock
            self._channels[rid] = chan  # nrlint: disable=lock-discipline — caller holds _lock
        self._read_tokens[rid] = token  # nrlint: disable=lock-discipline — caller holds _lock
        self._depth_gauges[rid] = gauge  # nrlint: disable=lock-discipline — caller holds _lock

    def start(self) -> None:
        """Start every not-yet-running worker (idempotent)."""
        with self._lock:
            if self._closed:
                raise FrontendClosed("cannot start a closed frontend")
            self._started = True
            for t in (list(self._workers.values())
                      + list(self._completers.values())):
                if not t.is_alive() and not t.ident:
                    t.start()

    @property
    def rids(self) -> list[int]:
        with self._lock:  # grow() can resize the dict mid-iteration
            return sorted(self._queues)

    def threads(self) -> dict[str, list[str]]:
        """Live worker threads by profiler role (`obs.profile.role_of`)
        — the introspection face of the thread-name contract the
        sampling profiler attributes by. Covers the frontend's own
        workers/completers plus the exporter accept thread and the
        profiler sampler when those exist. Names are unique (each
        embeds its rid or node id), so the dict is loss-free."""
        from node_replication_tpu.obs.profile import role_of

        with self._lock:
            live = [
                t for t in (list(self._workers.values())
                            + list(self._completers.values()))
                if t.is_alive()
            ]
        for extra in (
            self.exporter.accept_thread
            if self.exporter is not None else None,
            self.profiler.thread
            if self.profiler is not None else None,
        ):
            if extra is not None and extra.is_alive():
                live.append(extra)
        out: dict[str, list[str]] = {}
        for t in live:
            out.setdefault(role_of(t.name), []).append(t.name)
        for names in out.values():
            names.sort()
        return out

    def grow(self, k: int = 1) -> list[int]:
        """Add `k` replicas to the live fleet (`grow_fleet`) and start
        serving them — queues and workers spin up while existing
        traffic keeps flowing (elasticity under load)."""
        if not hasattr(self._nr, "grow_fleet"):
            raise TypeError(
                f"{type(self._nr).__name__} has no grow_fleet"
            )
        with self._lock:
            if self._closed:
                raise FrontendClosed("cannot grow a closed frontend")
            new_rids = self._nr.grow_fleet(k)
            for rid in new_rids:
                rid = int(rid)
                if rid in self._queues:
                    raise ValueError(f"replica {rid} served twice")
                self._store_replica(rid, self._new_replica(rid))
                self._record_device(rid)
            started = self._started
        get_tracer().emit("serve-grow", rids=list(map(int, new_rids)))
        if started:
            self.start()
        return new_rids

    # ------------------------------------------------------------ failover

    def healthy_rids(self) -> list[int]:
        """Served replicas currently accepting admissions (rids minus
        failed ones) — `call_with_retry`'s re-route domain."""
        with self._lock:
            return sorted(r for r in self._queues
                          if r not in self._failed)

    def _rehome(self, req: _Request,
                targets: list[_SubmissionQueue]) -> bool:
        """Move a failed replica's queued request onto a healthy
        replica's queue (admission-order preserved within the batch of
        leftovers; `readmit` — the request was already counted
        accepted once). False when no target admits it."""
        for q in targets:
            if q.readmit(req):
                return True
        return False

    def _fail_replica(self, rid: int, q: _SubmissionQueue,
                      exc: BaseException) -> None:
        """Retire replica `rid` from admission (worker death path):
        mark it failed, re-home its queued requests onto healthy
        replicas (rejecting with retryable `ReplicaFailed` only when
        none admits), and hand the corpse to `on_replica_failed`.
        Runs on the dying worker thread; idempotent."""
        with self._lock:
            already = rid in self._failed
            if not already:
                self._failed[rid] = exc
        if already:
            return
        leftovers = q.close(drain=False)
        # one topology snapshot for the whole leftover batch (per-
        # request healthy_rids() would hammer the frontend lock from
        # the dying worker while clients contend on submit)
        with self._lock:
            targets = [self._queues[r] for r in sorted(self._queues)
                       if r != rid and r not in self._failed]
        rehomed = 0
        for req in leftovers:
            if self._rehome(req, targets):
                rehomed += 1
            else:
                req.future._reject(
                    ReplicaFailed(rid, exc, maybe_executed=False)
                )
        with self._lock:
            self._rehomed += rehomed
            gauge = self._depth_gauges.get(rid)
        # retire the replica's per-rid depth gauge with it: a gauge
        # for a replica no one serves would haunt every scrape (and
        # the registry) with its last pre-death value forever;
        # `restart_replica` re-registers the name on readmission.
        # Handle-owned removal: after a restart re-registered a fresh
        # gauge, a straggling retire from the OLD worker must not
        # remove the live one. (Two co-resident frontends serving the
        # same rid share the name outright — but then the gauge was
        # already last-write-wins noise; per-node metrics are
        # process-grained, obs/export.py docstring.)
        get_registry().remove(f"serve.queue_depth.r{rid}", gauge)
        if rehomed:
            self._m_rehomed.inc(rehomed)
            get_tracer().emit("serve-rehome", rid=rid, n=rehomed)
        get_tracer().emit(
            "serve-replica-failed", rid=rid, rehomed=rehomed,
            queued=len(leftovers), cause=type(exc).__name__,
        )
        logger.warning(
            "serve worker r%d retired after %s: %s (%d queued "
            "request(s) re-homed)", rid, type(exc).__name__, exc,
            rehomed,
        )
        cb = self.on_replica_failed
        if cb is not None:
            try:
                cb(rid, exc)
            # the replica failure is already recorded (self._failed +
            # every future rejected) before this guard; it only shields
            # the worker exit from a buggy USER lifecycle handler
            # nrlint: disable=swallowed-worker-exception
            except Exception:
                logger.exception(
                    "on_replica_failed handler raised; replica %d "
                    "stays failed", rid,
                )

    def restart_replica(self, rid: int) -> None:
        """Readmit a failed replica after repair (`fault/repair.py`):
        fresh queue + worker; the read token is reused (registration is
        permanent). The retired queue's counters fold into the
        frontend-level aggregates so `stats()` stays cumulative."""
        with self._lock:
            if self._closed:
                raise FrontendClosed(
                    "cannot restart a replica on a closed frontend"
                )
            if rid not in self._failed:
                raise ValueError(f"replica {rid} has not failed")
            old = self._queues[rid].stats()
            for k in ("accepted", "shed", "completed",
                      "deadline_missed", "evicted",
                      "priority_inversions"):
                self._retired[k] = self._retired.get(k, 0) + old[k]
            for name, v in old["shed_by_priority"].items():
                self._retired_prio[name] = (
                    self._retired_prio.get(name, 0) + v
                )
            q = _SubmissionQueue(self.cfg.queue_depth)
            t, cpl, chan = self._spawn_workers(rid, q)
            self._queues[rid] = q
            self._workers[rid] = t
            if cpl is not None:
                self._completers[rid] = cpl
                self._channels[rid] = chan
            # fresh gauge registration: `_fail_replica` removed the
            # retired replica's name from the registry
            self._depth_gauges[rid] = get_registry().gauge(
                f"serve.queue_depth.r{rid}"
            )
            del self._failed[rid]
            started = self._started
        get_tracer().emit("serve-replica-restart", rid=rid)
        if started:
            t.start()
            if cpl is not None:
                cpl.start()

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every queue is empty and no batch is in flight.
        Returns False on timeout. Admission stays open — this is a
        flush barrier, not a shutdown."""
        with self._lock:  # grow() can resize the dict mid-iteration
            qs = list(self._queues.values())
        clock = get_clock()
        t_end = (
            None if timeout is None else clock.now() + timeout
        )
        for q in qs:
            rem = None if t_end is None else t_end - clock.now()
            if not q.wait_idle(rem):
                return False
        return True

    def close(self, drain: bool = True,
              timeout: float | None = None) -> None:
        """Stop admission and shut down. `drain=True` (default)
        flushes every queued op through the combiner first;
        `drain=False` rejects the backlog with `FrontendClosed`.
        Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            queues = list(self._queues.items())
            workers = (list(self._workers.values())
                       + list(self._completers.values()))
            gauges = dict(self._depth_gauges)
            started = self._started
        leftovers: list[_Request] = []
        for _, q in queues:
            leftovers.extend(q.close(drain))
        for req in leftovers:
            req.future._reject(FrontendClosed("closed before service"))
        if timeout is None:
            timeout = self.cfg.drain_timeout_s
        clock = get_clock()
        t_end = clock.now() + timeout
        if started:
            for t in workers:
                t.join(max(0.0, t_end - clock.now()))
        # paused frontend (never started) or drain timeout: requests
        # may still sit in the queues — reject, never strand a future
        for _, q in queues:
            for req in q.close(drain=False):
                req.future._reject(
                    FrontendClosed("closed before service")
                )
        # every served replica retires with the frontend: their
        # per-rid depth gauges leave the registry (the scrape surface)
        # instead of reporting a dead frontend's last depths forever
        # (handle-owned removal — see _fail_replica)
        reg = get_registry()
        for rid, _ in queues:
            reg.remove(f"serve.queue_depth.r{rid}", gauges.get(rid))
        if self.profiler is not None:
            self.profiler.stop()
        if self.exporter is not None:
            self.exporter.close()
        get_tracer().emit("serve-close", drained=drain)

    def __enter__(self) -> "ServeFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))

    # ------------------------------------------------------------ client API

    def submit(self, op: tuple, rid: int = 0,
               deadline_s: float | None = None,
               priority: int = NORMAL) -> ServeFuture:
        """Stage one write op on replica `rid`; returns its future.
        Raises `Overloaded` when the admission queue is full at its
        (possibly adaptive) limit, `FrontendClosed` after `close()`,
        (failover mode) `ReplicaFailed` while the replica is down, and
        (follower mode) `NotPrimary` while writes are disabled — all
        BEFORE the op can have any effect.

        `priority` (`serve.overload.CRITICAL/NORMAL/BULK`) orders
        shedding, strictly: at a full queue a higher-priority arrival
        evicts the newest queued lower-priority request (whose future
        rejects with `Overloaded(evicted=True)`) instead of shedding
        itself, so BULK traffic always sheds first. Deadline-expired
        queued requests are swept out at admission time — a corpse
        never costs a live request its slot."""
        if self._read_only:
            # follower mode (`repl/`): no write is ever admitted, so a
            # rejected caller can safely resubmit against the primary
            raise NotPrimary(rid)
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r} (CRITICAL=0 NORMAL=1 "
                f"BULK=2)"
            )
        # closed wins over failed: a closed frontend is PERMANENT and
        # must not hand retry loops a retryable ReplicaFailed.
        # Admission fast path: GIL-atomic flag/dict reads — a racing
        # failover is caught again below (`q.offer` under its lock)
        # nrcheck: unshared — GIL-atomic reads; re-checked under lock
        if not self._closed and rid in self._failed:
            # nrcheck: unshared — GIL-atomic dict read
            raise ReplicaFailed(rid, self._failed.get(rid),
                                maybe_executed=False)
        q = self._queues.get(rid)  # nrcheck: unshared — GIL-atomic read
        if q is None:
            raise ValueError(f"replica {rid} is not served "
                             f"(have {self.rids})")
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        now = get_clock().now()
        deadline = None if deadline_s is None else now + deadline_s
        gov = self.governor
        limit = (
            self.cfg.queue_depth if gov is None
            else min(self.cfg.queue_depth, gov.limit(rid))
        )
        fut = ServeFuture(rid, deadline=deadline)
        try:
            res = q.offer(_Request(op, fut, priority), limit, now)
        except FrontendClosed:
            # a per-replica queue closed while the frontend is open can
            # only mean this replica failed (or is mid-restart): that
            # is the retryable signal, not a permanent closure
            # nrcheck: unshared — GIL-atomic flag read
            if not self._closed:
                raise ReplicaFailed(
                    # nrcheck: unshared — GIL-atomic dict read
                    rid, self._failed.get(rid), maybe_executed=False
                ) from None
            raise
        self._finish_offer(rid, res, limit, now)
        if not res.admitted:
            self._m_shed.inc()
            if gov is not None:
                gov.note_shed(priority)
            get_tracer().emit("serve-shed", rid=rid, depth=limit,
                              prio=PRIORITY_NAMES[priority])
            raise Overloaded(rid, limit, priority=priority)
        self._m_submitted.inc()
        return fut

    def _finish_offer(self, rid: int, res: _OfferResult, limit: int,
                      now: float) -> None:
        """Resolve the futures `offer` removed under its lock — the
        eagerly swept expired requests and the priority eviction —
        and do their accounting (outside the queue lock: rejection
        runs user done-callbacks)."""
        for req in res.expired:
            late = now - (req.future.deadline or now)
            req.future._reject(DeadlineExceeded(rid, late))
        if res.expired:
            self._m_miss.inc(len(res.expired))
            get_tracer().emit("serve-deadline-miss", rid=rid,
                              n=len(res.expired), swept=1)
        ev = res.evicted
        if ev is not None:
            self._m_shed.inc()
            if self.governor is not None:
                self.governor.note_shed(ev.priority, evicted=True)
            get_tracer().emit("serve-evict", rid=rid,
                              prio=PRIORITY_NAMES[ev.priority])
            ev.future._reject(Overloaded(
                rid, limit, priority=ev.priority, evicted=True,
            ))
        if res.inversion:
            # the queue already counted it (priority_inversions, the
            # invariant the sim/bench gates assert stays zero); make
            # it loud in the trace too
            get_tracer().emit("serve-priority-inversion", rid=rid)

    def call(self, op: tuple, rid: int = 0,
             deadline_s: float | None = None,
             timeout: float | None = None,
             priority: int = NORMAL):
        """Closed-loop convenience: `submit` + `result`."""
        return self.submit(op, rid, deadline_s,
                           priority=priority).result(timeout)

    def add_backpressure_source(self, name: str, fn, low: int,
                                high: int) -> None:
        """Attach a downstream lag feed to the admission controller
        (`serve/overload.py:LagSource` semantics: no influence below
        `low`, growth pause between, multiplicative decrease at/above
        `high`). Raises when the overload plane is off — silently
        ignoring a backpressure wire would let the backlog it guards
        grow unbounded."""
        if self.governor is None:
            raise ValueError(
                "backpressure needs the overload plane: construct the "
                "frontend with ServeConfig(overload=OverloadConfig())"
            )
        self.governor.add_source(LagSource(name, fn, low, high))

    @property
    def read_only(self) -> bool:
        """True while serving in follower mode (writes rejected)."""
        return self._read_only

    def enable_writes(self) -> None:
        """Promotion re-home (`repl/promote.py`): flip a read-only
        (follower-mode) frontend into write serving. The queues and
        workers were live all along — only admission changes — so the
        first write after promotion needs no warm-up. Idempotent."""
        if not self._read_only:
            return
        self._read_only = False
        if self.exporter is not None:
            # the fleet view should see the promotion, not a stale
            # "follower" label on the node now taking writes
            self.exporter.role = "primary"
        get_tracer().emit("serve-enable-writes")

    def read(self, op: tuple, rid: int = 0,
             min_pos: int | None = None, wait_s: float = 0.0):
        """Read against replica `rid` via the wrapper's read-sync path
        (`execute`): waits only for THIS replica to pass the completed
        tail, then dispatches locally — never enters the write queue
        or the log (`nr/src/replica.rs:404-410`).

        `min_pos` is the bounded-staleness cursor (the `repl/`
        follower read path): the read dispatches only once replica
        `rid`'s applied position (`ltails[rid]`) has reached `min_pos`,
        waiting up to `wait_s` seconds and then rejecting with a typed
        `StaleRead` — a client never silently observes state older
        than its bound. On a primary the bound is trivially satisfied
        (the write path replays before responding).

        **Brownout** (`ServeConfig.overload`): while the governor is
        in brownout, a read WITHOUT an explicit `min_pos` degrades to
        the bounded-staleness path instead of paying read-sync — it
        dispatches against the replica's current state
        (`execute_stale`) when the replica's lag is within
        `OverloadConfig.brownout_max_lag`, falling back to the synced
        path when it is not. A brownout read can therefore never
        exceed its staleness bound; the worst lag actually served is
        recorded (`governor.stats()['max_brownout_lag']`). An
        explicit `min_pos` (read-your-writes) always takes the synced
        path — a client that asked for a bound gets that bound."""
        # nrcheck: unshared — GIL-atomic dict read; read fast path
        token = self._read_tokens.get(rid)
        if token is None:
            raise ValueError(f"replica {rid} is not served "
                             f"(have {self.rids})")
        gov = self.governor
        if (min_pos is None and gov is not None and gov.brownout()
                and hasattr(self._nr, "execute_stale_bounded")):
            # bound check + dispatch are ONE lock acquisition inside
            # the wrapper — a separate read_lag peek would race a
            # concurrent batch advancing the completed tail and serve
            # (and under-record) beyond the bound
            hit = self._nr.execute_stale_bounded(
                op, token, gov.cfg.brownout_max_lag
            )
            if hit is not None:
                value, lag = hit
                gov.note_brownout_read(lag)
                return value
            # replica too far behind for the brownout bound: pay the
            # synced path rather than serve beyond the bound
        if min_pos is not None:
            min_pos = int(min_pos)
            ltail = getattr(self._nr, "ltail", None)
            if ltail is None:
                raise TypeError(
                    f"{type(self._nr).__name__} has no ltail "
                    f"accessor; bounded-staleness reads need it"
                )
            clock = get_clock()
            deadline = clock.now() + max(0.0, wait_s)
            while True:
                # locked cursor peek: an unlocked log read races the
                # exec round's buffer donation (core/replica.ltail)
                applied = ltail(rid)
                if applied >= min_pos:
                    break
                if clock.now() >= deadline:
                    raise StaleRead(rid, applied, min_pos)
                clock.sleep(0.0002)
        return self._nr.execute(op, token)

    def stats(self) -> dict:
        """Aggregate + per-replica frontend counters (plain ints,
        independent of the metrics registry's enable flag). Counters of
        queues retired by failover restarts are folded into the
        aggregates; `rehomed`/`failed` expose the failover state."""
        with self._lock:  # grow() can resize the dict mid-iteration
            queues = sorted(self._queues.items())
            retired = dict(self._retired)
            retired_prio = dict(self._retired_prio)
            rehomed = self._rehomed
            failed = sorted(self._failed)
            # `_record_device` writes this map under the lock from
            # worker threads: snapshot it here, not mid-iteration
            dev_map = dict(self.device_of_rid)
        per = {rid: q.stats() for rid, q in queues}
        agg = {
            k: sum(s[k] for s in per.values())
            for k in ("queued", "in_service", "accepted", "shed",
                      "completed", "deadline_missed", "evicted",
                      "priority_inversions")
        }
        agg["shed_by_priority"] = {
            name: sum(s["shed_by_priority"][name]
                      for s in per.values())
            + retired_prio.get(name, 0)
            for name in PRIORITY_NAMES
        }
        for k, v in retired.items():
            agg[k] += v
        agg["rehomed"] = rehomed
        agg["failed"] = failed
        agg["replicas"] = per
        if self.governor is not None:
            agg["overload"] = self.governor.stats()
        if dev_map:
            per_dev: dict[str, int] = {}
            for dev in dev_map.values():
                per_dev[dev] = per_dev.get(dev, 0) + 1
            agg["mesh"] = {
                "devices": len(per_dev),
                "replicas_per_device": per_dev,
                "device_of_rid": dict(sorted(dev_map.items())),
            }
        return agg

    # ------------------------------------------------------------ worker

    def _worker_loop(self, rid: int, q: _SubmissionQueue) -> None:
        cfg = self.cfg
        while True:
            batch = q.take_batch(cfg.batch_max_ops,
                                 cfg.batch_linger_s)
            if batch is None:
                return
            try:
                self._run_batch(rid, q, batch)
            except _ReplicaDown as down:
                # failover: retire the replica FIRST (marks it failed,
                # re-homes the queue, spawns the lifecycle callback),
                # THEN complete the in-flight futures — a caller woken
                # by its ReplicaFailed must find the failover already
                # in motion, not a pre-failover limbo
                self._fail_replica(rid, q, down.cause)
                for req in down.pending:
                    req.future._reject(ReplicaFailed(
                        rid, down.cause,
                        maybe_executed=down.maybe_executed,
                    ))
                return
            except Exception as e:  # pragma: no cover - last resort
                logger.exception(
                    "serve worker r%d: unexpected batch failure", rid
                )
                if cfg.failover:
                    self._fail_replica(rid, q, e)
                # never strand a caller: reject whatever _run_batch
                # had not resolved (first resolution wins, so futures
                # it DID resolve are untouched)
                for req in batch:
                    req.future._reject(e)
                q.batch_done(0, 0, len(batch))
                if cfg.failover:
                    return

    def _sweep_batch(self, rid: int, q: _SubmissionQueue,
                     batch: list[_Request]):
        """Batch-assembly head shared by the serial round and the
        pipelined assembly stage: the AIMD update (the queue-delay
        control signal is measured HERE, at assembly — a pipelined
        round's in-flight time must not double-count into the
        governor's sojourn signal) and the pre-append deadline sweep.
        Returns `(live, missed, delay)`."""
        now = get_clock().now()
        delay = 0.0
        if batch:
            delay = max(
                0.0, now - min(r.future.t_submit for r in batch)
            )
        if self.governor is not None and batch:
            # the control signal: how long the batch's OLDEST request
            # waited between admission and assembly (CoDel's sojourn
            # time) — one AIMD update per combiner round
            self.governor.on_round(rid, delay, len(batch))
        live: list[_Request] = []
        missed = 0
        for req in batch:
            dl = req.future.deadline
            if dl is not None and now > dl:
                missed += 1
                req.future._reject(
                    DeadlineExceeded(rid, now - dl)
                )
            else:
                live.append(req)
        if missed:
            self._m_miss.inc(missed)
            get_tracer().emit("serve-deadline-miss", rid=rid, n=missed)
        return live, missed, delay

    def _run_batch(self, rid: int, q: _SubmissionQueue,
                   batch: list[_Request]) -> None:
        """One combiner round: sweep expired deadlines, execute the
        survivors as a single `execute_mut_batch`, resolve futures.
        In failover mode a round that throws completes its requests
        with `ReplicaFailed` and raises `_ReplicaDown` so the loop
        retires the replica."""
        try:
            # injection choke point (`fault/inject.py`): fires BEFORE
            # any op can touch the log, so a kill here is pre-append
            # and every in-flight request is exactly-once retryable
            fault_hook("serve-batch", rid, self._nr)
        except Exception as e:
            if not self.cfg.failover:
                raise
            q.batch_done(0, 0, len(batch))
            raise _ReplicaDown(e, batch, maybe_executed=False) from e
        live, missed, delay = self._sweep_batch(rid, q, batch)
        if not live:
            q.batch_done(0, missed, len(batch))
            return
        t0 = get_clock().now()
        try:
            resps = self._nr.execute_mut_batch(
                [req.op for req in live], rid
            )
        except Exception as e:
            if self.cfg.failover:
                # `maybe_executed`: a failure out of the wrapper is
                # only provably pre-append when it is the fence guard
                # or an append-site injection (both fire before the
                # batch reaches the log). Anything else may have struck
                # mid-replay — the ops WILL replay, only responses are
                # lost — so auto-retry must be refused (exactly-once).
                pre_append = isinstance(e, ReplicaFencedError) or (
                    isinstance(e, FaultError) and e.site == "append"
                )
                q.batch_done(0, missed, len(batch))
                logger.exception(
                    "serve worker r%d: batch of %d failed; retiring "
                    "replica", rid, len(live)
                )
                raise _ReplicaDown(
                    e, live, maybe_executed=not pre_append
                ) from e
            for req in live:
                req.future._reject(e)
            q.batch_done(0, missed, len(batch))
            logger.exception(
                "serve worker r%d: batch of %d failed", rid, len(live)
            )
            return
        self._finish_delivery(rid, q, live, missed, len(batch),
                              resps, t0, delay)

    def _finish_delivery(self, rid: int, q: _SubmissionQueue,
                         live: list[_Request], missed: int,
                         taken: int, resps: list, t0: float,
                         delay: float) -> None:
        """Delivery tail shared by the serial round and the pipelined
        completion stage: durable-ack barrier, the SECOND deadline
        sweep (late successes delivered but counted —
        `serve.deadline_late_success`), future resolution, accounting,
        and the `serve-batch` trace event. Raises `_ReplicaDown` on a
        barrier failure in failover mode, exactly like the execute
        path (post-append: `maybe_executed=True`)."""
        barrier = self.ack_barrier
        if self._durable_sync or barrier is not None:
            # durable-ack barrier (`ServeConfig(durability="batch")`):
            # ONE fsync covers the whole batch; futures resolve only
            # past it, so an acked response is on disk. With a
            # replication `ack_barrier` installed (`repl/shipper.py`)
            # the batch additionally waits until the feed holds its
            # records (ship-before-ack), so an acked response also
            # survives PRIMARY loss via promotion. A failed fsync or
            # ship is post-append by definition (the ops are in the
            # log and WILL replay in-process) — reject with
            # maybe_executed semantics rather than ack a durability
            # promise the disk (or the feed) refused.
            try:
                if self._durable_sync:
                    durable = self._nr.wal_sync()
                else:
                    # barrier without batch-fsync (durability="always"
                    # keeps durable_tail == tail): gate on the journal
                    # TAIL, which covers this batch's records — gating
                    # on durable_tail would let a policy="none" WAL
                    # ack unshipped (even un-fsynced) ops silently;
                    # this way the shipper (which ships only fsynced
                    # records) times the barrier out instead, and the
                    # misconfiguration is loud
                    wal = getattr(self._nr, "wal", None)
                    durable = None if wal is None else wal.tail
                if barrier is not None:
                    if durable is None:
                        # an installed barrier with no journal to
                        # gate on would otherwise be skipped silently
                        # — acks would claim replication that never
                        # happened
                        raise RuntimeError(
                            "ack_barrier installed but no WAL is "
                            "attached; ship-before-ack needs the "
                            "journal"
                        )
                    barrier(durable)
            except Exception as e:
                q.batch_done(0, missed, taken)
                logger.exception(
                    "serve worker r%d: durable-ack barrier failed for "
                    "batch of %d", rid, len(live)
                )
                if self.cfg.failover:
                    raise _ReplicaDown(
                        e, live, maybe_executed=True
                    ) from e
                for req in live:
                    req.future._reject(e)
                return
        now2 = get_clock().now()
        dur = now2 - t0
        # second deadline sweep, at delivery: a request that expired
        # while its round was in flight DID execute — deliver the
        # response (first resolution wins; nothing changes for the
        # future) but count it, so SLO accounting never claims an
        # in-deadline success that wasn't
        late = sum(
            1 for req in live
            if req.future.deadline is not None
            and now2 > req.future.deadline
        )
        if late:
            self._m_late.inc(late)
        for req, resp in zip(live, resps):
            req.future._resolve(resp)
            lat = req.future.latency_s
            if lat is not None:
                self._m_req_lat.observe(lat)
        q.batch_done(len(live), missed, taken)
        depth = q.depth()
        self._m_batches.inc()
        self._m_completed.inc(len(live))
        self._m_batch_size.observe(len(live))
        self._m_batch_dur.observe(dur)
        # the map is written under _lock at replica creation, before
        # this worker exists, so the lock-free lookup cannot race it
        # nrcheck: unshared — GIL-atomic dict read
        self._depth_gauges[rid].set(depth)
        tracer = get_tracer()
        if tracer.enabled:
            # which combiner-round engine served this batch
            # (pallas_fused / mesh_fused / combined / scan —
            # obs/report's Kernels section consumes; meshed fleets
            # route eligible rounds through the one-launch mesh-fused
            # tier, and the pipelined worker's defer=True issues that
            # meshed launch at _begin_round with readback at
            # _finish_round, so the overlap composes). Per-rid lookup:
            # this worker is the only round-driver for its replica, so
            # the stamp cannot be overwritten by a concurrent worker's
            # round the way a wrapper-wide field would be.
            tier_of = getattr(self._nr, "round_tier", None)
            # per-record trace join key (`obs/` fleet tracing): the
            # log position this batch appended at, read per-rid for
            # the same reason as the tier. With it the serve-batch
            # event IS the record's submit→ack hop: `queue_delay_s`
            # (admission → assembly) + `duration_s` (assembly → ack)
            # reconstruct the submit time from the ack stamp.
            pos_of = getattr(self._nr, "round_pos", None)
            tracer.emit(
                "serve-batch", rid=rid, n=len(live), expired=missed,
                queue_depth=depth, duration_s=dur,
                queue_delay_s=delay,
                late_success=late,
                pos=(pos_of(rid) if pos_of is not None else None),
                engine=(tier_of(rid) if tier_of is not None
                        else getattr(self._nr, "last_round_tier",
                                     None)),
            )

    # ----------------------------------------------------- pipelined worker

    def _assembly_loop(self, rid: int, q: _SubmissionQueue,
                       chan: _PipelineChannel) -> None:
        """Assembly stage (`pipeline_depth > 0`, thread
        `serve-asm-r{rid}`): wait for the in-flight round's device
        half (`wait_clear` — the queue keeps FILLING through the whole
        round, so batches stay as large as the serial worker's), then
        drain the queue, sweep deadlines, begin the round
        (`begin_mut_batch` — the batch is appended and, on the fused
        tier, the kernel launched when it returns), and hand off. The
        drain + sweep + begin of round N+1 overlap round N's
        completion-stage host work (barrier, future resolution), and
        round N+1's device work overlaps both.

        Death discipline mirrors `_worker_loop`: a begin failure in
        failover mode retires the replica FIRST, then rejects. When
        the completion stage died instead, a not-yet-begun batch never
        exists here (the queue was already closed and re-homed by
        `_fail_replica`) — but a begun round whose `put` the poisoned
        channel refused is post-append, and is torn down honestly
        (`_abort_staged`)."""
        cfg = self.cfg
        while True:
            dead = chan.wait_clear()
            if dead is not None:
                # completion died and already retired the replica
                # (`_fail_replica` ran before `round_done(exc)`);
                # queued requests were re-homed there, nothing is
                # taken or begun on this side — just exit
                return
            batch = q.take_batch(cfg.batch_max_ops,
                                 cfg.batch_linger_s)
            if batch is None:
                chan.close()  # completion drains in-flight, exits
                return
            try:
                staged = self._assemble(rid, q, batch)
            except _ReplicaDown as down:
                chan.close()
                self._fail_replica(rid, q, down.cause)
                for req in down.pending:
                    req.future._reject(ReplicaFailed(
                        rid, down.cause,
                        maybe_executed=down.maybe_executed,
                    ))
                return
            except Exception as e:  # pragma: no cover - last resort
                logger.exception(
                    "serve assembly r%d: unexpected failure", rid
                )
                q.batch_done(0, 0, len(batch))
                for req in batch:
                    req.future._reject(e)
                if cfg.failover:
                    chan.close()
                    self._fail_replica(rid, q, e)
                    return
                continue
            if staged is None:
                continue  # whole batch expired at the sweep
            dead = chan.put(staged)
            if dead is not None:
                # completion died between wait_clear and put: the
                # round IS begun (appended) — post-append teardown
                self._abort_staged(rid, q, staged, dead)
                return

    def _abort_staged(self, rid: int, q: _SubmissionQueue,
                      staged: _Staged, cause: BaseException) -> None:
        """Tear down a begun round nobody will finish (completion-
        stage death): release the wrapper's in-flight slot and drop
        its deliveries (`abort_mut_batch` — the ops are appended and
        WILL replay; only responses are lost), then reject with
        post-append `maybe_executed=True` honesty. `_fail_replica`
        already ran on the completion thread."""
        q.batch_done(0, staged.missed, staged.taken)
        abort = getattr(self._nr, "abort_mut_batch", None)
        if abort is not None:
            try:
                abort(staged.pending)
            # the guard only shields the teardown's slot release; the
            # failure IS recorded — every future of the staged round
            # rejects typed immediately below, and the replica is
            # already marked failed (`_fail_replica` ran first)
            # nrlint: disable=swallowed-worker-exception
            except Exception:  # pragma: no cover - teardown guard
                logger.exception(
                    "serve r%d: abort_mut_batch failed during "
                    "failover teardown", rid
                )
        for req in staged.live:
            req.future._reject(ReplicaFailed(
                rid, cause, maybe_executed=True,
            ))

    def _assemble(self, rid: int, q: _SubmissionQueue,
                  batch: list[_Request]) -> "_Staged | None":
        """One assembly pass: injection choke point, AIMD update +
        deadline sweep (`_sweep_batch` — the queue-delay signal is
        measured here, never at completion), `begin_mut_batch`.
        Returns the staged round for the completion stage (None when
        every request expired). Raises `_ReplicaDown` in failover
        mode; the begin failure is pre-append retryable exactly when
        it is the fence guard or an append/serve-batch-site injection
        — the same classification as the serial path."""
        try:
            # pre-append injection site, same as the serial worker: a
            # kill here fires before any op can touch the log
            fault_hook("serve-batch", rid, self._nr)
        except Exception as e:
            if not self.cfg.failover:
                raise
            q.batch_done(0, 0, len(batch))
            raise _ReplicaDown(e, batch, maybe_executed=False) from e
        clock = get_clock()
        t_asm = clock.now()
        live, missed, delay = self._sweep_batch(rid, q, batch)
        if not live:
            q.batch_done(0, missed, len(batch))
            return None
        t0 = clock.now()
        try:
            pending = self._nr.begin_mut_batch(
                [req.op for req in live], rid
            )
        except Exception as e:
            pre_append = isinstance(e, ReplicaFencedError) or (
                isinstance(e, FaultError) and e.site == "append"
            )
            q.batch_done(0, missed, len(batch))
            logger.exception(
                "serve assembly r%d: begin of %d failed", rid,
                len(live)
            )
            if self.cfg.failover:
                raise _ReplicaDown(
                    e, live, maybe_executed=not pre_append
                ) from e
            for req in live:
                req.future._reject(e)
            return None
        tracer = get_tracer()
        if tracer.enabled:
            # the assembly half of the overlap picture (obs/report's
            # serve section pairs this with the serve-batch span to
            # show assembly-vs-device busy fractions)
            tracer.emit(
                "serve-assemble", rid=rid, n=len(live),
                expired=missed, duration_s=clock.now() - t_asm,
                queue_delay_s=delay,
            )
        return _Staged(pending, live, missed, len(batch), t0, delay)

    def _completion_loop(self, rid: int, q: _SubmissionQueue,
                         chan: _PipelineChannel) -> None:
        """Completion stage (thread `serve-cpl-r{rid}`): finish the
        in-flight round (`finish_mut_batch` — the device replay /
        fused readback), signal `device_done` (the assembly stage may
        begin the next round NOW), then run the durable-ack barrier,
        resolve futures, fire `batch_done` and accounting. A round
        that dies here is post-append by construction
        (`maybe_executed=True`); the replica is retired BEFORE
        `round_done(exc)` wakes the assembly stage, so every observer
        finds the failover in motion — and a round the assembly began
        during our delivery is drained and torn down with the same
        post-append honesty."""
        while True:
            staged = chan.take()
            if staged is None:
                return
            try:
                self._complete(rid, q, staged, chan)
            except _ReplicaDown as down:
                self._fail_replica(rid, q, down.cause)
                for req in down.pending:
                    req.future._reject(ReplicaFailed(
                        rid, down.cause,
                        maybe_executed=down.maybe_executed,
                    ))
                stale = chan.drain_slot()
                if stale is not None:
                    # begun (appended) while round N was mid-delivery;
                    # nobody will finish it — post-append teardown
                    self._abort_staged(rid, q, stale, down.cause)
                chan.round_done(down.cause)
                return
            except Exception as e:  # pragma: no cover - last resort
                logger.exception(
                    "serve completion r%d: unexpected failure", rid
                )
                for req in staged.live:
                    req.future._reject(e)
                q.batch_done(0, 0, staged.taken)
                if self.cfg.failover:
                    self._fail_replica(rid, q, e)
                    stale = chan.drain_slot()
                    if stale is not None:
                        self._abort_staged(rid, q, stale, e)
                    chan.round_done(e)
                    return
                chan.round_done()
                continue

    def _complete(self, rid: int, q: _SubmissionQueue,
                  staged: _Staged, chan: _PipelineChannel) -> None:
        """One completion pass: post-append injection site, finish the
        round, release the assembly stage (`device_done`), shared
        delivery tail (`_finish_delivery`: barrier, second deadline
        sweep, future resolution, accounting)."""
        live, missed, taken = staged.live, staged.missed, staged.taken
        try:
            # post-append injection site: the round is begun — a kill
            # here loses responses, never ops (maybe_executed=True)
            fault_hook("serve-complete", rid, self._nr)
            resps = self._nr.finish_mut_batch(staged.pending)
        except Exception as e:
            q.batch_done(0, missed, taken)
            logger.exception(
                "serve completion r%d: finish of %d failed", rid,
                len(live)
            )
            # release the wrapper's in-flight slot: when the failure
            # struck BEFORE finish_mut_batch (the serve-complete
            # injection site) the begun round is still registered, and
            # a restarted worker's first begin would refuse forever.
            # Idempotent — a no-op when finish's own cleanup already
            # ran (or fence_replica's crash semantics will).
            abort = getattr(self._nr, "abort_mut_batch", None)
            if abort is not None:
                abort(staged.pending)
            if self.cfg.failover:
                raise _ReplicaDown(
                    e, live, maybe_executed=True
                ) from e
            for req in live:
                req.future._reject(e)
            # non-failover: the replica keeps serving — release the
            # assembly stage (the round left flight unsuccessfully;
            # without this the channel stays busy and every later
            # submission wedges in wait_clear)
            chan.round_done()
            return
        # the overlap release point: the wrapper slot is free and the
        # responses are in hand — everything below is host-only work
        # that round N+1's assembly (and device work) runs under
        chan.device_done()
        self._finish_delivery(rid, q, live, missed, taken, resps,
                              staged.t0, staged.delay)
