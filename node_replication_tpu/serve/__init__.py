"""serve/: concurrent batching frontend over the replicated log.

The serving layer (ISSUE 3): many OS-thread clients submit ops through
bounded per-replica admission queues; one elected worker per replica
drains its queue into an adaptive batch and executes it as a single
flat-combining round (`execute_mut_batch` on the wrapper, under the
reentrant combiner lock). Production edges — admission control with
typed `Overloaded` shedding, per-request deadlines, client
retry-with-backoff, graceful drain — live here; the replication core
stays untouched underneath.

    from node_replication_tpu.serve import ServeFrontend, ServeConfig

    with ServeFrontend(nr, ServeConfig(queue_depth=128)) as fe:
        fut = fe.submit((HM_PUT, k, v), rid=0)
        value = fe.read((HM_GET, k), rid=0)
        ok = fut.result(timeout=1.0)
"""

from node_replication_tpu.serve.client import (
    CircuitBreaker,
    RetryPolicy,
    call_with_retry,
)
from node_replication_tpu.serve.errors import (
    CircuitOpen,
    DeadlineExceeded,
    FrontendClosed,
    NotPrimary,
    Overloaded,
    ReplicaFailed,
    ServeError,
    ShardUnavailable,
    StaleRead,
    TxnAborted,
    TxnConflict,
    TxnInDoubt,
    WrongShard,
)
from node_replication_tpu.serve.frontend import (
    ServeConfig,
    ServeFrontend,
)
from node_replication_tpu.serve.future import ServeFuture
from node_replication_tpu.serve.overload import (
    BULK,
    CRITICAL,
    NORMAL,
    LagSource,
    OverloadConfig,
    OverloadGovernor,
)

__all__ = [
    "BULK",
    "CRITICAL",
    "CircuitBreaker",
    "CircuitOpen",
    "DeadlineExceeded",
    "FrontendClosed",
    "LagSource",
    "NORMAL",
    "NotPrimary",
    "OverloadConfig",
    "OverloadGovernor",
    "Overloaded",
    "ReplicaFailed",
    "RetryPolicy",
    "ServeConfig",
    "ServeError",
    "ServeFrontend",
    "ServeFuture",
    "ShardUnavailable",
    "StaleRead",
    "TxnAborted",
    "TxnConflict",
    "TxnInDoubt",
    "WrongShard",
    "call_with_retry",
]
