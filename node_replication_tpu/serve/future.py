"""Per-request future: the response handle `ServeFrontend.submit` returns.

A deliberately small, allocation-light future (the stdlib
`concurrent.futures.Future` carries executor/cancel machinery the serve
path never uses). One request = one future = exactly one resolution —
the frontend resolves it with the combiner response or rejects it with
a typed error (`serve/errors.py`), never both, never twice.

Memory ordering: `_resolve`/`_reject` write the payload under the
condition's lock and publish with `notify_all`; `result()` waits on the
same condition, so the woken read observes a fully-written payload
(the `queue.Queue`/`concurrent.futures` idiom). Timed waits route
through the injectable clock (`utils/clock.py`), so a simulated run
(`sim/`) resolves result timeouts in virtual time.

Done-callbacks run on the WORKER thread that resolves the future (or
inline on the caller when added after resolution), so they must never
block — machine-checked by the nrlint `blocking-in-handler` rule.
"""

from __future__ import annotations

import logging
import threading

from node_replication_tpu.analysis.locks import make_condition
from typing import Any, Callable

from node_replication_tpu.utils.clock import get_clock

logger = logging.getLogger("node_replication_tpu")


class ServeFuture:
    """Write-once response slot for one submitted op."""

    __slots__ = (
        "_cond", "_done", "_value", "_exc", "_callbacks",
        "rid", "deadline", "t_submit", "t_done",
    )

    def __init__(self, rid: int, deadline: float | None = None):
        self._cond = make_condition("ServeFuture._cond")
        self._done = False
        self._value: Any = None
        self._exc: BaseException | None = None
        self._callbacks: list[Callable[["ServeFuture"], None]] = []
        #: replica the request was admitted on
        self.rid = rid
        #: absolute monotonic deadline (None = no deadline)
        self.deadline = deadline
        #: monotonic admission stamp (set by the frontend at enqueue)
        self.t_submit = get_clock().now()
        #: monotonic resolution stamp (None until done)
        self.t_done: float | None = None

    # ------------------------------------------------------------ caller API

    def done(self) -> bool:
        return self._done  # GIL-atomic flag read

    def _wait_done(self, timeout: float | None) -> bool:
        clock = get_clock()
        t_end = None if timeout is None else clock.now() + timeout
        with self._cond:
            while not self._done:
                rem = None if t_end is None else t_end - clock.now()
                if rem is not None and rem <= 0:
                    return False
                clock.wait(self._cond, rem)
            return True

    def result(self, timeout: float | None = None):
        """Block until resolved and return the response (or raise the
        typed rejection). `timeout` bounds THIS wait only — it is not
        the request deadline, which the frontend enforces queue-side."""
        if not self._wait_done(timeout):
            raise TimeoutError(
                f"response still pending after {timeout}s "
                f"(request deadline is enforced by the frontend)"
            )
        if self._exc is not None:
            raise self._exc
        return self._value

    def exception(self, timeout: float | None = None) -> BaseException | None:
        """Block until resolved; return the rejection (None on success)."""
        if not self._wait_done(timeout):
            raise TimeoutError(f"response still pending after {timeout}s")
        return self._exc

    @property
    def latency_s(self) -> float | None:
        """Admission-to-resolution latency (None until resolved)."""
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    def add_done_callback(
        self, fn: Callable[["ServeFuture"], None]
    ) -> None:
        """Run `fn(future)` when the future resolves — on the resolving
        worker thread, or inline right now if already resolved. Handlers
        must not block (nrlint `blocking-in-handler`); exceptions are
        logged and swallowed so one bad handler cannot kill the batch
        loop."""
        run_now = False
        with self._cond:
            if self._done:
                run_now = True
            else:
                self._callbacks.append(fn)
        if run_now:
            self._run_callback(fn)

    # ---------------------------------------------------------- frontend API

    def _finish(self, value: Any, exc: BaseException | None) -> bool:
        """Resolve exactly once; returns False if already resolved
        (late resolutions — e.g. a drain racing a deadline sweep — are
        dropped, first writer wins)."""
        with self._cond:
            if self._done:
                return False
            self._value = value
            self._exc = exc
            self.t_done = get_clock().now()
            cbs = self._callbacks
            self._callbacks = []
            self._done = True
            self._cond.notify_all()
        for fn in cbs:
            self._run_callback(fn)
        return True

    def _resolve(self, value: Any) -> bool:
        return self._finish(value, None)

    def _reject(self, exc: BaseException) -> bool:
        return self._finish(None, exc)

    def _run_callback(self, fn) -> None:
        try:
            fn(self)
        except Exception:
            logger.exception("serve done-callback raised; ignored")
