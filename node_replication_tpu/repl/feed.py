"""Replication feed: the transport between a primary's WAL and its
follower fleet.

A `Feed` carries the WAL's record stream — the same position-chained
`(pos, opcodes, args)` batches `durable/wal.py` frames on disk — from
the shipper (`repl/shipper.py`) to any number of followers
(`repl/follower.py`), each tracking its own read cursor. The transport
is abstracted so tests are hermetic: `DirectoryFeed` is the bundled
shared-disk implementation, a directory of one CRC-framed message file
per shipped record, which models a network feed faithfully (messages
can arrive torn, duplicated, or with gaps) while staying a pure-stdlib
filesystem exchange any two local processes can share. Its cross-host
twin is `repl/transport.py`: `FeedServer` serves any feed-shaped
source over TCP and `SocketFeed` implements this same read interface
on the far end, so followers (and relays, `repl/relay.py`) on other
hosts consume the identical stream under the identical delivery
rules.

Message format (little-endian): file `rec-<pos:020d>.msg` holds one
record `u32 length | u32 crc32(payload) | payload` where the payload is
`int64 epoch | int64 pos | int32 count` followed by `opcodes
int32[count]` and `args int32[count * arg_width]`. Naming messages by
their starting position makes log order lexicographic order AND makes
re-shipping idempotent: a shipper that resumes (or a promoted primary
that re-publishes an overlapping batch) overwrites the same name
rather than forking history.

Delivery edge cases, each with a defined rule:

- **torn tail** — a message whose frame is incomplete (the writer was
  killed mid-`publish`). `poll` stops BEFORE it without error (it may
  still be in flight); a shipper that resumes re-publishes over it.
  Ship-before-ack (`repl/shipper.py:barrier`) means nothing torn was
  ever acked, so dropping it at promotion loses no acknowledged write
  — the same torn-tail reasoning `durable/recovery.py` applies to the
  WAL itself.
- **duplicate delivery** — a message whose records the follower has
  already applied; the follower skips it idempotently
  (`repl.duplicate_records`).
- **gap** — a message starting PAST the follower's cursor with nothing
  in between (the feed was pruned beyond this follower, or files were
  lost): typed `FeedGapError` carrying both positions; the follower
  needs a re-seed, never a silent skip.
- **corruption** — a COMPLETE message with a bad CRC below the feed's
  readable tail: `FeedCorruptError`, never silently dropped history.

Epoch fencing: the feed carries an `EPOCH` file (durably published:
tmp + fsync + rename + dir fsync). `publish` re-reads it and refuses
records stamped with an older epoch (`EpochFencedError`) — after a
promotion bumps the epoch (`fence`), a zombie primary's late records
are rejected at the transport. Followers enforce the same monotonicity
on the apply side (`repl/follower.py`): once a record of epoch E is
applied, lower-epoch records are fenced, closing the race where a
zombie's write lands between the epoch check and the file write.
"""

from __future__ import annotations

import bisect
import dataclasses
import os
import re
import struct
import zlib

import numpy as np

from node_replication_tpu.durable.wal import durable_publish
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.trace import get_tracer

_MSG_RE = re.compile(r"^rec-(\d{20})\.msg$")
_MSG_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
_MSG_PREFIX = struct.Struct("<qqi")  # epoch, pos, count

#: sanity bound on one message payload (mirrors the WAL's frame bound)
MAX_PAYLOAD_BYTES = 1 << 26

EPOCH_FILE = "EPOCH"
HEARTBEAT_FILE = "HEARTBEAT"


class FeedError(RuntimeError):
    """Replication-feed usage/IO failure."""


class FeedGapError(FeedError):
    """The next available feed record starts past the follower's
    cursor: positions `[expected, got)` are on no message this feed
    still holds. The follower cannot continue by replay alone — it
    needs a re-seed (snapshot transfer) — so the gap is a typed,
    position-carrying error, never a silent skip."""

    def __init__(self, expected: int, got: int):
        super().__init__(
            f"feed gap: next record starts at {got} but the follower "
            f"has applied only up to {expected} (positions "
            f"[{expected}, {got}) are missing)"
        )
        self.expected = expected
        self.got = got


class FeedCorruptError(FeedError):
    """A complete feed message failed validation below the readable
    tail — bit rot or a framing bug, not an in-flight write."""

    def __init__(self, path: str, pos: int, detail: str):
        super().__init__(
            f"corrupt feed message {path} (position {pos}): {detail}"
        )
        self.path = path
        self.pos = pos
        self.detail = detail


class EpochFencedError(FeedError):
    """A publish carried an epoch older than the feed's — the writer
    is a fenced (zombie) primary; its record was NOT written."""

    def __init__(self, epoch: int, current: int):
        super().__init__(
            f"publish fenced: record epoch {epoch} < feed epoch "
            f"{current} (a newer primary owns this feed)"
        )
        self.epoch = epoch
        self.current = current


@dataclasses.dataclass(frozen=True)
class FeedRecord:
    """One shipped batch: `count` ops at logical `pos`, stamped with
    the shipping primary's `epoch`."""

    epoch: int
    pos: int
    opcodes: np.ndarray  # int32[count]
    args: np.ndarray  # int32[count, arg_width]

    @property
    def count(self) -> int:
        return int(self.opcodes.shape[0])

    def ops(self) -> list[tuple]:
        """The batch as host `(opcode, *args)` tuples — the shape the
        follower replays through `_append_and_replay`."""
        return [
            (int(self.opcodes[i]), *(int(a) for a in self.args[i]))
            for i in range(self.count)
        ]


def _message_name(pos: int) -> str:
    return f"rec-{int(pos):020d}.msg"


class DirectoryFeed:
    """Shared-directory feed: one CRC-framed message file per record.

    One writer (the current primary's shipper) and any number of
    readers; readers are cursor-based and independent. All methods are
    stateless over the directory (safe to call from several threads /
    processes), except that `publish` assumes a single live writer —
    exactly the invariant epoch fencing exists to enforce.
    """

    def __init__(self, directory: str, arg_width: int = 3,
                 fsync: bool = False):
        self.dir = directory
        self.arg_width = int(arg_width)
        # fsync per message: off by default — the feed's durability
        # story is the follower's own WAL (applied records are
        # re-journaled there); flipping this on makes the feed itself
        # a crash-durable artifact at a per-publish fsync cost
        self.fsync = bool(fsync)
        os.makedirs(self.dir, exist_ok=True)
        reg = get_registry()
        self._m_published = reg.counter("repl.published_records")
        self._m_fenced_pub = reg.counter("repl.fenced_publishes")

    # ------------------------------------------------------------ epoch

    def epoch(self) -> int:
        """The feed's current fencing epoch (0 when never fenced)."""
        try:
            with open(os.path.join(self.dir, EPOCH_FILE), "rb") as f:
                return int(f.read().decode("ascii").strip() or 0)
        except FileNotFoundError:
            return 0

    def fence(self, epoch: int) -> int:
        """Raise the feed's epoch (promotion, `repl/promote.py`).
        Durably published (tmp + fsync + rename + dir fsync) so a
        fence survives a crash of the promoting process. Refuses to
        move backwards. Returns the new epoch."""
        epoch = int(epoch)
        current = self.epoch()
        if epoch <= current:
            raise FeedError(
                f"fence epoch {epoch} must exceed current {current}"
            )
        durable_publish(os.path.join(self.dir, EPOCH_FILE),
                        str(epoch).encode("ascii"))
        get_tracer().emit("repl-fence", epoch=epoch, previous=current)
        return epoch

    # ---------------------------------------------------------- publish

    def publish(self, epoch: int, pos: int, opcodes, args) -> None:
        """Write one record at `pos` stamped with `epoch`. Re-reads
        the fence file first: a stale epoch raises `EpochFencedError`
        and writes NOTHING — a zombie primary cannot extend the feed.
        The message file is written in place (no tmp+rename) so a
        mid-write kill leaves a torn tail for `poll`'s torn-tail rule,
        exactly like a half-shipped network frame."""
        epoch = int(epoch)
        current = self.epoch()
        if epoch < current:
            self._m_fenced_pub.inc()
            get_tracer().emit("repl-fenced-publish", epoch=epoch,
                              current=current, pos=int(pos))
            raise EpochFencedError(epoch, current)
        opcodes = np.ascontiguousarray(opcodes, np.int32)
        args = np.ascontiguousarray(args, np.int32)
        n = int(opcodes.shape[0])
        payload = (
            _MSG_PREFIX.pack(epoch, int(pos), n)
            + opcodes.tobytes() + args.tobytes()
        )
        frame = _MSG_HEADER.pack(len(payload),
                                 zlib.crc32(payload)) + payload
        path = os.path.join(self.dir, _message_name(pos))
        with open(path, "wb") as f:
            f.write(frame)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        self._m_published.inc()

    def publish_record(self, epoch: int, rec) -> None:
        """Publish a `durable/wal.py:WalRecord` (the shipper's unit)."""
        self.publish(epoch, rec.pos, rec.opcodes, rec.args)

    # ------------------------------------------------------------- read

    def _messages(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.dir):
            m = _MSG_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.dir, name)))
        out.sort()
        return out

    def _read_message(self, pos: int, path: str):
        """Decode one message file; returns a `FeedRecord`, or None
        when the frame is incomplete (torn / still being written).
        A complete frame that fails CRC or shape checks raises
        `FeedCorruptError`."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return None  # pruned between listing and read
        if len(data) < _MSG_HEADER.size:
            return None
        length, crc = _MSG_HEADER.unpack_from(data, 0)
        if length < _MSG_PREFIX.size or length > MAX_PAYLOAD_BYTES:
            raise FeedCorruptError(
                path, pos, f"implausible message length {length}"
            )
        body = data[_MSG_HEADER.size:_MSG_HEADER.size + length]
        if len(body) < length:
            return None  # torn tail: the write never finished
        if zlib.crc32(body) != crc:
            raise FeedCorruptError(path, pos, "payload CRC mismatch")
        epoch, rpos, count = _MSG_PREFIX.unpack_from(body, 0)
        want = _MSG_PREFIX.size + 4 * count * (1 + self.arg_width)
        if count < 1 or length != want or rpos != pos:
            raise FeedCorruptError(
                path, pos,
                f"message shape invalid (pos {rpos}, count {count}, "
                f"length {length} != {want})",
            )
        opcodes = np.frombuffer(body, np.int32, count,
                                _MSG_PREFIX.size)
        args = np.frombuffer(
            body, np.int32, count * self.arg_width,
            _MSG_PREFIX.size + 4 * count,
        ).reshape(count, self.arg_width)
        return FeedRecord(int(epoch), int(rpos), opcodes.copy(),
                          args.copy())

    def poll(self, start: int = 0) -> list[FeedRecord]:
        """Readable records covering positions >= `start`, in order.
        Includes a record straddling `start` whole (the follower
        slices the overlap — its dedup path). Stops cleanly at the
        first incomplete (in-flight / torn) message; a corrupt
        complete message below that point raises. Gap DETECTION is the
        follower's job — `poll` reports what is readable, the follower
        compares against its cursor."""
        msgs = self._messages()
        # skip messages wholly below `start` WITHOUT decoding them:
        # positions chain densely, so only the last message starting
        # at or below `start` can straddle it — everything earlier is
        # history. The listing itself stays O(files in the feed);
        # `prune()` is what bounds that.
        lo = max(0, bisect.bisect_right([p for p, _ in msgs],
                                        int(start)) - 1)
        out: list[FeedRecord] = []
        for pos, path in msgs[lo:]:
            rec = self._read_message(pos, path)
            if rec is None:
                break  # in-flight tail: nothing past it is applicable
            if rec.pos + rec.count > start:
                out.append(rec)
        return out

    def tail_pos(self) -> int:
        """End position of the newest READABLE record (0 when empty) —
        the follower's staleness reference: `max_lag_pos` bounds are
        measured against this. Scans backwards past a torn tail."""
        msgs = self._messages()
        for pos, path in reversed(msgs):
            try:
                rec = self._read_message(pos, path)
            except FeedCorruptError:
                rec = None
            if rec is not None:
                return rec.pos + rec.count
        return 0

    # ------------------------------------------------------------ prune

    def prune(self, floor: int) -> int:
        """Delete messages whose records lie wholly below `floor`
        (operator/manager entry — a pruned follower cursor below the
        floor turns into `FeedGapError`, by design). Returns the
        number of messages removed."""
        removed = 0
        msgs = self._messages()
        for i, (pos, path) in enumerate(msgs):
            nxt = msgs[i + 1][0] if i + 1 < len(msgs) else None
            if nxt is None or nxt > floor:
                break
            os.remove(path)
            removed += 1
        return removed

    # -------------------------------------------------------- heartbeat

    def write_heartbeat(self, value: str) -> None:
        """Publish the liveness beacon (the shipper — or a relay
        forwarding its upstream's beacon — writes a monotonically
        changing value each loop). Routed through the hardened publish
        path with `fsync=False`: the atomic rename means a reader (or
        a downstream `FeedServer` re-serving the value) can never
        observe a torn beacon — a crashed relay mid-write leaves the
        previous complete value — while skipping the per-beacon disk
        flush a lost-on-crash beacon does not need."""
        durable_publish(os.path.join(self.dir, HEARTBEAT_FILE),
                        value.encode("utf-8"), fsync=False)

    def read_heartbeat(self) -> str | None:
        try:
            with open(os.path.join(self.dir, HEARTBEAT_FILE)) as f:
                return f.read()
        except FileNotFoundError:
            return None
