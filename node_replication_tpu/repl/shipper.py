"""WAL shipper: streams a primary's write-ahead log into a feed.

The primary half of log-shipping replication (`repl/`): a background
thread follows the primary's `durable/wal.py:WriteAheadLog` — closed
segments first, then a tailing read of the active segment, both
through the WAL's own position-ordered `records()` reader — and
publishes every fsynced record into a `repl/feed.py:Feed`, stamped
with the primary's epoch. Only records at or below `durable_tail` are
shipped: the feed never holds an op the primary could still lose, so
follower state is always a prefix-fold of durable primary history.

Reclamation safety: the shipper PINS the WAL at its ship cursor
(`WriteAheadLog.set_pin`) and advances the pin only after the record
is published, so segment reclamation (snapshot floor + GC head,
`maybe_reclaim`) can never delete an unshipped segment out from under
the follower fleet.

Ship-before-ack (`barrier`): installed as the serve frontend's
`ack_barrier`, a durable-ack batch resolves only once the feed holds
its records — semi-synchronous replication. An ack then implies BOTH
"on the primary's disk" and "visible to the follower feed", which is
what makes promotion lossless for acked writes: the most-advanced
follower provably holds every acknowledged op
(`bench.py --follower`'s zero-lost-acks gate rests on exactly this).

Liveness: every ship loop iteration refreshes the feed's heartbeat
beacon (a monotonically increasing counter — the promotion watcher
detects CHANGE with its own monotonic clock, so no wall-clock
coordination is needed across processes). A shipper failure is never
swallowed: the error is recorded for `barrier` callers to observe
(acks stop — correct, they can no longer be replicated), reported to
the optional `fault/health.py:HealthTracker`, and counted.
"""

from __future__ import annotations

import logging
import threading

from node_replication_tpu.analysis.locks import (
    make_condition,
    make_lock,
)

from node_replication_tpu.fault.inject import fault_hook
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer, pos_sampled

logger = logging.getLogger("node_replication_tpu")

#: WAL reclamation pin-name PREFIX (`WriteAheadLog.set_pin`). Each
#: shipper pins under its own `ship:<n>` key — pins are a shared
#: namespace on the WAL, and a fan-out primary can run several
#: consumers at once (two shippers, a snapshot transfer's
#: `snapshot-server:<n>` pin, `repl/transport.py`), so one consumer's
#: `clear_pin` must never release another's reclaim floor.
SHIP_PIN = "ship"

_pin_seq = 0
_pin_seq_lock = make_lock("shipper._pin_seq_lock")


def _next_pin_name() -> str:
    global _pin_seq
    with _pin_seq_lock:
        n = _pin_seq
        _pin_seq += 1
    return f"{SHIP_PIN}:{n}"


class ShipError(RuntimeError):
    """The shipper cannot (or can no longer) replicate — construction
    found an unshippable WAL, or `barrier` observed a dead/stopped
    ship loop. Acks gated on the barrier fail with this."""


class ReplicationShipper:
    """Follows a WAL and publishes its durable records into a feed.

    One shipper per primary per feed. `barrier(pos)` is the
    ship-before-ack hook for `ServeFrontend.ack_barrier`; `stats()`
    exposes the cursor/lag for ops tooling. Thread-safe: the ship
    loop, barrier callers (serve workers), and stop() all synchronize
    on one condition.
    """

    def __init__(
        self,
        wal,
        feed,
        epoch: int | None = None,
        poll_s: float = 0.002,
        heartbeat_interval_s: float = 0.05,
        barrier_timeout_s: float = 30.0,
        health=None,
        health_rid: int = 0,
        auto_start: bool = True,
        pin_name: str | None = None,
    ):
        self._wal = wal
        self._feed = feed
        #: this shipper's own WAL reclamation pin key (unique per
        #: instance by default; see `SHIP_PIN`)
        self.pin_name = pin_name or _next_pin_name()
        #: this primary's fencing epoch (stamped on every record). A
        #: fresh primary adopts the feed's current epoch; a promoted
        #: one passes the bumped epoch explicitly.
        self.epoch = feed.epoch() if epoch is None else int(epoch)
        self.poll_s = float(poll_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.barrier_timeout_s = float(barrier_timeout_s)
        #: optional `fault/health.py:HealthTracker` + the rid the
        #: shipper's failures are attributed to (the primary's slot)
        self.health = health
        self.health_rid = int(health_rid)

        # resume where the feed ends: re-publishing from 0 would be
        # idempotent (pos-keyed messages overwrite) but wasteful
        self._cursor = feed.tail_pos()
        if self._cursor < wal.base:
            raise ShipError(
                f"feed ends at {self._cursor} but the WAL has "
                f"reclaimed up to {wal.base}: positions "
                f"[{self._cursor}, {wal.base}) are unshippable — "
                f"re-seed the feed (the ship pin prevents this on a "
                f"live attachment)"
            )
        wal.set_pin(self.pin_name, self._cursor)

        self._cond = make_condition("ReplicationShipper._cond")
        self._published = self._cursor
        self._error: BaseException | None = None
        self._stop = False
        self._hb_seq = 0
        self._hb_due = 0.0  # monotonic deadline for the next beacon

        reg = get_registry()
        self._m_records = reg.counter("repl.shipped_records")
        self._m_ops = reg.counter("repl.shipped_ops")
        self._m_errors = reg.counter("repl.ship_errors")
        self._g_lag_pos = reg.gauge("repl.ship_lag_pos")
        self._g_lag_bytes = reg.gauge("repl.ship_lag_bytes")

        self._thread = threading.Thread(
            target=self._ship_loop, name="repl-shipper", daemon=True,
        )
        if auto_start:
            self.start()

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        if not self._thread.is_alive() and not self._thread.ident:
            self._thread.start()

    def stop(self, clear_pin: bool = True,
             timeout: float | None = 5.0) -> None:
        """Stop the ship loop (joins it) and, by default, release the
        WAL reclamation pin — call with `clear_pin=False` to keep
        unshipped segments protected for a successor shipper."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.ident:
            self._thread.join(timeout)
        if clear_pin:
            self._wal.clear_pin(self.pin_name)

    # -------------------------------------------------------- ship loop

    def _ship_loop(self) -> None:
        while True:
            try:
                self._ship_once()
            # a dead shipper must never be silent: the failure is
            # recorded for barrier callers (durable acks stop) and
            # reported to the health tracker when one is attached
            except Exception as e:
                self._record_failure(e)
                return
            with self._cond:
                if self._stop:
                    return
                if self._error is None and \
                        self._cursor >= self._wal.durable_tail:
                    get_clock().wait(self._cond, self.poll_s)

    def _ship_once(self) -> None:
        fault_hook("ship", -1, self)
        self._maybe_heartbeat()
        target = self._wal.durable_tail
        # nrcheck: unshared — ship thread is _cursor's only writer
        cur = self._cursor
        if cur >= target:
            return
        tracer = get_tracer()
        aw = getattr(self._wal, "arg_width", 3)
        for rec in self._wal.records(start=cur):
            if rec.pos >= target:
                break  # past the fsync boundary: not yet shippable
            self._feed.publish_record(self.epoch, rec)
            end = rec.pos + rec.count
            with self._cond:
                self._cursor = end
                self._published = end
                self._cond.notify_all()
            # pin AFTER publish: reclamation may now pass this record
            self._wal.set_pin(self.pin_name, end)
            self._m_records.inc()
            self._m_ops.inc(rec.count)
            lag = max(0, self._wal.durable_tail - end)
            self._g_lag_pos.set(lag)
            # payload bytes per op are fixed by the arg width (the
            # WAL's dense int32 framing), so position lag converts
            # exactly
            self._g_lag_bytes.set(lag * 4 * (1 + aw))
            # per-record hop event, thinned by the fleet sampling
            # modulus (NR_TPU_TRACE_SAMPLE) so tracing stays
            # affordable under load; sampling is a pure function of
            # `pos`, so every process narrates the SAME records
            if tracer.enabled and pos_sampled(rec.pos):
                tracer.emit("repl-ship", pos=rec.pos, n=rec.count,
                            epoch=self.epoch, lag=lag)
            self._maybe_heartbeat()

    def _maybe_heartbeat(self) -> None:
        now = get_clock().now()
        if now < self._hb_due:
            return
        self._hb_due = now + self.heartbeat_interval_s
        self._hb_seq += 1
        self._feed.write_heartbeat(
            # nrcheck: unshared — ship thread, own write
            f"{self.epoch} {self._hb_seq} {self._cursor}"
        )

    def _record_failure(self, exc: BaseException) -> None:
        """Surface a ship-loop failure: wake barrier waiters (their
        acks must fail, not hang), count it, report it to the health
        tracker. The sanctioned worker-exception path the nrlint
        `swallowed-worker-exception` sweep recognizes."""
        with self._cond:
            self._error = exc
            self._cond.notify_all()
        self._m_errors.inc()
        get_tracer().emit("repl-ship-error", epoch=self.epoch,
                          # nrcheck: unshared — ship thread, own write
                          cursor=self._cursor,
                          cause=type(exc).__name__)
        logger.exception("replication shipper failed at cursor %d",
                         # nrcheck: unshared — ship thread, own write
                         self._cursor)
        if self.health is not None:
            self.health.report_worker_exception(self.health_rid, exc)

    # ---------------------------------------------------------- barrier

    def barrier(self, pos: int, timeout: float | None = None) -> None:
        """Block until the feed holds every record below `pos` — the
        ship-before-ack hook (`ServeFrontend.ack_barrier`). Raises
        `ShipError` when the ship loop has died, was stopped, or the
        timeout (default `barrier_timeout_s`) expires; the serve layer
        maps that to its maybe_executed rejection (the ops are in the
        log and WILL replay; they were just never replicated, so an
        ack would overpromise)."""
        pos = int(pos)
        if timeout is None:
            timeout = self.barrier_timeout_s
        clock = get_clock()
        t_end = clock.now() + timeout
        with self._cond:
            self._cond.notify_all()  # kick the ship loop's poll wait
            while self._published < pos:
                if self._error is not None:
                    raise ShipError(
                        f"shipper failed; records below {pos} are not "
                        f"replicated"
                    ) from self._error
                if self._stop:
                    raise ShipError("shipper stopped")
                rem = t_end - clock.now()
                if rem <= 0:
                    raise ShipError(
                        f"ship barrier timed out after {timeout}s "
                        f"(published {self._published} < {pos})"
                    )
                clock.wait(self._cond, min(rem, 0.05))

    # ------------------------------------------------------------ state

    @property
    def cursor(self) -> int:
        """Next unshipped logical position."""
        # nrcheck: unshared — lock-free poll; one int load
        return self._cursor

    @property
    def error(self) -> BaseException | None:
        # nrcheck: unshared — lock-free poll; one reference load
        return self._error

    def lag(self) -> int:
        """Positions fsynced on the primary but not yet shipped."""
        # nrcheck: unshared — lock-free poll; approximate by design
        return max(0, self._wal.durable_tail - self._cursor)

    def install_backpressure(self, frontend, low: int = 512,
                             high: int = 4096) -> None:
        """Feed this shipper's lag into the frontend's admission
        controller (`serve/overload.py:LagSource` watermarks): between
        `low` and `high` admission stops growing; at/above `high` it
        shrinks multiplicatively every round. Combined with
        `barrier` installed as the frontend's `ack_barrier`
        (ship-before-ack), this closes the loop the overload plane
        promises — semi-sync replication can never build an unbounded
        ship backlog, because the primary slows admission instead.
        Requires the frontend's overload plane
        (`ServeConfig(overload=...)`); raises otherwise."""
        frontend.add_backpressure_source("ship", self.lag, low, high)

    def stats(self) -> dict:
        with self._cond:
            return {
                "epoch": self.epoch,
                "cursor": self._cursor,
                "published": self._published,
                "lag_pos": self.lag(),
                "stopped": self._stop,
                "error": (
                    None if self._error is None
                    else f"{type(self._error).__name__}: {self._error}"
                ),
            }
