"""repl/: WAL-shipping follower fleets — read scale-out, bounded
staleness, measured-RTO promotion.

The replication plane (ISSUE 6), and the first multi-process
subsystem: the segmented write-ahead log (`durable/wal.py`) is
already a complete, CRC-framed, position-chained replication stream,
so a **shipper** streams it (closed segments + a tailing feed of the
active one) into a transport-abstracted **feed**; **followers** in
other processes replay it through the same deterministic combiner
protocol — bit-identical state at every common position — and serve
reads at a bounded-staleness cursor through a read-only
`ServeFrontend`. Ship-before-ack (`shipper.barrier` as the frontend's
`ack_barrier`) makes acks survive primary loss; on primary death the
**promotion** path (heartbeat watch on `fault/`'s health machine)
elects the most-advanced follower, drains the feed under the
torn-tail rules, fences the dead primary's epoch so zombie records
are rejected, and re-homes durable-ack write serving — classic
log-shipping primary/replica architecture built from parts the repo
already proves.

    feed = DirectoryFeed(shared_dir)
    shipper = ReplicationShipper(primary.wal, feed)   # on the primary
    frontend.ack_barrier = shipper.barrier            # ship-before-ack

    f = Follower(dispatch, feed, directory=my_dir)    # other process
    v = f.read((HM_GET, k), max_lag_pos=64)           # bounded staleness

    mgr = PromotionManager(feed, [f])
    mgr.start()                                       # heartbeat watch
    report = mgr.wait()                               # measured RTO

Cross-host (ISSUE 12): `transport.py` carries the same stream over
TCP — `FeedServer` serves any feed-shaped source (plus snapshots for
cold-follower bootstrap), `SocketFeed` is the far end's drop-in feed —
and `relay.py`'s `RelayNode` is a feed-of-feeds interior node, so a
1→R→N tree ships each record once per edge instead of N× from the
primary:

    srv = FeedServer(feed, snapshot_dir=primary_dir)  # on the primary
    up = SocketFeed(*srv.address, arg_width=aw)       # another host
    relay = RelayNode(up, directory=relay_dir, arg_width=aw)
    leaf = SocketFeed(*relay.address, arg_width=aw)
    f = Follower(dispatch, leaf, directory=my_dir)    # bootstraps from
    ...                                               # the snapshot
"""

from node_replication_tpu.repl.feed import (
    DirectoryFeed,
    EpochFencedError,
    FeedCorruptError,
    FeedError,
    FeedGapError,
    FeedRecord,
)
from node_replication_tpu.repl.follower import Follower
from node_replication_tpu.repl.promote import (
    PromotionManager,
    PromotionReport,
)
from node_replication_tpu.repl.relay import RelayNode
from node_replication_tpu.repl.shipper import (
    SHIP_PIN,
    ReplicationShipper,
    ShipError,
)
from node_replication_tpu.repl.transport import (
    FeedServer,
    PipeTransport,
    SocketFeed,
    TransportError,
    make_tree_barrier,
)

__all__ = [
    "DirectoryFeed",
    "EpochFencedError",
    "FeedCorruptError",
    "FeedError",
    "FeedGapError",
    "FeedRecord",
    "FeedServer",
    "Follower",
    "PipeTransport",
    "PromotionManager",
    "PromotionReport",
    "RelayNode",
    "ReplicationShipper",
    "SHIP_PIN",
    "ShipError",
    "SocketFeed",
    "TransportError",
    "make_tree_barrier",
]
