"""Network transport for the replication feed: the cross-host twin of
`repl/feed.py`'s `DirectoryFeed`.

`DirectoryFeed` models a replication stream faithfully but stops at the
filesystem: every follower must share a disk with its primary. This
module carries the SAME record stream over TCP so follower fleets live
on other hosts:

- **`FeedServer`** serves any feed-shaped source — a primary's
  `DirectoryFeed` (populated by `repl/shipper.py`), or a relay's local
  journal (`repl/relay.py`) — plus, over a sidecar exchange, the
  newest durable snapshot from a durability directory, so a cold
  follower bootstraps from `snap-<tail>.npz` instead of replaying the
  whole WAL.
- **`SocketFeed`** is the client: it implements the exact
  `DirectoryFeed` read interface (`poll` / `tail_pos` / `epoch` /
  `read_heartbeat` / `fence`) so `repl/follower.py:Follower` and
  `repl/promote.py:PromotionManager` work unchanged behind it.
- **`PipeTransport`** is the deterministic in-memory twin `sim/` and
  tests drive: the same client semantics (cached state while
  disconnected, duplicate delivery after reconnect) with no sockets
  and no threads.

Wire format (little-endian): every message is one CRC frame
`u32 length | u32 crc32(payload) | payload`, the WAL's own framing
idiom. The first payload byte is the message kind; records travel in
the feed's message-payload encoding (`epoch | pos | count | opcodes |
args`), so a record's bytes are identical on disk and on the wire.

Delivery semantics, mapped onto the feed's rules:

- **torn stream** — a connection dying mid-frame is the wire's torn
  tail: the client discards the partial frame, reconnects, and
  re-polls from its cursor. Nothing is applied from a frame whose CRC
  never validated.
- **reconnect = re-ship** — every poll carries the follower's cursor,
  so a resumed connection simply re-serves from it; records the
  follower already applied are duplicates it skips idempotently
  (`repl.duplicate_records`), the same name-idempotent re-ship
  semantics `DirectoryFeed`'s pos-keyed message files give.
- **gap** — the server reports what its source holds; a record
  starting past the follower's cursor surfaces as the follower's
  typed `FeedGapError`, exactly as on a pruned directory feed.
- **epoch fencing rides the stream** — records carry their epoch;
  `SocketFeed.fence` forwards a promotion fence to the server, which
  fences its SOURCE feed (durably, `EPOCH` publish), so a zombie
  primary's late publishes are rejected at the source with the same
  typed `EpochFencedError` contract.

Transient transport failures are NOT errors to the read path: `poll`
returns nothing, `tail_pos`/`epoch`/`read_heartbeat` answer from the
last connected observation, and the client reconnects on the next
call — a follower behind a flaky link degrades to a lagging follower,
never a dead one. (A frozen cached heartbeat is exactly what lets the
promotion watcher detect a dead upstream.) `fence` and
`fetch_snapshot` DO raise on transport failure: promotion and
bootstrap must never silently half-happen.

Liveness discipline: every socket in this module carries an explicit
timeout — blocking `accept`/`recv` without one would wedge a worker
thread forever on a half-open connection (nrlint rule
`raw-socket-in-worker` enforces this for repl/ thread targets).
"""

from __future__ import annotations

import io
import logging
import os
import socket
import struct
import threading

from node_replication_tpu.analysis.locks import (
    make_condition,
    make_lock,
)
import zlib

import numpy as np

from node_replication_tpu.durable.wal import durable_publish
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.repl.feed import (
    EpochFencedError,
    FeedError,
    FeedGapError,
    FeedRecord,
    MAX_PAYLOAD_BYTES,
)
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer, pos_sampled

logger = logging.getLogger("node_replication_tpu")

_FRAME = struct.Struct("<II")  # payload length, crc32(payload)
_REC_PREFIX = struct.Struct("<qqi")  # epoch, pos, count (feed format)

# ---- message kinds (first payload byte) -------------------------------
_REQ_POLL = 1  # <q start><i max_records>
_REQ_STAT = 2  # (empty)
_REQ_FENCE = 3  # <q epoch><16s fencer token>
_REQ_SNAP = 4  # <q min_pos>

_RSP_RECORDS = 16  # <q tail><q epoch><i hb_len><i nrec> hb recs
_RSP_STAT = 17  # <q tail><q epoch><i hb_len> hb
_RSP_ERROR = 18  # <i code><q a><q b> msg
_RSP_SNAP_META = 19  # <q pos><q size> (pos < 0: nothing newer)
_RSP_SNAP_CHUNK = 20  # raw file bytes
_RSP_SNAP_END = 21  # <q total_bytes>

_ERR_GENERIC = 0
_ERR_FENCED = 1  # a = record epoch, b = current epoch
_ERR_GAP = 2  # a = expected, b = got
_ERR_CORRUPT = 3

_POLL_HDR = struct.Struct("<qi")
_RECORDS_HDR = struct.Struct("<qqii")
_STAT_HDR = struct.Struct("<qqi")
_ERROR_HDR = struct.Struct("<iqq")
_SNAP_META = struct.Struct("<qq")
_Q = struct.Struct("<q")
_I = struct.Struct("<i")

#: snapshot stream chunk size (each chunk is one CRC frame)
SNAP_CHUNK_BYTES = 1 << 18

#: soft cap on one poll response's record bytes — comfortably under
#: the frame bound the client enforces, so a deep backlog streams as
#: several responses instead of one rejected mega-frame
MAX_RESPONSE_BYTES = 1 << 23

#: client-side frame bound: one response may legally carry one
#: maximum-size feed record plus headers
MAX_FRAME_BYTES = MAX_PAYLOAD_BYTES + 4096

#: WAL reclamation pin prefix held while a snapshot transfer streams
SNAPSHOT_PIN = "snapshot-server"


class TransportError(FeedError):
    """A transient wire failure (disconnect, timeout, torn frame).
    The client's cue to reconnect and resume from its cursor — never a
    statement about the data, which is CRC-framed end to end."""


# ==========================================================================
# framing
# ==========================================================================


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (TimeoutError, socket.timeout) as e:
            raise TransportError(f"socket timeout mid-frame: {e}") from e
        except OSError as e:
            raise TransportError(f"socket error: {e}") from e
        if not chunk:
            raise TransportError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    """Write one CRC frame (single `sendall`)."""
    try:
        sock.sendall(
            _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        )
    except (TimeoutError, socket.timeout) as e:
        raise TransportError(f"socket timeout on send: {e}") from e
    except OSError as e:
        raise TransportError(f"socket error on send: {e}") from e


def recv_frame(sock: socket.socket,
               max_bytes: int = MAX_FRAME_BYTES) -> bytes:
    """Read one CRC frame; raises `TransportError` on EOF, timeout, an
    implausible length, or a CRC mismatch — all of which mean "this
    connection is done", not "the feed is corrupt" (the data is intact
    at the source; the client re-polls over a fresh connection)."""
    hdr = _recv_exact(sock, _FRAME.size)
    length, crc = _FRAME.unpack(hdr)
    if length > max_bytes:
        raise TransportError(f"implausible frame length {length}")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) != crc:
        raise TransportError("frame CRC mismatch (torn stream)")
    return payload


def encode_record(rec: FeedRecord) -> bytes:
    """One record in the feed's message-payload encoding."""
    opcodes = np.ascontiguousarray(rec.opcodes, np.int32)
    args = np.ascontiguousarray(rec.args, np.int32)
    return (
        _REC_PREFIX.pack(int(rec.epoch), int(rec.pos), rec.count)
        + opcodes.tobytes() + args.tobytes()
    )


def decode_record(data: bytes, arg_width: int) -> FeedRecord:
    """Inverse of `encode_record` (frame CRC already validated)."""
    epoch, pos, count = _REC_PREFIX.unpack_from(data, 0)
    want = _REC_PREFIX.size + 4 * count * (1 + arg_width)
    if count < 1 or len(data) != want:
        raise TransportError(
            f"record shape invalid (count {count}, {len(data)} bytes "
            f"!= {want})"
        )
    opcodes = np.frombuffer(data, np.int32, count, _REC_PREFIX.size)
    args = np.frombuffer(
        data, np.int32, count * arg_width,
        _REC_PREFIX.size + 4 * count,
    ).reshape(count, arg_width)
    return FeedRecord(int(epoch), int(pos), opcodes.copy(), args.copy())


def _pack_hb(hb: str | None) -> tuple[int, bytes]:
    if hb is None:
        return -1, b""
    raw = hb.encode("utf-8")
    return len(raw), raw


def _error_payload(code: int, a: int, b: int, msg: str) -> bytes:
    return (bytes([_RSP_ERROR]) + _ERROR_HDR.pack(code, a, b)
            + msg.encode("utf-8"))


def _raise_error(payload: bytes) -> None:
    code, a, b = _ERROR_HDR.unpack_from(payload, 1)
    msg = payload[1 + _ERROR_HDR.size:].decode("utf-8", "replace")
    if code == _ERR_FENCED:
        raise EpochFencedError(a, b)
    if code == _ERR_GAP:
        raise FeedGapError(a, b)
    raise FeedError(msg)


# ==========================================================================
# server
# ==========================================================================


class FeedServer:
    """Serves a feed-shaped source (and optionally snapshots) over TCP.

    One server per node; any number of downstream `SocketFeed` clients,
    each on its own connection handled by its own thread. The source
    needs the `DirectoryFeed` read surface (`poll` / `tail_pos` /
    `epoch` / `read_heartbeat`) plus `fence` for promotion forwarding.

        feed = DirectoryFeed(feed_dir)          # shipper publishes here
        srv = FeedServer(feed, snapshot_dir=durability_dir)
        host, port = srv.address                # hand to followers

    `snapshot_dir` (a durability directory holding `snap-<tail>.npz`
    files from `save_durable_snapshot`) enables bootstrap serving; a
    relay passes `snapshot_provider` instead to fetch-and-cache from
    its upstream. `wal=` (the primary only) lets a snapshot transfer
    pin WAL reclamation at the snapshot position under its own
    `snapshot-server:<n>` key while the stream is in flight, so the
    bootstrap window can never be reclaimed out from under the
    fetching follower. `on_fence` (the relay) observes forwarded
    fences AFTER the source accepted them.
    """

    _seq = 0
    _seq_lock = make_lock("FeedServer._seq_lock")

    def __init__(
        self,
        source,
        host: str = "127.0.0.1",
        port: int = 0,
        snapshot_dir: str | None = None,
        snapshot_provider=None,
        wal=None,
        on_fence=None,
        max_records: int = 256,
        accept_timeout_s: float = 0.2,
        io_timeout_s: float = 10.0,
        auto_start: bool = True,
        name: str = "feed-server",
    ):
        if snapshot_dir is not None and snapshot_provider is not None:
            raise ValueError(
                "pass snapshot_dir OR snapshot_provider, not both"
            )
        self.source = source
        self.name = name
        self.snapshot_dir = snapshot_dir
        self._snapshot_provider = snapshot_provider
        self._wal = wal
        self._on_fence = on_fence
        self.max_records = int(max_records)
        self.accept_timeout_s = float(accept_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        with FeedServer._seq_lock:
            self._id = FeedServer._seq
            FeedServer._seq += 1

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self._sock.settimeout(self.accept_timeout_s)
        self.address: tuple[str, int] = self._sock.getsockname()[:2]

        self._lock = make_lock("FeedServer._lock")
        self._cond = make_condition("FeedServer._lock", lock=self._lock)
        self._stop = False
        self._conns: dict[int, socket.socket] = {}
        #: conn id -> highest poll cursor the client has CONFIRMED (a
        #: POLL at `start` proves the client holds everything below
        #: `start`) — the tree ack barrier reads this
        self._cursors: dict[int, int] = {}
        self._conn_seq = 0
        self._threads: list[threading.Thread] = []
        self._snap_seq = 0
        self._fence_lock = make_lock("FeedServer._fence_lock")
        self._last_fence: tuple[int, bytes] | None = None

        reg = get_registry()
        self._m_conns = reg.counter("repl.transport.connections")
        self._m_requests = reg.counter("repl.transport.requests")
        self._m_records = reg.counter("repl.transport.records_served")
        self._m_bytes = reg.counter("repl.transport.bytes_served")
        self._m_snaps = reg.counter("repl.transport.snapshots_served")
        self._m_errors = reg.counter("repl.transport.server_errors")

        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"repl-feed-server-{name}",
            daemon=True,
        )
        if auto_start:
            self.start()

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        if not self._accept_thread.is_alive() \
                and not self._accept_thread.ident:
            self._accept_thread.start()
            get_tracer().emit("transport-serve", name=self.name,
                             host=self.address[0],
                             port=self.address[1])

    def close(self) -> None:
        """Stop accepting, close every connection, join the threads."""
        with self._lock:
            if self._stop:
                return
            self._stop = True
            conns = list(self._conns.values())
            threads = list(self._threads)
            self._cond.notify_all()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread.ident:
            self._accept_thread.join(5.0)
        for t in threads:
            if t.ident:
                t.join(5.0)

    def __enter__(self) -> "FeedServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------ accept loop

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
            try:
                conn, _addr = self._sock.accept()
            except (TimeoutError, socket.timeout):
                continue  # the periodic stop-flag check
            except OSError:
                with self._lock:
                    stopping = self._stop
                if stopping:
                    return
                self._m_errors.inc()
                continue
            conn.settimeout(self.io_timeout_s)
            with self._lock:
                if self._stop:
                    conn.close()
                    return
                cid = self._conn_seq
                self._conn_seq += 1
                self._conns[cid] = conn
                t = threading.Thread(
                    target=self._serve_conn, args=(cid, conn),
                    name=f"repl-feed-conn-{self.name}-{cid}",
                    daemon=True,
                )
                self._threads.append(t)
                # bound the join list: forget threads that finished
                self._threads = [x for x in self._threads
                                 if x.is_alive() or not x.ident]
            self._m_conns.inc()
            t.start()

    # ------------------------------------------------- connection serve

    def _serve_conn(self, cid: int, conn: socket.socket) -> None:
        try:
            while True:
                with self._lock:
                    if self._stop:
                        return
                try:
                    req = recv_frame(conn)
                except TransportError:
                    return  # client went away: its cursor re-syncs on
                    # the next connection's polls
                self._m_requests.inc()
                try:
                    rsp_frames = self._handle(cid, conn, req)
                except Exception as e:
                    # a per-request failure is ANSWERED, not swallowed:
                    # the client gets a typed error frame and the
                    # failure is counted/traced via _record_failure
                    self._record_failure(e, cid)
                    rsp_frames = [self._error_for(e)]
                for frame in rsp_frames:
                    send_frame(conn, frame)
                    self._m_bytes.inc(len(frame))
        except TransportError:
            return  # mid-response disconnect: nothing to clean beyond
            # the finally below; the client re-polls from its cursor
        finally:
            with self._lock:
                self._conns.pop(cid, None)
                self._cursors.pop(cid, None)
                self._cond.notify_all()
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _error_for(exc: Exception) -> bytes:
        if isinstance(exc, EpochFencedError):
            return _error_payload(_ERR_FENCED, exc.epoch, exc.current,
                                  str(exc))
        if isinstance(exc, FeedGapError):
            return _error_payload(_ERR_GAP, exc.expected, exc.got,
                                  str(exc))
        return _error_payload(
            _ERR_GENERIC, 0, 0, f"{type(exc).__name__}: {exc}"
        )

    def _record_failure(self, exc: Exception, cid: int) -> None:
        """Count + trace a request-handling failure (the sanctioned
        worker-exception path: the error is also RETURNED to the
        client as a typed frame by the caller)."""
        self._m_errors.inc()
        get_tracer().emit("transport-error", name=self.name, conn=cid,
                          cause=type(exc).__name__)
        logger.exception("feed server %s: request failed on conn %d",
                         self.name, cid)

    def _stat_payload(self, kind: int) -> bytes:
        tail = int(self.source.tail_pos())
        epoch = int(self.source.epoch())
        hb_len, hb = _pack_hb(self.source.read_heartbeat())
        return bytes([kind]) + _STAT_HDR.pack(tail, epoch,
                                              hb_len) + hb

    def _handle(self, cid: int, conn: socket.socket,
                req: bytes) -> list[bytes]:
        if not req:
            raise FeedError("empty request frame")
        kind = req[0]
        if kind == _REQ_POLL:
            start, max_records = _POLL_HDR.unpack_from(req, 1)
            return [self._poll_payload(cid, start, max_records)]
        if kind == _REQ_STAT:
            return [self._stat_payload(_RSP_STAT)]
        if kind == _REQ_FENCE:
            (epoch,) = _Q.unpack_from(req, 1)
            token = bytes(req[1 + _Q.size:1 + _Q.size + 16])
            epoch = int(epoch)
            # serialized: concurrent fences from racing promotions
            # must not both pass the source's check-then-publish.
            # Token-keyed idempotence: the client retries a request
            # whose RESPONSE was lost on the wire, so re-applying the
            # SAME fencer's fence at the current epoch succeeds —
            # while a DIFFERENT promoter racing to the same number
            # still fails typed (two winners at one epoch would be
            # split brain, exactly what fencing exists to prevent).
            with self._fence_lock:
                current = int(self.source.epoch())
                if not (epoch == current
                        and self._last_fence == (epoch, token)):
                    current = int(self.source.fence(epoch))
                    self._last_fence = (current, token)
                if self._on_fence is not None:
                    self._on_fence(current)
            return [self._stat_payload(_RSP_STAT)]
        if kind == _REQ_SNAP:
            (min_pos,) = _Q.unpack_from(req, 1)
            return self._snapshot_frames(conn, int(min_pos))
        raise FeedError(f"unknown request kind {kind}")

    def _poll_payload(self, cid: int, start: int,
                      max_records: int) -> bytes:
        start = int(start)
        with self._lock:
            self._cursors[cid] = max(self._cursors.get(cid, 0), start)
            self._cond.notify_all()
        cap = min(int(max_records) if max_records > 0 else
                  self.max_records, self.max_records)
        records = self.source.poll(start)[:cap]
        tail = int(self.source.tail_pos())
        epoch = int(self.source.epoch())
        hb_len, hb = _pack_hb(self.source.read_heartbeat())
        # bound the response by BYTES as well as record count: the
        # client's recv_frame rejects frames past MAX_PAYLOAD_BYTES,
        # and an uncapped backlog response would be rejected on every
        # retry — a silent permanent stall. Truncation is safe: the
        # follower's next poll continues from its advanced cursor.
        blobs: list[bytes] = []
        total = 0
        for rec in records:
            blob = encode_record(rec)
            if blobs and total + len(blob) > MAX_RESPONSE_BYTES:
                break  # the FIRST record always ships, however large
            blobs.append(blob)
            total += _I.size + len(blob)
        out = io.BytesIO()
        out.write(bytes([_RSP_RECORDS]))
        out.write(_RECORDS_HDR.pack(tail, epoch, hb_len, len(blobs)))
        out.write(hb)
        for blob in blobs:
            out.write(_I.pack(len(blob)))
            out.write(blob)
        if blobs:
            self._m_records.inc(len(blobs))
            # the record's wire hop (`obs/` fleet tracing): a sampled
            # record leaving THIS node for a downstream consumer —
            # sampled on `pos` like ship/forward/apply, so the fleet
            # report sees which edge a record crossed and when
            tracer = get_tracer()
            if tracer.enabled:
                for rec in records[:len(blobs)]:
                    if pos_sampled(rec.pos):
                        tracer.emit("transport-poll", pos=rec.pos,
                                    n=rec.count, name=self.name,
                                    conn=cid)
        return out.getvalue()

    # --------------------------------------------------------- snapshot

    def _newest_snapshot(self, min_pos: int):
        """(pos, path) of the newest servable snapshot past `min_pos`,
        or None."""
        if self._snapshot_provider is not None:
            return self._snapshot_provider(min_pos)
        if self.snapshot_dir is None:
            return None
        from node_replication_tpu.durable.recovery import list_snapshots

        for pos, path in list_snapshots(self.snapshot_dir):
            if pos > min_pos:
                return pos, path
            break  # newest first: nothing newer exists
        return None

    def _snapshot_frames(self, conn: socket.socket,
                         min_pos: int) -> list[bytes]:
        """Stream the newest snapshot past `min_pos` as META + CHUNK*
        + END frames (sent inline: the sidecar connection carries
        nothing else). Integrity is layered: each chunk is CRC-framed
        in flight, and the npz itself carries the blake2b manifest
        digest `recover_fleet` validates before trusting it."""
        found = self._newest_snapshot(min_pos)
        if found is None:
            return [bytes([_RSP_SNAP_META]) + _SNAP_META.pack(-1, 0)]
        pos, path = found
        size = os.path.getsize(path)
        pin = None
        if self._wal is not None:
            with self._lock:
                self._snap_seq += 1
                pin = f"{SNAPSHOT_PIN}:{self._id}.{self._snap_seq}"
            self._wal.set_pin(pin, pos)
        try:
            send_frame(conn, bytes([_RSP_SNAP_META])
                       + _SNAP_META.pack(pos, size))
            sent = 0
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(SNAP_CHUNK_BYTES)
                    if not chunk:
                        break
                    send_frame(conn, bytes([_RSP_SNAP_CHUNK]) + chunk)
                    sent += len(chunk)
                    self._m_bytes.inc(len(chunk))
        finally:
            if pin is not None:
                self._wal.clear_pin(pin)
        self._m_snaps.inc()
        get_tracer().emit("transport-snapshot-served", pos=pos,
                          bytes=sent, name=self.name)
        return [bytes([_RSP_SNAP_END]) + _Q.pack(sent)]

    # ---------------------------------------------------- ack plumbing

    def downstream_cursors(self) -> dict[int, int]:
        """conn id -> highest confirmed poll cursor (live conns only)."""
        with self._lock:
            return {cid: cur for cid, cur in self._cursors.items()
                    if cid in self._conns}

    def barrier(self, pos: int, min_clients: int = 1,
                timeout: float | None = 30.0) -> None:
        """Block until at least `min_clients` live downstream
        connections have confirmed (via a poll cursor) every record
        below `pos` — the tree's ship-before-ack extension: composed
        with `ReplicationShipper.barrier` (`make_tree_barrier`), an
        ack then implies the write is fsynced, feed-visible, AND
        received by `min_clients` downstream node(s). Raises
        `FeedError` on timeout or server shutdown (the serve layer
        maps it to its maybe_executed rejection)."""
        pos = int(pos)
        min_clients = max(1, int(min_clients))
        clock = get_clock()
        t_end = None if timeout is None else clock.now() + timeout
        with self._lock:
            while True:
                confirmed = sum(
                    1 for cid, cur in self._cursors.items()
                    if cid in self._conns and cur >= pos
                )
                if confirmed >= min_clients:
                    return
                if self._stop:
                    raise FeedError("feed server stopped; downstream "
                                    "receipt cannot be confirmed")
                rem = None if t_end is None else t_end - clock.now()
                if rem is not None and rem <= 0:
                    raise FeedError(
                        f"downstream barrier timed out: {confirmed}/"
                        f"{min_clients} connection(s) past {pos}"
                    )
                clock.wait(self._cond,
                           0.05 if rem is None else min(rem, 0.05))

    def stats(self) -> dict:
        with self._lock:
            return {
                "address": list(self.address),
                "connections": len(self._conns),
                "cursors": {str(k): v for k, v in
                            self._cursors.items()
                            if k in self._conns},
                "stopped": self._stop,
            }


def make_tree_barrier(shipper, server: FeedServer,
                      min_clients: int = 1,
                      timeout: float | None = 30.0):
    """`ServeFrontend.ack_barrier` for a tree root: ship-before-ack
    (the record is fsynced and feed-visible, `shipper.barrier`) AND
    received-downstream-before-ack (`server.barrier`). With relays
    journaling what they receive, an ack survives the loss of the
    primary AND any `min_clients - 1` downstream nodes."""

    def ack_barrier(pos: int) -> None:
        shipper.barrier(pos)
        server.barrier(pos, min_clients=min_clients, timeout=timeout)

    return ack_barrier


# ==========================================================================
# client
# ==========================================================================


class SocketFeed:
    """TCP client side of a `FeedServer`: the `DirectoryFeed` read
    interface over the wire.

        feed = SocketFeed(host, port, arg_width=dispatch.arg_width)
        follower = Follower(dispatch, feed, directory=my_dir)

    Thread-safe (one request/response in flight at a time under the
    client lock — the apply thread, read path, and promotion watcher
    all share the connection). Transient failures reconnect-and-retry
    once per call; a still-dead upstream degrades reads to cached
    state and polls to empty, which is indistinguishable from a slow
    feed — by design (see module docstring).
    """

    def __init__(
        self,
        host: str,
        port: int,
        arg_width: int = 3,
        connect_timeout_s: float = 5.0,
        io_timeout_s: float = 10.0,
        max_records: int = 256,
        name: str = "socket-feed",
    ):
        self.host = host
        self.port = int(port)
        self.arg_width = int(arg_width)
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self.max_records = int(max_records)
        self.name = name

        # nrcheck: lock-order SocketFeed._lock -> Counter._lock — RPC failure/retry counters bump under the transport lock
        self._lock = make_lock("SocketFeed._lock")
        self._sock: socket.socket | None = None
        # last connected observations: the degraded-mode answers
        self._tail = 0
        self._epoch = 0
        self._hb: str | None = None

        reg = get_registry()
        self._m_connects = reg.counter("repl.transport.connects")
        self._m_reconnects = reg.counter("repl.transport.reconnects")
        self._m_errors = reg.counter("repl.transport.client_errors")
        self._m_records = reg.counter("repl.transport.records_fetched")
        self._m_snap_bytes = reg.counter("repl.snapshot.bytes_fetched")

    # ------------------------------------------------------- connection

    def _connect_locked(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
        except OSError as e:
            raise TransportError(
                f"cannot connect to {self.host}:{self.port}: {e}"
            ) from e
        sock.settimeout(self.io_timeout_s)
        self._sock = sock
        self._m_connects.inc()
        get_tracer().emit("transport-connect", host=self.host,
                          port=self.port, name=self.name)
        return sock

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _request(self, payload: bytes) -> bytes:
        """One framed exchange; reconnects and retries ONCE on a
        transient failure (torn stream / dead socket). Error frames
        raise their typed exception."""
        with self._lock:
            for attempt in (0, 1):
                try:
                    sock = self._connect_locked()
                    send_frame(sock, payload)
                    rsp = recv_frame(sock)
                    break
                except TransportError:
                    self._drop_locked()
                    if attempt:
                        self._m_errors.inc()
                        raise
                    self._m_reconnects.inc()
                    get_tracer().emit("transport-reconnect",
                                      host=self.host, port=self.port,
                                      name=self.name)
        if rsp and rsp[0] == _RSP_ERROR:
            _raise_error(rsp)
        return rsp

    def close(self) -> None:
        with self._lock:
            self._drop_locked()

    def __enter__(self) -> "SocketFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- read

    def poll(self, start: int = 0) -> list:
        """Readable records covering positions >= `start` (capped per
        response — the follower's poll loop drains the rest). Empty on
        transport failure: a flaky link reads as a quiet feed."""
        try:
            rsp = self._request(
                bytes([_REQ_POLL])
                + _POLL_HDR.pack(int(start), self.max_records)
            )
        except TransportError:
            return []
        if rsp[0] != _RSP_RECORDS:
            raise FeedError(f"unexpected response kind {rsp[0]}")
        tail, epoch, hb_len, nrec = _RECORDS_HDR.unpack_from(rsp, 1)
        off = 1 + _RECORDS_HDR.size
        self._note_stat(tail, epoch, hb_len,
                        rsp[off:off + max(0, hb_len)])
        off += max(0, hb_len)
        records = []
        for _ in range(nrec):
            (blob_len,) = _I.unpack_from(rsp, off)
            off += _I.size
            records.append(
                decode_record(rsp[off:off + blob_len], self.arg_width)
            )
            off += blob_len
        if records:
            self._m_records.inc(len(records))
        return records

    def _note_stat(self, tail: int, epoch: int, hb_len: int,
                   hb_raw: bytes) -> None:
        with self._lock:
            self._tail = max(self._tail, int(tail))
            self._epoch = max(self._epoch, int(epoch))
            if hb_len >= 0:
                self._hb = hb_raw.decode("utf-8", "replace")

    def _stat(self) -> None:
        try:
            rsp = self._request(bytes([_REQ_STAT]))
        except TransportError:
            return  # degraded: cached observations answer
        if rsp[0] != _RSP_STAT:
            raise FeedError(f"unexpected response kind {rsp[0]}")
        tail, epoch, hb_len = _STAT_HDR.unpack_from(rsp, 1)
        off = 1 + _STAT_HDR.size
        self._note_stat(tail, epoch, hb_len,
                        rsp[off:off + max(0, hb_len)])

    def tail_pos(self) -> int:
        self._stat()
        with self._lock:
            return self._tail

    def epoch(self) -> int:
        self._stat()
        with self._lock:
            return self._epoch

    def read_heartbeat(self) -> str | None:
        self._stat()
        with self._lock:
            return self._hb

    def peek_stat(self) -> tuple[int, int, str | None]:
        """`(tail, epoch, heartbeat)` from the LAST response, no RPC —
        every poll response already carries all three, so a tight
        consumer loop (the relay pump) reads them here instead of
        issuing redundant STAT round-trips after each poll."""
        with self._lock:
            return self._tail, self._epoch, self._hb

    # ------------------------------------------------------------ fence

    def fence(self, epoch: int) -> int:
        """Forward a promotion fence to the server's source feed.
        Raises (never degrades) on transport failure: a promotion must
        know whether the fence took. The per-call fencer token makes
        the internal retry safe: a fence whose RESPONSE was lost on
        the wire re-applies idempotently, while a different promoter
        racing to the same epoch still fails typed."""
        rsp = self._request(bytes([_REQ_FENCE])
                            + _Q.pack(int(epoch))
                            + os.urandom(16))
        if rsp[0] != _RSP_STAT:
            raise FeedError(f"unexpected response kind {rsp[0]}")
        tail, new_epoch, hb_len = _STAT_HDR.unpack_from(rsp, 1)
        off = 1 + _STAT_HDR.size
        self._note_stat(tail, new_epoch, hb_len,
                        rsp[off:off + max(0, hb_len)])
        return int(new_epoch)

    # --------------------------------------------------------- snapshot

    def fetch_snapshot(self, dest_dir: str,
                       min_pos: int = 0) -> tuple[int, str] | None:
        """Download the server's newest snapshot strictly past
        `min_pos` into `dest_dir` as `snap-<pos>.npz` (the name
        `recover_fleet` globs). Returns `(pos, path)`, or None when
        the server holds nothing newer. Uses a SIDECAR connection so a
        long transfer never blocks the record stream; the file is
        durably published (tmp + fsync + rename) and its manifest
        digest is validated by `recover_fleet` before anything trusts
        it. Raises on transport failure — bootstrap never
        half-happens."""
        from node_replication_tpu.durable.recovery import snapshot_path

        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
        except OSError as e:
            raise TransportError(
                f"cannot connect to {self.host}:{self.port}: {e}"
            ) from e
        sock.settimeout(self.io_timeout_s)
        try:
            send_frame(sock, bytes([_REQ_SNAP]) + _Q.pack(int(min_pos)))
            meta = recv_frame(sock)
            if meta[0] == _RSP_ERROR:
                _raise_error(meta)
            if meta[0] != _RSP_SNAP_META:
                raise FeedError(f"unexpected response kind {meta[0]}")
            pos, size = _SNAP_META.unpack_from(meta, 1)
            if pos < 0:
                return None
            os.makedirs(dest_dir, exist_ok=True)
            buf = io.BytesIO()
            while True:
                frame = recv_frame(sock)
                if frame[0] == _RSP_SNAP_CHUNK:
                    buf.write(frame[1:])
                    continue
                if frame[0] == _RSP_SNAP_END:
                    (total,) = _Q.unpack_from(frame, 1)
                    break
                if frame[0] == _RSP_ERROR:
                    _raise_error(frame)
                raise FeedError(
                    f"unexpected response kind {frame[0]}"
                )
            data = buf.getvalue()
            if len(data) != total or total != size:
                raise TransportError(
                    f"snapshot transfer incomplete ({len(data)} of "
                    f"{size} bytes)"
                )
        finally:
            try:
                sock.close()
            except OSError:
                pass
        path = snapshot_path(dest_dir, pos)
        durable_publish(path, data)
        self._m_snap_bytes.inc(len(data))
        get_tracer().emit("transport-snapshot-fetched", pos=int(pos),
                          bytes=len(data), name=self.name)
        return int(pos), path


# ==========================================================================
# in-memory twin
# ==========================================================================


class PipeTransport:
    """Deterministic in-memory stand-in for `SocketFeed`: wraps any
    feed and reproduces the CLIENT's degraded-mode contract without
    sockets or threads — `sim/properties.py` drives it to cover
    stream gaps, duplicate delivery, and zombie fencing over "the
    wire" in the 1000-seed sweep, and tests use it where a real
    listener would only add nondeterminism.

    - `disconnect()` → polls return [], `tail_pos`/`epoch`/
      `read_heartbeat` answer from the last connected observation (so
      a promotion watcher sees heartbeat silence, exactly as over a
      dead socket), `fence` raises.
    - `reconnect(rewind=k)` → the next poll re-serves from `k`
      positions before the caller's cursor: the retransmit-after-
      resume duplicate delivery the follower must absorb
      idempotently.
    """

    def __init__(self, inner, rewind: int = 8):
        self.inner = inner
        self.arg_width = getattr(inner, "arg_width", 3)
        self.rewind = int(rewind)
        self._connected = True
        self._replay_next = 0  # rewind amount pending for next poll
        self._tail = 0
        self._epoch = 0
        self._hb: str | None = None
        self._m_drops = get_registry().counter(
            "repl.transport.pipe_drops"
        )

    @property
    def connected(self) -> bool:
        return self._connected

    def disconnect(self) -> None:
        self._connected = False

    def reconnect(self, rewind: int | None = None) -> None:
        if not self._connected:
            self._connected = True
            self._replay_next = (
                self.rewind if rewind is None else int(rewind)
            )

    # ---- DirectoryFeed read surface -----------------------------------

    def poll(self, start: int = 0) -> list:
        if not self._connected:
            self._m_drops.inc()
            return []
        eff = max(0, int(start) - self._replay_next)
        self._replay_next = 0
        records = self.inner.poll(eff)
        self._tail = max(self._tail, self.inner.tail_pos())
        self._epoch = max(self._epoch, self.inner.epoch())
        hb = self.inner.read_heartbeat()
        if hb is not None:
            self._hb = hb
        return records

    def tail_pos(self) -> int:
        if not self._connected:
            return self._tail
        self._tail = max(self._tail, self.inner.tail_pos())
        return self._tail

    def epoch(self) -> int:
        if not self._connected:
            return self._epoch
        self._epoch = max(self._epoch, self.inner.epoch())
        return self._epoch

    def read_heartbeat(self) -> str | None:
        if not self._connected:
            return self._hb
        hb = self.inner.read_heartbeat()
        if hb is not None:
            self._hb = hb
        return self._hb

    def peek_stat(self) -> tuple[int, int, str | None]:
        """The socket client's no-RPC cache peek, same contract."""
        return self._tail, self._epoch, self._hb

    def fence(self, epoch: int) -> int:
        if not self._connected:
            raise FeedError(
                "transport disconnected: cannot forward fence"
            )
        return self.inner.fence(epoch)

    def fetch_snapshot(self, dest_dir: str, min_pos: int = 0):
        if not self._connected:
            raise TransportError(
                "transport disconnected: cannot fetch snapshot"
            )
        fetch = getattr(self.inner, "fetch_snapshot", None)
        if fetch is None:
            return None
        return fetch(dest_dir, min_pos=min_pos)
