"""Promotion: primary-death detection, election, measured-RTO failover.

The coordinator half of `repl/` failover, built on the SAME health
machine the in-process replica lifecycle uses (`fault/health.py`):
the `PromotionManager` tracks the PRIMARY PROCESS as replica
`health_rid` of a `HealthTracker` and walks it

    HEALTHY -> SUSPECT -> QUARANTINED

on missed heartbeats, exactly as a dead serve worker walks an
in-process replica. Detection is heartbeat-CHANGE based: the shipper
refreshes a beacon in the feed every loop (`repl/shipper.py`), and
the watcher compares successive reads with its OWN monotonic clock —
no wall-clock agreement between processes is required, so NTP steps
and clock skew cannot fake (or mask) a death.

On QUARANTINED the manager elects the MOST-ADVANCED follower (max
`applied_pos()` — with ship-before-ack every acked write is at or
below the feed tail, and the drain during `Follower.promote` brings
the winner to the tail, so no acked write can be lost by electing
any live follower; electing the most advanced just minimizes drain
time) and promotes it: epoch fence + drain + WAL fsync + write
re-home (`Follower.promote`; fence-first, so the drain is bounded and
no zombie record can land mid-drain).

The `PromotionReport` carries the measured recovery timeline:
`detect_s` (last observed heartbeat change -> quarantine),
`promote_s` (drain/fence/re-home duration), and `rto_s` (their sum —
outage start to writes-served-again, the number
`bench.py --follower` commits to `replication_benchmarks.csv`).
"""

from __future__ import annotations

import dataclasses
import logging
import threading

from node_replication_tpu.analysis.locks import make_lock

from node_replication_tpu.fault.health import (
    HEALTHY,
    QUARANTINED,
    SUSPECT,
    HealthTracker,
)
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer

logger = logging.getLogger("node_replication_tpu")


@dataclasses.dataclass
class PromotionReport:
    """One completed failover, timed (JSON-safe)."""

    follower: str  # elected follower's name
    new_epoch: int
    applied_pos: int
    drained_records: int
    detect_s: float  # heartbeat silence -> primary declared dead
    promote_s: float  # drain + fence + re-home
    rto_s: float  # outage start -> writes served again

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class PromotionManager:
    """Watches a primary's heartbeat; elects and promotes on death.

    `check()` is one watch step (call it on any cadence);
    `start()`/`wait()` run the watch on a daemon thread and hand back
    the `PromotionReport` once a promotion completes. `promote_now()`
    is the operator's manual failover entry (skips detection).
    """

    def __init__(
        self,
        feed,
        followers,
        heartbeat_timeout_s: float = 0.5,
        check_interval_s: float = 0.05,
        health: HealthTracker | None = None,
        health_rid: int = 0,
    ):
        if not followers:
            raise ValueError("need at least one follower to promote")
        self._feed = feed
        self.followers = list(followers)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.check_interval_s = float(check_interval_s)
        # the primary occupies slot `health_rid` of the tracker — the
        # same machine (and the same legality rules) the in-process
        # lifecycle walks; 3 missed-beat strikes suspect it, silence
        # past 2x the timeout quarantines it
        self.health = health or HealthTracker(
            max(1, health_rid + 1), stall_threshold=3
        )
        self.health_rid = int(health_rid)

        # nrcheck: lock-order PromotionManager._lock -> HealthTracker._lock — election consults replica health under the manager lock
        self._lock = make_lock("PromotionManager._lock")
        self._last_hb: str | None = None
        self._last_change = get_clock().now()
        # silence counts only once a primary has been OBSERVED: a
        # watcher armed before the primary finishes booting (or with
        # no primary at all) must not fail over onto thin air —
        # promotion presumes there was acked history to take over
        self._seen = False
        self._report: PromotionReport | None = None
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        self._stop = False
        # `repl.promotions` itself is counted inside Follower.promote;
        # the manager only adds the detection timing on top
        get_registry().counter("repl.promotions")

    # ------------------------------------------------------------ watch

    def check(self) -> str:
        """One watch step: read the beacon, credit a change, strike
        silence. Returns the primary's current health state; when the
        step quarantines the primary, the caller should promote
        (`run()`/the watch thread do so automatically)."""
        now = get_clock().now()
        hb = self._feed.read_heartbeat()
        with self._lock:
            if hb is None and not self._seen:
                # no primary has ever beaconed on this feed: nothing
                # to detect the death of (yet)
                self._last_change = now
                return self.health.state(self.health_rid)
            if hb != self._last_hb:
                self._seen = True
                self._last_hb = hb
                self._last_change = now
                if self.health.state(self.health_rid) == SUSPECT:
                    # the primary spoke again during probation
                    self.health.clear_suspect(self.health_rid)
                return self.health.state(self.health_rid)
            silent = now - self._last_change
        state = self.health.state(self.health_rid)
        if silent >= self.heartbeat_timeout_s and state == HEALTHY:
            # each silent check past the timeout is one stall strike;
            # stall_threshold of them suspect the primary
            state = self.health.report_stall(self.health_rid)
        if silent >= 2 * self.heartbeat_timeout_s and state == SUSPECT:
            self.health.quarantine(self.health_rid)
            state = QUARANTINED
        return state

    def elect(self):
        """The most-advanced live follower (max applied position)."""
        live = [f for f in self.followers if f.error is None]
        if not live:
            live = self.followers  # last resort: promote anyway
        return max(live, key=lambda f: f.applied_pos())

    def promote_now(self, detect_s: float = 0.0) -> PromotionReport:
        """Elect and promote immediately (detection already done, or
        operator-initiated failover)."""
        chosen = self.elect()
        t0 = get_clock().now()
        rep = chosen.promote()
        promote_s = get_clock().now() - t0
        report = PromotionReport(
            follower=rep["name"],
            new_epoch=rep["epoch"],
            applied_pos=rep["applied"],
            drained_records=rep["drained_records"],
            detect_s=detect_s,
            promote_s=promote_s,
            rto_s=detect_s + promote_s,
        )
        with self._lock:
            self._report = report
        self._done.set()
        get_tracer().emit(
            "repl-rto", follower=report.follower,
            detect_s=report.detect_s, promote_s=report.promote_s,
            rto_s=report.rto_s,
        )
        return report

    def run(self, timeout: float | None = None) -> PromotionReport | None:
        """Watch until the primary dies, then promote; returns the
        report (None when `timeout` expires with the primary alive).
        The watch thread (`start()`) runs exactly this."""
        clock = get_clock()
        t_end = (
            None if timeout is None else clock.now() + timeout
        )
        while True:
            with self._lock:
                if self._stop:
                    return self._report
            state = self.check()
            if state == QUARANTINED:
                with self._lock:
                    silence = clock.now() - self._last_change
                logger.warning(
                    "primary declared dead after %.2fs of heartbeat "
                    "silence; promoting", silence,
                )
                return self.promote_now(detect_s=silence)
            if t_end is not None and clock.now() >= t_end:
                return None
            clock.sleep(self.check_interval_s)

    # --------------------------------------------------------- threaded

    def start(self) -> None:
        """Run the watch on a daemon thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return
            t = threading.Thread(
                target=self._watch_loop, name="repl-promotion-watch",
                daemon=True,
            )
            self._thread = t
        t.start()

    def _watch_loop(self) -> None:
        try:
            self.run()
        # the watch dying silently would turn primary death into an
        # unbounded outage — record health and release waiters
        except Exception as e:
            logger.exception("promotion watch failed")
            self.health.report_worker_exception(self.health_rid, e)
        finally:
            self._done.set()

    def stop(self) -> None:
        """Stop the watch; `wait()` callers release (report may be
        None — the primary was alive when the watch stopped)."""
        with self._lock:
            self._stop = True
        self._done.set()

    def wait(self, timeout: float | None = None) -> PromotionReport | None:
        """Block until a promotion completes (None on timeout)."""
        self._done.wait(timeout)
        with self._lock:
            return self._report

    @property
    def report(self) -> PromotionReport | None:
        with self._lock:
            return self._report
