"""Relay: a feed-of-feeds fan-out node for cross-host follower trees.

A `RelayNode` is the interior node of a replication tree (1 primary →
R relays → N followers): it consumes ONE upstream record stream
(normally a `repl/transport.py:SocketFeed`, any feed-shaped source
works), journals every record into its own local `DirectoryFeed`, and
serves any number of downstream consumers from that journal through
its own `FeedServer`. Each primary record therefore crosses each tree
EDGE exactly once — a 1→8→64 tree costs the primary 8 downstream
streams, not 64 — and a relay crash loses nothing: the local journal
is the cursor, and the pump resumes from `local.tail_pos()`.

The pump applies the follower's delivery rules (`repl/feed.py`) on the
forwarding path:

- records chaining onto the journal cursor republish AS-IS (same
  epoch, same position — the journal is a byte-faithful copy, so
  downstream bit-identity composes through any relay depth);
- records wholly below the cursor are duplicates (upstream resume /
  re-ship) and skip idempotently;
- a record starting past the cursor is a typed `FeedGapError` — the
  relay surfaces it (health API + error slot) rather than forwarding
  a hole to its whole subtree;
- a record with an epoch older than the local journal's fence is a
  zombie primary's late write: the journal's own `EpochFencedError`
  rejects the publish, the relay counts it and drops the record —
  fenced history never reaches the subtree.

Promotion composes through relays: a downstream follower's
`promote()` fences its upstream feed — this relay's server fences the
LOCAL journal (so the pump can forward nothing older) and the relay
propagates the fence toward the primary best-effort (`on_fence` →
`upstream.fence`; a dead primary's unreachable server is fine — its
own late publishes die against apply-side fences and this journal's).

The heartbeat is forwarded VERBATIM: downstream watchers
(`repl/promote.py`) detect change in the PRIMARY's beacon, so a dead
primary is detected at every leaf even though the relay between them
is alive. (A dead relay also reads as silence below it — correct: its
subtree really is cut off.)

Snapshot bootstrap composes too: a downstream `fetch_snapshot` is
served from the relay's local snapshot cache, refreshed from upstream
at most once per newer-snapshot request — snapshots also ship once
per edge, not once per leaf.
"""

from __future__ import annotations

import logging
import os
import threading

from node_replication_tpu.analysis.locks import (
    make_condition,
    make_lock,
)

from node_replication_tpu.fault.inject import fault_hook
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.repl.feed import (
    DirectoryFeed,
    EpochFencedError,
    FeedGapError,
)
from node_replication_tpu.repl.transport import FeedServer
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer, pos_sampled

logger = logging.getLogger("node_replication_tpu")

#: local journal / snapshot-cache subdirectories of a relay directory
FEED_SUBDIR = "feed"
SNAP_CACHE_SUBDIR = "snapshots"


class RelayNode:
    """One interior tree node: upstream consumer + local journal +
    downstream server.

        up = SocketFeed(primary_host, primary_port, arg_width=aw)
        relay = RelayNode(up, directory=my_dir, arg_width=aw)
        host, port = relay.address        # hand to the subtree
    """

    def __init__(
        self,
        upstream,
        directory: str,
        arg_width: int = 3,
        host: str = "127.0.0.1",
        port: int = 0,
        poll_s: float = 0.002,
        health=None,
        health_rid: int = 0,
        auto_start: bool = True,
        name: str = "relay",
        obs_port: int | None = None,
        obs_node_id: str | None = None,
    ):
        self.name = name
        self.upstream = upstream
        self._poll_s = float(poll_s)
        self.health = health
        self.health_rid = int(health_rid)
        self._snap_cache = os.path.join(directory, SNAP_CACHE_SUBDIR)

        self.local = DirectoryFeed(
            os.path.join(directory, FEED_SUBDIR), arg_width=arg_width
        )
        # resume from the journal: everything below its tail already
        # reached (and is re-servable to) the subtree
        self._cursor = self.local.tail_pos()
        #: highest epoch among FORWARDED records (starts 0 like the
        #: follower's apply floor: a relay booted behind a promotion
        #: must still forward the older epochs' history below it)
        self.epoch = 0
        self._cond = make_condition("RelayNode._cond")
        self._error: BaseException | None = None
        self._stop = False
        self._last_hb: str | None = None
        self._snap_lock = make_lock("RelayNode._snap_lock")

        reg = get_registry()
        self._m_forwarded = reg.counter("repl.relay.forwarded_records")
        self._m_ops = reg.counter("repl.relay.forwarded_ops")
        self._m_dups = reg.counter("repl.relay.duplicate_records")
        self._m_fenced = reg.counter("repl.relay.fenced_records")
        self._m_errors = reg.counter("repl.relay.errors")
        self._g_lag = reg.gauge("repl.relay.lag_pos")

        self.server = FeedServer(
            self.local,
            host=host,
            port=port,
            snapshot_provider=self._snapshot_provider,
            on_fence=self._propagate_fence,
            auto_start=auto_start,
            name=f"{name}-server",
        )
        #: fleet observability side port (`obs/export.py`): the
        #: relay's scrape endpoint, serving the process registry plus
        #: this relay's stats under its own node identity (several
        #: relays in one process each get their own endpoint). None
        #: (default) starts nothing — zero added work anywhere.
        self.exporter = None
        if obs_port is not None:
            from node_replication_tpu.obs.export import MetricsExporter

            self.exporter = MetricsExporter(
                node_id=obs_node_id or name, role="relay",
                port=obs_port,
            )
            self.exporter.add_stats("relay", self.stats)

        self._thread = threading.Thread(
            target=self._pump_loop, name=f"repl-relay-{name}",
            daemon=True,
        )
        if auto_start:
            self.start()

    @property
    def address(self) -> tuple[str, int]:
        """`(host, port)` the subtree connects to."""
        return self.server.address

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.server.start()
        if not self._thread.is_alive() and not self._thread.ident:
            self._thread.start()

    def stop(self, timeout: float | None = 5.0) -> None:
        """Stop the pump (joins it); the server keeps serving the
        journal until `close()` — a wedged upstream must not cut off
        the subtree's reads."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.ident:
            self._thread.join(timeout)

    def close(self) -> None:
        self.stop()
        self.server.close()
        if self.exporter is not None:
            self.exporter.close()
        close = getattr(self.upstream, "close", None)
        if close is not None:
            close()

    def __enter__(self) -> "RelayNode":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- pump

    def _pump_loop(self) -> None:
        while True:
            try:
                self._pump_once()
            # a silent relay failure would starve the whole subtree:
            # record it (error slot + health + counter) and stop
            except Exception as e:
                self._record_failure(e)
                return
            with self._cond:
                if self._stop:
                    return
                get_clock().wait(self._cond, self._poll_s)

    def _pump_once(self) -> int:
        """Poll upstream once and journal everything readable;
        returns records forwarded. Single-driver (the pump thread, or
        tests calling it directly with `auto_start=False`)."""
        fault_hook("relay-pump", -1, self)
        # _cursor reads below: the pump is _cursor's only writer, and
        # this method is single-driver (see docstring) — a lock-free
        # read in the writing thread cannot be stale
        records = self.upstream.poll(self._cursor)  # nrcheck: unshared
        forwarded = 0
        tracer = get_tracer()
        for rec in records:
            end = rec.pos + rec.count
            if end <= self._cursor:  # nrcheck: unshared — pump-only write
                self._m_dups.inc()
                continue
            if rec.pos > self._cursor:  # nrcheck: unshared — pump-only write
                raise FeedGapError(self._cursor, rec.pos)  # nrcheck: unshared
            with self._cond:
                # snapshot the forwarding floor under the lock: a
                # server-thread fence (`_propagate_fence`) can raise
                # it concurrently, and a stale read here would
                # forward a record below the new floor
                epoch_floor = self.epoch
            if rec.epoch < epoch_floor:
                # zombie record below the forwarding floor: drop it
                # and advance PAST it — these positions belong to a
                # superseded history no consumer may ever see, and
                # re-polling them forever would wedge the pump
                self._m_fenced.inc()
                tracer.emit("relay-fenced", pos=rec.pos,
                            epoch=rec.epoch, current=epoch_floor)
                with self._cond:
                    self._cursor = end
                continue
            try:
                self.local.publish(rec.epoch, rec.pos, rec.opcodes,
                                   rec.args)
            except EpochFencedError:
                # the JOURNAL is fenced ahead of us (a downstream
                # promotion landed through the server): same rule
                self._m_fenced.inc()
                tracer.emit("relay-fenced", pos=rec.pos,
                            epoch=rec.epoch,
                            current=self.local.epoch())
                with self._cond:
                    self._cursor = end
                continue
            with self._cond:
                self._cursor = end
                if rec.epoch > self.epoch:
                    self.epoch = int(rec.epoch)
                self._cond.notify_all()
            forwarded += 1
            self._m_forwarded.inc()
            self._m_ops.inc(rec.count)
            # the record's relay hop (`obs/` fleet tracing): sampled
            # on `pos` like ship/apply, so a sampled record's chain
            # includes every relay it crossed — the join that answers
            # "which relay is the lag bottleneck"
            if tracer.enabled and pos_sampled(rec.pos):
                tracer.emit("relay-forward", pos=rec.pos, n=rec.count,
                            epoch=rec.epoch, name=self.name)
        # the poll response already carried tail + heartbeat: read the
        # transport's cache instead of issuing two more STAT RPCs per
        # pump cycle (at a 1ms poll that would triple every relay's
        # request load on the primary); plain local feeds answer the
        # method calls directly — they cost no wire round-trip
        peek = getattr(self.upstream, "peek_stat", None)
        if peek is not None:
            up_tail, _, hb = peek()
        else:
            up_tail = self.upstream.tail_pos()
            hb = self.upstream.read_heartbeat()
        if hb is not None and hb != self._last_hb:
            # verbatim: leaves must observe the PRIMARY's beacon
            self.local.write_heartbeat(hb)
            self._last_hb = hb
        with self._cond:
            cur = self._cursor
        self._g_lag.set(max(0, int(up_tail) - cur))
        return forwarded

    def _record_failure(self, exc: BaseException) -> None:
        """The sanctioned worker-exception path (`repl/` contract):
        error slot for callers, health report when attached, counter +
        trace event."""
        with self._cond:
            self._error = exc
            self._cond.notify_all()
        self._m_errors.inc()
        get_tracer().emit("relay-error", name=self.name,
                          # nrcheck: unshared — pump thread, own write
                          cursor=self._cursor,
                          cause=type(exc).__name__)
        logger.exception("relay %s pump failed at cursor %d",
                         # nrcheck: unshared — pump thread, own write
                         self.name, self._cursor)
        if self.health is not None:
            self.health.report_worker_exception(self.health_rid, exc)

    # ------------------------------------------------------------ fence

    def _propagate_fence(self, epoch: int) -> None:
        """Server hook: the local journal just fenced to `epoch`
        (a downstream promotion). Raise the pump's forwarding floor
        and push the fence toward the primary, best effort — an
        unreachable (dead) upstream is the EXPECTED case during a
        failover, and the journal fence already protects the subtree."""
        with self._cond:
            if epoch > self.epoch:
                self.epoch = int(epoch)
        try:
            self.upstream.fence(epoch)
        except Exception as e:
            get_registry().counter(
                "repl.relay.fence_propagation_failures"
            ).inc()
            get_tracer().emit("relay-fence-unpropagated",
                              epoch=int(epoch),
                              cause=type(e).__name__)
            logger.warning(
                "relay %s: fence %d not propagated upstream (%s: %s)",
                self.name, epoch, type(e).__name__, e,
            )

    # --------------------------------------------------------- snapshot

    def _snapshot_provider(self, min_pos: int):
        """Downstream bootstrap source: serve from the local cache,
        refreshing from upstream when the cache cannot satisfy
        `min_pos` — one upstream transfer per NEW snapshot, however
        many leaves bootstrap below this node."""
        from node_replication_tpu.durable.recovery import list_snapshots

        fetch = getattr(self.upstream, "fetch_snapshot", None)
        with self._snap_lock:
            cached = list_snapshots(self._snap_cache)
            have = cached[0][0] if cached else 0
            if fetch is not None:
                try:
                    got = fetch(self._snap_cache, min_pos=have)
                except Exception as e:
                    got = None  # degraded: the cache still serves
                    get_registry().counter(
                        "repl.relay.snapshot_refresh_failures"
                    ).inc()
                    logger.warning(
                        "relay %s: upstream snapshot refresh failed "
                        "(%s: %s)", self.name, type(e).__name__, e,
                    )
                if got is not None:
                    cached = [got] + cached
            for pos, path in cached:
                if pos > min_pos:
                    return pos, path
                break  # newest first
            return None

    # ------------------------------------------------------------ state

    @property
    def error(self) -> BaseException | None:
        # nrcheck: unshared — lock-free poll; one reference load
        return self._error

    def cursor(self) -> int:
        with self._cond:
            return self._cursor

    def lag(self) -> int:
        """Positions upstream holds that this relay has not journaled
        (served from the transport's cached tail while upstream is
        unreachable — a partitioned relay reads as a lagging one)."""
        with self._cond:
            cur = self._cursor
        return max(0, int(self.upstream.tail_pos()) - cur)

    def wait_forwarded(self, pos: int,
                       timeout: float | None = None) -> bool:
        """Block until the journal covers `pos` (test/ops barrier).
        False on timeout or a dead pump."""
        clock = get_clock()
        t_end = None if timeout is None else clock.now() + timeout
        with self._cond:
            while self._cursor < pos:
                if self._error is not None or self._stop:
                    return False
                rem = None if t_end is None else t_end - clock.now()
                if rem is not None and rem <= 0:
                    return False
                clock.wait(self._cond,
                           0.05 if rem is None else min(rem, 0.05))
            return True

    def prune(self, floor: int) -> int:
        """Prune the local journal below `floor`, clamped to the
        slowest LIVE downstream cursor the server knows — a connected
        straggler is never pruned into a `FeedGapError`; a
        disconnected one may be (it re-seeds via snapshot bootstrap,
        by design)."""
        cursors = self.server.downstream_cursors()
        if cursors:
            floor = min(int(floor), min(cursors.values()))
        return self.local.prune(int(floor))

    def stats(self) -> dict:
        with self._cond:
            return {
                "name": self.name,
                "address": list(self.address),
                "cursor": self._cursor,
                "epoch": self.epoch,
                "stopped": self._stop,
                "error": (
                    None if self._error is None
                    else f"{type(self._error).__name__}: {self._error}"
                ),
            }
