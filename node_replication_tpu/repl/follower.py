"""Follower: replays a replication feed and serves bounded-staleness
reads.

The read-scale-out half of `repl/`: a follower process boots from its
own durability directory (`durable/recovery.py:recover_fleet` — empty
dir = fresh boot, populated dir = crash-resume at the journaled tail),
then follows the primary's feed on an apply thread. Every received
record replays through the SAME combiner protocol live primary
traffic uses (`NodeReplicated._append_and_replay`), and is journaled
into the follower's OWN write-ahead log by that protocol — so
follower state is bit-identical to the primary's fold at every common
position (deterministic replay, the repo's recovery property), and a
follower can itself be promoted, crash-recovered, or used to seed
further followers.

Apply rules (the feed's delivery edge cases, `repl/feed.py`):

- records that chain onto the applied cursor apply;
- records wholly below it are DUPLICATES and skip idempotently
  (`repl.duplicate_records`) — re-shipping is always safe;
- records straddling it are sliced (the overlap is the duplicate
  prefix);
- a record starting past it is a typed `FeedGapError` — the apply
  thread records the failure (health API + error slot) rather than
  silently skipping acknowledged history;
- a record with an epoch OLDER than one already applied is a zombie
  primary's late write: fenced (`repl.fenced_records`), never applied.

Reads go through a read-only `ServeFrontend` (writes reject with
`NotPrimary` until promotion) at a bounded-staleness cursor:
`read(op, max_lag_pos=K)` resolves the bound against the feed's
readable tail and waits until the serving replica has applied within
K positions of it, rejecting with typed `StaleRead` past the allowed
wait — a client can buy freshness with latency, per-read.

`promote()` is the failover half (`repl/promote.py` drives it): stop
applying, bump the feed's fencing epoch so the dead primary's late
records are rejected at the transport (fence-first bounds the drain
and closes the mid-drain zombie window), drain every remaining
readable record from the feed (torn-tail rules: an incomplete
trailing message is dropped — ship-before-ack means nothing acked was
on it), fsync the follower's WAL, and flip the frontend into write
serving (`enable_writes`). Durable-ack serving resumes exactly where
the acked history ends.
"""

from __future__ import annotations

import dataclasses
import logging
import threading

from node_replication_tpu.analysis.locks import make_condition

import numpy as np

from node_replication_tpu.durable.recovery import recover_fleet
from node_replication_tpu.fault.inject import fault_hook
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.repl.feed import FeedGapError
from node_replication_tpu.serve.errors import StaleRead
from node_replication_tpu.serve.frontend import ServeConfig, ServeFrontend
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer, pos_sampled, span

logger = logging.getLogger("node_replication_tpu")


class Follower:
    """One follower node: recovered wrapper + apply thread + read-only
    serve frontend.

        feed = DirectoryFeed(shared_dir)
        f = Follower(dispatch, feed, directory=my_dir)
        v = f.read((HM_GET, k), max_lag_pos=64)   # bounded staleness
        ...primary dies...
        f.promote()                               # now serves writes
    """

    def __init__(
        self,
        dispatch,
        feed,
        directory: str,
        config: ServeConfig | None = None,
        poll_s: float = 0.002,
        health=None,
        health_rid: int = 0,
        nr_kwargs: dict | None = None,
        auto_start: bool = True,
        name: str = "follower",
        bootstrap: bool = True,
        obs_port: int | None = None,
        obs_node_id: str | None = None,
    ):
        self.name = name
        self._feed = feed
        self._poll_s = float(poll_s)
        self.health = health
        self.health_rid = int(health_rid)

        # snapshot bootstrap (the cold-follower fast path): when the
        # feed can serve snapshots (`repl/transport.py:SocketFeed`
        # against a `FeedServer` with a snapshot source), fetch the
        # newest one strictly past what this directory already covers
        # BEFORE recovery — `recover_fleet` then digest-validates it,
        # boots from it, and the apply thread streams only
        # `[snapshot_pos, tail)` instead of replaying the whole
        # history. Bounded catch-up; a fetch failure falls back to
        # full replay (counted), never a dead follower.
        self.bootstrap_report: tuple[int, str] | None = None
        fetch = getattr(feed, "fetch_snapshot", None)
        if bootstrap and fetch is not None:
            self.bootstrap_report = self._bootstrap_snapshot(
                directory, fetch
            )

        # boot (or crash-resume) from the follower's own durability
        # directory; the WAL comes back attached at the recovered
        # tail, so applied records keep journaling seamlessly
        self.nr, self.recovery_report = recover_fleet(
            directory, dispatch, policy="batch", attach=True,
            nr_kwargs=nr_kwargs,
        )
        self._cond = make_condition("Follower._cond")
        self._applied = int(np.asarray(self.nr.log.tail))
        #: highest epoch among APPLIED records (the zombie fence
        #: floor) — starts at 0, NOT feed.epoch(): a follower seeded
        #: (or crash-resumed) behind a promotion point must still
        #: apply the older epochs' history below the fence; the floor
        #: rises as records apply, which is the documented rule
        self.epoch = 0
        self._error: BaseException | None = None
        self._stop = False
        self._promoted = False

        # durable-ack config by default: the frontend refuses durable
        # modes without a WAL, and recover_fleet attached one — so a
        # promoted follower serves the same ack contract the primary
        # did without rebuilding anything
        cfg = config or ServeConfig(durability="batch")
        if obs_port is not None:
            # fleet observability (`obs/export.py`): the follower's
            # scrape endpoint rides the frontend's exporter knob (one
            # exporter per node), labeled with the follower's name
            cfg = dataclasses.replace(cfg, obs_port=obs_port,
                                      obs_node_id=obs_node_id or name)
        self.frontend = ServeFrontend(self.nr, cfg, read_only=True)
        if self.frontend.exporter is not None:
            self.frontend.exporter.add_stats("follower", self.stats)

        reg = get_registry()
        self._m_records = reg.counter("repl.applied_records")
        self._m_ops = reg.counter("repl.applied_ops")
        self._m_dups = reg.counter("repl.duplicate_records")
        self._m_fenced = reg.counter("repl.fenced_records")
        self._m_gaps = reg.counter("repl.feed_gaps")
        self._m_stale = reg.counter("repl.stale_reads")
        self._m_errors = reg.counter("repl.apply_errors")
        self._g_lag = reg.gauge("repl.apply_lag_pos")
        self._g_staleness = reg.gauge("repl.read_staleness_pos")

        self._thread = threading.Thread(
            target=self._apply_loop, name=f"repl-apply-{name}",
            daemon=True,
        )
        if auto_start:
            self.start()

    # -------------------------------------------------------- bootstrap

    def _bootstrap_snapshot(self, directory: str,
                            fetch) -> tuple[int, str] | None:
        """Fetch the newest upstream snapshot strictly past what this
        directory's own newest snapshot covers. Returns `(pos, path)`
        when one landed (then `recover_fleet` validates its digest and
        boots from it — a corrupt transfer is skipped there, falling
        back to older bases + longer replay, never trusted blindly)."""
        from node_replication_tpu.durable.recovery import list_snapshots

        have = 0
        snaps = list_snapshots(directory)
        if snaps:
            have = snaps[0][0]
        try:
            got = fetch(directory, min_pos=have)
        except Exception as e:
            # a degraded sidecar is not fatal: the apply thread can
            # always replay the full feed instead
            get_registry().counter(
                "repl.snapshot.bootstrap_failures"
            ).inc()
            get_tracer().emit("repl-bootstrap-failed", name=self.name,
                             cause=type(e).__name__)
            logger.warning(
                "follower %s: snapshot bootstrap failed (%s: %s); "
                "falling back to full replay", self.name,
                type(e).__name__, e,
            )
            return None
        if got is None:
            return None
        pos, path = got
        get_registry().counter("repl.snapshot.bootstraps").inc()
        get_tracer().emit("repl-bootstrap", name=self.name,
                         pos=int(pos), had=have)
        return int(pos), path

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        if not self._thread.is_alive() and not self._thread.ident:
            self._thread.start()

    def stop_apply(self, timeout: float | None = 5.0) -> None:
        """Stop the apply thread (idempotent; promotion's first step)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.ident:
            self._thread.join(timeout)

    def close(self) -> None:
        """Stop applying, close the frontend, release the WAL."""
        self.stop_apply()
        self.frontend.close()
        wal = self.nr.detach_wal()
        if wal is not None:
            wal.close()

    # ------------------------------------------------------- apply loop

    def _apply_loop(self) -> None:
        while True:
            try:
                self._apply_once()
            # gap/corruption/replay failures must surface: readers
            # keep serving (bounded staleness still holds at the
            # stalled cursor) but the lag stops shrinking — record
            # the error and report replica health instead of spinning
            except Exception as e:
                self._record_failure(e)
                return
            with self._cond:
                if self._stop:
                    return
                get_clock().wait(self._cond, self._poll_s)

    def _apply_once(self, drain: bool = False) -> int:
        """Poll the feed once and apply everything readable. Returns
        the number of records applied. `drain=True` (the promotion
        path) ignores the stop flag so the backlog flushes whole."""
        fault_hook("repl-apply", -1, self)
        # _applied/epoch reads in the apply path below: the apply
        # thread is their only writer after __init__ (promote() joins
        # the thread first), so lock-free reads here cannot be stale
        records = self._feed.poll(self._applied)  # nrcheck: unshared
        applied = 0
        tail = (
            records[-1].pos + records[-1].count if records else 0
        )
        for rec in records:
            if self._apply_record(rec, feed_tail=tail):
                applied += 1
            with self._cond:
                if self._stop and not drain:
                    break
        if records:
            # nrcheck: unshared — apply thread, own write
            self._g_lag.set(max(0, tail - self._applied))
        return applied

    def _apply_record(self, rec, feed_tail: int = 0) -> bool:
        """Apply one feed record against the cursor rules; returns
        True when it advanced the applied position. `feed_tail` (the
        poll batch's end position) feeds the per-record lag stamp on
        the `repl-apply` event."""
        expected = self._applied  # nrcheck: unshared — apply-only write
        end = rec.pos + rec.count
        if rec.epoch < self.epoch:  # nrcheck: unshared — apply-only write
            # zombie fence: a record stamped by a superseded primary
            # arriving after a newer epoch was applied — reject, the
            # new primary's history owns these positions
            self._m_fenced.inc()
            get_tracer().emit("repl-fenced-record", pos=rec.pos,
                              # nrcheck: unshared — apply thread
                              epoch=rec.epoch, current=self.epoch)
            return False
        if end <= expected:
            # duplicate delivery (shipper resume / re-ship): skip
            self._m_dups.inc()
            get_tracer().emit("repl-dup", pos=rec.pos, n=rec.count)
            return False
        if rec.pos > expected:
            self._m_gaps.inc()
            raise FeedGapError(expected, rec.pos)
        ops = rec.ops()[expected - rec.pos:]  # slice the overlap away
        # the SAME combiner protocol live traffic uses — and the
        # follower's own attached WAL journals the batch inside it
        self.nr._append_and_replay(ops, 0, [])
        with self._cond:
            self._applied = expected + len(ops)
            if rec.epoch > self.epoch:
                self.epoch = rec.epoch
            self._cond.notify_all()
        self._m_records.inc()
        self._m_ops.inc(len(ops))
        tracer = get_tracer()
        # per-record hop event, sampled on `pos` like every other hop
        # (NR_TPU_TRACE_SAMPLE) — a sampled record's apply is always
        # narrated, an unsampled one never is, on every follower alike
        if tracer.enabled and pos_sampled(rec.pos):
            tracer.emit("repl-apply", pos=rec.pos, n=len(ops),
                        # nrcheck: unshared — apply thread, own write
                        epoch=rec.epoch, applied=self._applied,
                        # nrcheck: unshared — apply thread, own write
                        lag=max(0, feed_tail - self._applied),
                        name=self.name)
        return True

    def _record_failure(self, exc: BaseException) -> None:
        """Surface an apply failure (the nrlint-sanctioned worker
        exception path): error slot for callers, health report when a
        tracker is attached, counter + trace event."""
        with self._cond:
            self._error = exc
            self._cond.notify_all()
        self._m_errors.inc()
        # nrcheck: unshared — apply thread, own write
        get_tracer().emit("repl-apply-error", applied=self._applied,
                          cause=type(exc).__name__)
        logger.exception("follower %s apply failed at %d", self.name,
                         # nrcheck: unshared — apply thread, own write
                         self._applied)
        if self.health is not None:
            self.health.report_worker_exception(self.health_rid, exc)

    # ------------------------------------------------------------ state

    def applied_pos(self) -> int:
        """Logical position this follower has applied (and journaled)
        up to — the promotion election key."""
        with self._cond:
            return self._applied

    def lag(self) -> int:
        """Positions the feed holds that this follower has not yet
        applied — the apply-lag backpressure signal. Register it on
        the primary frontend's admission controller
        (`frontend.add_backpressure_source("apply", follower.lag,
        low, high)`, in-process deployments) so a follower falling
        behind slows primary admission instead of lagging without
        bound; cross-process deployments feed the same number from
        `repl.apply_lag_pos` through their own channel."""
        with self._cond:
            applied = self._applied
        return max(0, self._feed.tail_pos() - applied)

    @property
    def error(self) -> BaseException | None:
        # nrcheck: unshared — lock-free poll; one reference load
        return self._error

    @property
    def promoted(self) -> bool:
        # nrcheck: unshared — lock-free poll; one bool load
        return self._promoted

    def wait_applied(self, pos: int,
                     timeout: float | None = None) -> bool:
        """Block until the applied cursor reaches `pos` (test/ops
        barrier). False on timeout or a dead apply thread."""
        clock = get_clock()
        t_end = (
            None if timeout is None else clock.now() + timeout
        )
        with self._cond:
            while self._applied < pos:
                if self._error is not None or self._stop:
                    return False
                rem = (
                    None if t_end is None else t_end - clock.now()
                )
                if rem is not None and rem <= 0:
                    return False
                clock.wait(
                    self._cond, rem if rem is None else min(rem, 0.05)
                )
            return True

    def stats(self) -> dict:
        with self._cond:
            return {
                "applied": self._applied,
                "epoch": self.epoch,
                "promoted": self._promoted,
                "stopped": self._stop,
                "error": (
                    None if self._error is None
                    else f"{type(self._error).__name__}: {self._error}"
                ),
            }

    # ------------------------------------------------------------- read

    def read_result(self, op: tuple, rid: int = 0,
                    max_lag_pos: int | None = None,
                    min_pos: int | None = None,
                    wait_s: float = 0.5) -> tuple:
        """Bounded-staleness read; returns `(value, applied, bound)`.

        `max_lag_pos=K` resolves to the absolute bound
        `feed.tail_pos() - K` — the read reflects every op except at
        most the K newest the feed holds. An explicit `min_pos`
        (read-your-writes: pass the position an earlier ack reported)
        composes with it; the tighter bound wins. Waits up to
        `wait_s`, then rejects with `StaleRead` (counted in
        `repl.stale_reads`)."""
        bound = min_pos
        tail = None  # one feed scan per read, reused for the gauge
        if max_lag_pos is not None:
            tail = self._feed.tail_pos()
            lag_bound = max(0, tail - int(max_lag_pos))
            bound = lag_bound if bound is None else max(bound, lag_bound)
        try:
            value = self.frontend.read(op, rid=rid, min_pos=bound,
                                       wait_s=wait_s)
        except StaleRead as e:
            self._m_stale.inc()
            get_tracer().emit("repl-stale-read", rid=rid,
                              applied=e.applied_pos, bound=e.min_pos)
            raise
        applied = self.applied_pos()
        if bound is not None and applied < bound:
            # the bound was enforced against the replica's ltail
            # inside the read; the feed cursor trails it by a few
            # statements in _apply_record — report the position the
            # read actually observed, never one below its own bound
            applied = int(self.nr.ltail(rid))
        if bound is not None:
            if tail is None:
                tail = self._feed.tail_pos()
            self._g_staleness.set(max(0, tail - applied))
        return value, applied, (0 if bound is None else bound)

    def read(self, op: tuple, rid: int = 0,
             max_lag_pos: int | None = None,
             min_pos: int | None = None, wait_s: float = 0.5):
        """`read_result` returning just the value."""
        return self.read_result(op, rid=rid, max_lag_pos=max_lag_pos,
                                min_pos=min_pos, wait_s=wait_s)[0]

    # -------------------------------------------------------- promotion

    def promote(self, drain_timeout_s: float = 10.0) -> dict:
        """Take over as primary (the election already happened —
        `repl/promote.py` picks the most-advanced follower and calls
        this). Returns a report dict; also counted
        (`repl.promotions`) and emitted as `repl-promote`.

        Steps, in order: stop applying; FENCE the feed's epoch above
        every epoch ever applied, so the old primary's late records
        are rejected at the transport — fencing FIRST makes the drain
        bounded (nothing new can land) and closes the window where a
        still-live zombie slips a record into the feed mid-drain that
        a second follower would apply, silently diverging; DRAIN
        every remaining readable feed record (the dead primary's last
        shipped batches — an incomplete trailing message is dropped
        under the torn-tail rule, and ship-before-ack means no acked
        op was on it; the apply-side epoch floor stays at the OLD
        epoch until the drain completes, so the drained records are
        not self-fenced); fsync the follower's WAL (the drained
        records become durable history HERE before any new ack is
        issued); re-home write serving (`enable_writes`)."""
        t0 = get_clock().now()
        self.stop_apply()
        if self._thread.ident and self._thread.is_alive():
            # a wedged apply thread and the drain below would both
            # fold the same feed records — duplicated history; fail
            # the promotion so the election can pick another follower
            raise RuntimeError(
                f"follower {self.name}: apply thread still alive "
                f"after stop; draining now could double-apply"
            )
        # epoch/_applied reads below are safe lock-free: the apply
        # thread (their only other writer) was stopped and verified
        # dead above, so promotion is now the sole accessor
        new_epoch = self._feed.fence(
            # nrcheck: unshared — apply thread joined above
            max(self.epoch, self._feed.epoch()) + 1
        )
        # nrcheck: unshared — apply thread joined above
        with span("repl-promote-drain", applied=self._applied):
            drained = self._apply_once(drain=True)
            # keep draining until a poll finds nothing new: the feed
            # is fenced, so no writer can extend it — this terminates
            while True:
                more = self._apply_once(drain=True)
                if not more:
                    break
                drained += more
            # drain VERIFICATION: an empty poll is not proof over a
            # network feed — `SocketFeed.poll` degrades to [] on a
            # transient transport failure by design, and concluding
            # "drained" from a blip would silently drop acked records
            # the upstream still holds. The fence just succeeded over
            # the same transport and froze the tail, so re-poll until
            # the applied cursor covers the feed's readable tail;
            # past the deadline, FAIL the promotion loudly (the
            # election can pick another follower) rather than serve a
            # truncated history. (Local feeds exit on the first
            # check: their polls never lie.)
            clock = get_clock()
            t_dead = clock.now() + float(drain_timeout_s)
            while True:
                tail = int(self._feed.tail_pos())
                # nrcheck: unshared — apply thread joined above
                if self._applied >= tail:
                    break
                if clock.now() >= t_dead:
                    raise RuntimeError(
                        f"follower {self.name}: promotion drain "
                        # nrcheck: unshared — apply thread joined above
                        f"stalled at {self._applied} below the "
                        f"fenced feed tail {tail} (transport "
                        f"degraded?) — refusing to serve a "
                        f"truncated history"
                    )
                drained += self._apply_once(drain=True)
                clock.sleep(min(self._poll_s, 0.01))
        with self._cond:
            self.epoch = new_epoch
            self._promoted = True
        self.nr.wal_sync()
        self.frontend.enable_writes()
        dur = get_clock().now() - t0
        applied = self.applied_pos()
        get_registry().counter("repl.promotions").inc()
        get_tracer().emit(
            "repl-promote", epoch=new_epoch, applied=applied,
            drained_records=drained, duration_s=dur, name=self.name,
        )
        logger.warning(
            "follower %s promoted to primary: epoch %d, applied %d "
            "(%d record(s) drained, %.1fms)", self.name, new_epoch,
            applied, drained, dur * 1e3,
        )
        return {
            "name": self.name,
            "epoch": new_epoch,
            "applied": applied,
            "drained_records": drained,
            "duration_s": dur,
        }
