"""Txn durability plane: participant intent journals + the
coordinator's durable decision log.

Two tiny stores compose the crash-proofness of the 2PC layer
(`shard/txn.py`) out of disciplines the repo already trusts:

- `TxnIntentLog` — one append-only file per participant, framed
  exactly like the WAL (`u32 length | u32 crc32(payload) | payload`,
  `durable/wal.py`): a torn tail (crash mid-append) silently
  truncates — the record was never acknowledged to anyone — while a
  COMPLETE record with a bad CRC raises `TxnLogCorruptError`; crashes
  are expected, bit rot is loud. Payloads are JSON of three kinds:
  `intent` (the prepared sub-batch; the fsync of this record IS the
  yes-vote — a participant that voted can always re-derive what it
  promised), `commit-begin` (the shard WAL tail at the instant the
  participant starts applying — the dedup fence recovery scans from,
  so a crash between apply and resolve can never double-apply), and
  `resolved` (commit/abort outcome; releases the intent). Reopen
  compacts in memory: unresolved intents reload (the participant
  rebuilds their key locks), resolved outcomes are retained as an
  id → outcome index so re-driven `commit`/`abort` verbs stay
  idempotent across restarts.

- `DecisionLog` — the coordinator's decision store: one
  `dec-<txn>.json` per transaction written via `durable_publish`
  (atomic tmp + fsync + rename: fsync-before-ack, exactly the
  `durability="batch"` contract), plus the coordinator generation
  file `coord-epoch.json`. The PRESENCE of a complete decision file
  is the commit point; its ABSENCE, for a transaction stamped with a
  dead coordinator generation, means **presumed abort**. `bump_epoch`
  is the "dead generation" fence: every coordinator (re)start bumps
  it durably, so a participant holding an undecided intent from an
  older generation may abort without hearing from anyone — the
  feed-epoch fencing argument (`repl/feed.py`), replayed at the
  transaction layer.
"""

from __future__ import annotations

import json
import os
import re
import struct
import zlib

from node_replication_tpu.durable.wal import (
    WalError,
    _fsync_dir,
    durable_publish,
)

#: coordinator generation file inside a decision directory
EPOCH_FILENAME = "coord-epoch.json"

#: txn ids are path components (`dec-<txn>.json`) — restrict them
_TXN_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,120}$")

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)


class TxnLogCorruptError(WalError):
    """A COMPLETE intent-log or decision record failed validation.

    Torn tails are NOT this error (a crash mid-append truncates
    silently — nothing was promised on that record); a complete frame
    whose CRC or JSON does not check out is bit rot or tampering, and
    recovery must stop rather than guess at what was promised."""

    def __init__(self, path: str, offset: int, detail: str):
        super().__init__(
            f"corrupt txn record in {path} at byte {offset}: {detail}"
        )
        self.path = path
        self.offset = offset
        self.detail = detail


def _check_txn_id(txn: str) -> str:
    if not _TXN_ID_RE.match(txn):
        raise ValueError(f"invalid txn id {txn!r}")
    return txn


class TxnIntentLog:
    """One participant's append-only intent journal (CRC-framed)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        #: unresolved intents: txn -> {"gen", "ops", "commit_begin"}
        self._intents: dict[str, dict] = {}
        #: resolved outcomes: txn -> "commit" | "abort" (kept so a
        #: re-driven verb after restart stays idempotent)
        self._resolved: dict[str, str] = {}
        self.truncated_bytes = 0
        self._recover()
        self._f = open(path, "ab")

    # -------------------------------------------------------- recovery

    def _recover(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        off = 0
        while off < len(buf):
            if off + _HEADER.size > len(buf):
                break  # torn header: crash mid-append
            ln, crc = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > len(buf):
                break  # torn payload: crash mid-append
            payload = buf[off + _HEADER.size:end]
            if zlib.crc32(payload) != crc:
                raise TxnLogCorruptError(self.path, off, "CRC mismatch")
            try:
                rec = json.loads(payload.decode())
            except ValueError as e:
                raise TxnLogCorruptError(
                    self.path, off, f"bad JSON payload: {e}"
                ) from e
            self._fold(rec)
            off = end
        if off < len(buf):
            self.truncated_bytes = len(buf) - off
            with open(self.path, "r+b") as f:
                f.truncate(off)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(os.path.dirname(self.path) or ".")

    def _fold(self, rec: dict) -> None:
        kind, txn = rec["kind"], rec["txn"]
        if kind == "intent":
            self._intents[txn] = {
                "gen": int(rec["gen"]),
                "ops": [tuple(op) for op in rec["ops"]],
                "commit_begin": None,
            }
        elif kind == "commit-begin":
            info = self._intents.get(txn)
            if info is not None:
                info["commit_begin"] = int(rec["t0"])
        elif kind == "resolved":
            self._intents.pop(txn, None)
            self._resolved[txn] = rec["outcome"]

    # --------------------------------------------------------- appends

    def _append(self, rec: dict) -> None:
        payload = json.dumps(rec, sort_keys=True).encode()
        self._f.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        os.fsync(self._f.fileno())  # the vote/outcome IS the fsync
        self._fold(rec)

    def journal_intent(self, txn: str, gen: int, ops) -> None:
        """Durably record the prepared sub-batch. Returning from this
        call IS the yes-vote: the participant can crash at any later
        point and still re-derive what it promised to apply."""
        self._append({
            "kind": "intent", "txn": _check_txn_id(txn),
            "gen": int(gen), "ops": [list(op) for op in ops],
        })

    def journal_commit_begin(self, txn: str, t0: int) -> None:
        """Record the shard WAL tail before applying: recovery scans
        `[t0, tail)` for the intent's ops, so a crash between apply
        and resolve re-applies only what is provably missing."""
        self._append({"kind": "commit-begin", "txn": txn,
                      "t0": int(t0)})

    def journal_resolved(self, txn: str, outcome: str) -> None:
        if outcome not in ("commit", "abort"):
            raise ValueError(f"unknown outcome {outcome!r}")
        self._append({"kind": "resolved", "txn": txn,
                      "outcome": outcome})

    # ---------------------------------------------------------- lookup

    def unresolved(self) -> dict[str, dict]:
        """Prepared-but-undecided intents (shallow copies)."""
        return {t: dict(i) for t, i in self._intents.items()}

    def intent(self, txn: str) -> dict | None:
        info = self._intents.get(txn)
        return dict(info) if info is not None else None

    def outcome(self, txn: str) -> str | None:
        return self._resolved.get(txn)

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


class TxnDecision(dict):
    """One decision document: `{"txn", "outcome", "shards"}`."""


class DecisionLog:
    """The coordinator's durable decision + generation store."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _dec_path(self, txn: str) -> str:
        return os.path.join(self.directory,
                            f"dec-{_check_txn_id(txn)}.json")

    # ------------------------------------------------------- decisions

    def publish(self, txn: str, outcome: str, shards=()) -> None:
        """Durably publish the decision (atomic tmp + fsync + rename).
        This is the commit point: a caller future may resolve ONLY
        after this returns — the 2PC twin of fsync-before-ack."""
        if outcome not in ("commit", "abort"):
            raise ValueError(f"unknown outcome {outcome!r}")
        durable_publish(self._dec_path(txn), json.dumps({
            "txn": txn, "outcome": outcome,
            "shards": [int(s) for s in shards],
        }, sort_keys=True).encode())

    def load(self, txn: str) -> TxnDecision | None:
        """The decision document, or None when none was published —
        which, for a dead coordinator generation, means presumed
        abort."""
        path = self._dec_path(txn)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        try:
            return TxnDecision(json.loads(raw.decode()))
        except ValueError as e:
            # durable_publish guarantees complete documents; a torn
            # or hand-edited one must stop recovery, not presume abort
            raise TxnLogCorruptError(path, 0,
                                     f"bad decision JSON: {e}") from e

    def outcome(self, txn: str) -> str | None:
        d = self.load(txn)
        return d["outcome"] if d is not None else None

    def decisions(self) -> list[TxnDecision]:
        """Every published decision (coordinator-restart re-drive)."""
        out = []
        for fn in sorted(os.listdir(self.directory)):
            if fn.startswith("dec-") and fn.endswith(".json"):
                d = self.load(fn[len("dec-"):-len(".json")])
                if d is not None:
                    out.append(d)
        return out

    # ----------------------------------------------------- generations

    def epoch(self) -> int:
        """Current coordinator generation (0 when never bumped)."""
        path = os.path.join(self.directory, EPOCH_FILENAME)
        try:
            with open(path, "rb") as f:
                return int(json.loads(f.read().decode())["epoch"])
        except FileNotFoundError:
            return 0
        except (ValueError, KeyError) as e:
            raise TxnLogCorruptError(path, 0,
                                     f"bad epoch file: {e}") from e

    def bump_epoch(self) -> int:
        """Durably advance the generation; every coordinator
        (re)start calls this, fencing presumed-abort for all older
        undecided transactions."""
        e = self.epoch() + 1
        durable_publish(
            os.path.join(self.directory, EPOCH_FILENAME),
            json.dumps({"epoch": e}).encode(),
        )
        return e
