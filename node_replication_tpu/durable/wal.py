"""Segmented write-ahead persistence for the operation log.

The reference's recovery property is structural: any replica is the
deterministic fold of the log (SURVEY.md §5, `nr/src/log.rs`). PR 4
made that property survive *replica* death while the process lives;
this module makes it survive the process. Every appended batch is
framed into an append-only segment file, so after a kill -9 or a TPU
preemption the log itself — the source of truth — is still on disk and
`durable/recovery.py` can rebuild a bit-identical fleet from
snapshot + WAL tail.

Format (little-endian throughout):

- **segment files** `wal-<base>.seg`, named by the logical position of
  their first record (zero-padded so lexicographic order is log
  order). Header: 8-byte magic ``NRWAL001`` + int64 base position +
  int32 arg width. A segment covers `[base, next segment's base)`;
  rotation starts a new segment once the active one passes
  `segment_max_bytes`.
- **records**: `u32 length | u32 crc32(payload) | payload` where the
  payload is `int64 pos | int32 count` followed by the batch's
  `opcodes int32[count]` and `args int32[count * arg_width]`. One
  record per combiner append, written with a single `write()` call.

Crash-consistency rules on open (the framing exists for these):

- a record that runs past end-of-file in the NEWEST segment is a
  **torn tail** — the crash interrupted the write — and is truncated
  away (`wal.truncated_tail` counter, `wal-truncate` event); acks
  never covered it because acks wait for fsync.
- a complete record whose CRC mismatches, or any short read in a
  non-final segment, is **corruption** — `WalCorruptError` with the
  segment path, byte offset, and logical position, never a silent
  truncation of acknowledged history.
- record positions must chain (`pos[i+1] == pos[i] + count[i]`); a
  gap or overlap is corruption too.

fsync policy (`none | batch | always`) governs when appends become
durable: `always` fsyncs inside every `append` (an acked op is on
disk before the combiner returns), `batch` leaves fsync to an explicit
`sync()` — the serve frontend calls it once per batch before resolving
futures (`ServeConfig(durability="batch")`) — and `none` never fsyncs
until `close()` (page-cache durability only; acks are NOT
crash-durable). `durable_tail` is the logical position covered by the
last fsync — recovery's replay bound.

Reclamation is keyed to the log's GC head (`core/log.py`): the wrapper
reports head progress through `maybe_reclaim`, and whole segments
strictly below `min(head, reclaim_floor, pins…)` are deleted —
`reclaim_floor` is raised to the newest durable snapshot's position
(`durable/recovery.py:save_durable_snapshot`), because recovery needs
the WAL only from the snapshot forward; without a snapshot the floor
stays 0 and nothing is ever reclaimed (replay-from-init needs the
whole history). **Pins** (`set_pin`/`clear_pin`) let consumers that
stream the WAL hold reclamation below their own cursor: the
replication shipper (`repl/shipper.py`, this module's streaming
consumer — it ships closed segments plus a tailing feed of the active
one to follower fleets) pins its ship cursor so an unshipped segment
can never be deleted out from under an attached follower, however far
the snapshot floor and GC head have advanced.

Fault sites (`fault/inject.py`): `wal-open`, `wal-append`, `wal-fsync`
fire at the top of the corresponding operations; the `corrupt-bytes`
action calls `_corrupt_tail_bytes` to flip one byte of the last
record on disk, giving the CRC machinery something real to catch.
"""

from __future__ import annotations

import dataclasses
import os
import re
import struct
import threading

from node_replication_tpu.analysis.locks import make_lock
import zlib
from typing import Iterator, Sequence

import numpy as np

from node_replication_tpu.fault.inject import fault_hook
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer

_MAGIC = b"NRWAL001"
_SEG_HEADER = struct.Struct("<8sqi")  # magic, base pos, arg_width
_REC_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
_REC_PREFIX = struct.Struct("<qi")  # logical pos, count
_SEG_RE = re.compile(r"^wal-(\d{20})\.seg$")

# Sanity bound on a record payload: a length field past this is frame
# garbage, not a real batch (the largest legal batch is bounded by the
# log's appendable capacity, far below this).
MAX_PAYLOAD_BYTES = 1 << 26

FSYNC_POLICIES = ("none", "batch", "always")

DEFAULT_SEGMENT_BYTES = 4 << 20


class WalError(RuntimeError):
    """WAL usage/IO failure (gap appends, closed WAL, disk errors)."""


class WalCorruptError(WalError):
    """A WAL record failed validation somewhere a torn tail cannot
    explain. Carries exactly where, so operators can decide what the
    blast radius is instead of silently losing acknowledged history."""

    def __init__(self, segment: str, offset: int, pos: int, detail: str):
        super().__init__(
            f"corrupt WAL record in {segment} at byte {offset} "
            f"(logical position {pos}): {detail}"
        )
        self.segment = segment
        self.offset = offset
        self.pos = pos
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class WalRecord:
    """One decoded append batch: `count` ops at logical `pos`."""

    pos: int
    opcodes: np.ndarray  # int32[count]
    args: np.ndarray  # int32[count, arg_width]

    @property
    def count(self) -> int:
        return int(self.opcodes.shape[0])

    def ops(self) -> list[tuple]:
        """The batch as host `(opcode, *args)` tuples — the same shape
        the combiner appends, so recovery replays through the same
        dispatch scan (`core/replica._append_and_replay`)."""
        return [
            (int(self.opcodes[i]), *(int(a) for a in self.args[i]))
            for i in range(self.count)
        ]


def _segment_name(base: int) -> str:
    return f"wal-{base:020d}.seg"


def _fsync_dir(path: str) -> None:
    """fsync a directory so entry creation/removal is itself durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def durable_publish(path: str, data: bytes, fsync: bool = True) -> None:
    """Atomically publish `data` at `path`: tmp file + flush (+ fsync
    when `fsync`) + `os.replace` (+ parent-dir fsync). THE hardened
    publish path for every small control file the durability and
    replication planes expose to other processes — snapshots
    (`core/checkpoint.py` inlines the same discipline), the feed's
    `EPOCH` fence and `HEARTBEAT` beacon (`repl/feed.py`), and fetched
    snapshot files (`repl/transport.py`). A reader can NEVER observe a
    torn file: it sees the old content or the new, and with `fsync`
    the new content survives a crash of the publisher. `fsync=False`
    keeps the rename atomicity (no torn reads) without the per-publish
    disk flush — right for high-rate beacons whose loss is harmless
    but whose tearing is not. The tmp name is pid- AND thread-tagged
    so concurrent publishers — other processes, or two server
    connection threads fencing the same feed — cannot corrupt each
    other's staging; a failed publish removes its tmp file."""
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    if fsync:
        _fsync_dir(os.path.dirname(path) or ".")


class WriteAheadLog:
    """Append-only segmented WAL for encoded op batches.

    Thread-safe: appends arrive under the wrapper's combiner lock, but
    `sync()` (serve workers), `records()` (recovery verification) and
    `maybe_reclaim` (exec rounds) may race them, so every public entry
    takes the WAL's own lock.
    """

    def __init__(
        self,
        directory: str,
        policy: str = "batch",
        arg_width: int = 3,
        segment_max_bytes: int = DEFAULT_SEGMENT_BYTES,
    ):
        if policy not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {policy!r} "
                f"(policies: {', '.join(FSYNC_POLICIES)})"
            )
        self.dir = directory
        self.policy = policy
        self.arg_width = int(arg_width)
        self.segment_max_bytes = int(segment_max_bytes)
        #: newest durable snapshot position (`save_durable_snapshot`
        #: raises it); reclamation never passes min(GC head, floor)
        self.reclaim_floor = 0
        # named reclamation pins (`set_pin`): each holds the effective
        # reclaim floor at or below its position while present — the
        # shipper's ship cursor (`repl/shipper.py`) lives here
        self._pins: dict[str, int] = {}
        # Instrument/trace handles come through module-level get_*
        # accessors the analyzer cannot type through:
        # nrcheck: lock-order WriteAheadLog._lock -> Tracer._lock — fsync/reclaim emit trace events under the lock
        # nrcheck: lock-order WriteAheadLog._lock -> Counter._lock — append/fsync counters bump under the lock
        # nrcheck: lock-order WriteAheadLog._lock -> Histogram._lock — fsync durations observe under the lock
        self._lock = make_lock("WriteAheadLog._lock")
        self._fh = None  # active segment append handle
        self._segments: list[tuple[int, str]] = []  # (base, path) sorted
        self._tail = 0  # logical pos after the last written record
        self._durable = 0  # logical pos covered by the last fsync
        self._closed = False
        self._failed: BaseException | None = None
        #: bytes dropped by torn-tail truncation at the last open
        #: (recovery reports surface it)
        self.truncated_bytes = 0

        reg = get_registry()
        self._m_appended = reg.counter("wal.appended")
        self._m_records = reg.counter("wal.records")
        self._m_synced = reg.counter("wal.synced")
        self._m_truncated = reg.counter("wal.truncated_tail")
        self._m_reclaimed = reg.counter("wal.reclaimed_segments")
        self._m_fsync = reg.histogram("wal.fsync_s")

        fault_hook("wal-open", -1, self)
        os.makedirs(self.dir, exist_ok=True)
        self._open_and_recover()

    # ------------------------------------------------------------ open

    def _list_segments(self) -> list[tuple[int, str]]:
        segs = []
        for name in os.listdir(self.dir):
            m = _SEG_RE.match(name)
            if m:
                segs.append((int(m.group(1)),
                             os.path.join(self.dir, name)))
        segs.sort()
        return segs

    def _open_and_recover(self) -> None:
        """Scan every segment, validate framing, truncate a torn tail,
        and position the append handle after the last valid record."""
        with self._lock:
            self._segments = self._list_segments()
            truncated = 0
            pos = None
            for i, (base, path) in enumerate(self._segments):
                is_last = i == len(self._segments) - 1
                pos, cut = self._scan_segment(
                    base, path, expect_pos=pos, may_truncate=is_last
                )
                truncated += cut
            if pos is None:
                pos = 0
            self._tail = pos
            # everything that survived the scan is on disk already;
            # the durable cursor restarts at the recovered tail
            self._durable = pos
            # a torn-header segment removed itself from disk; drop it
            # from the index too
            self._segments = [s for s in self._segments
                              if os.path.exists(s[1])]
            if self._segments:
                self._fh = open(self._segments[-1][1], "ab")
            self.truncated_bytes = truncated
            n_segments = len(self._segments)
        if truncated:
            self._m_truncated.inc()
        get_tracer().emit(
            "wal-open", dir=self.dir, segments=n_segments,
            tail=pos, truncated_bytes=truncated,
            policy=self.policy,
        )

    def _scan_segment(self, base: int, path: str, expect_pos: int | None,
                      may_truncate: bool) -> tuple[int, int]:
        """Validate one segment; returns `(next logical pos, truncated
        bytes)`. `may_truncate` (final segment only) downgrades a
        record that runs past EOF from corruption to a torn tail."""
        with open(path, "rb") as f:
            data = f.read()

        def torn(off: int, pos: int, detail: str) -> int:
            if not may_truncate:
                raise WalCorruptError(path, off, pos, detail)
            dropped = len(data) - off
            os.truncate(path, off)
            get_tracer().emit(
                "wal-truncate", segment=os.path.basename(path),
                offset=off, dropped_bytes=dropped, pos=pos,
            )
            return dropped

        if len(data) < _SEG_HEADER.size:
            # header never finished: an empty rotation cut short. An
            # empty file is not a valid segment — drop it entirely
            # (the caller prunes its index entry)
            cut = torn(0, base, "segment header torn")
            os.remove(path)
            return (base if expect_pos is None else expect_pos), cut
        magic, hdr_base, aw = _SEG_HEADER.unpack_from(data, 0)
        if magic != _MAGIC or hdr_base != base:
            raise WalCorruptError(
                path, 0, base, f"bad segment header (magic {magic!r}, "
                               f"base {hdr_base})"
            )
        if aw != self.arg_width:
            raise WalCorruptError(
                path, 0, base,
                f"segment arg_width {aw} != WAL arg_width "
                f"{self.arg_width}",
            )
        if expect_pos is not None and base != expect_pos:
            raise WalCorruptError(
                path, 0, base,
                f"segment base {base} does not chain from previous "
                f"segment end {expect_pos}",
            )
        off = _SEG_HEADER.size
        pos = base
        while off < len(data):
            if off + _REC_HEADER.size > len(data):
                return pos, torn(off, pos, "record header torn")
            length, crc = _REC_HEADER.unpack_from(data, off)
            if length < _REC_PREFIX.size or length > MAX_PAYLOAD_BYTES:
                return pos, torn(
                    off, pos, f"implausible record length {length}"
                )
            body = data[off + _REC_HEADER.size:
                        off + _REC_HEADER.size + length]
            if len(body) < length:
                return pos, torn(off, pos, "record payload torn")
            if zlib.crc32(body) != crc:
                # a COMPLETE record with a bad checksum is bit rot, not
                # an interrupted write — never silently truncated
                raise WalCorruptError(
                    path, off, pos, "payload CRC mismatch"
                )
            rpos, count = _REC_PREFIX.unpack_from(body, 0)
            want = _REC_PREFIX.size + 4 * count * (1 + self.arg_width)
            if count < 1 or length != want:
                raise WalCorruptError(
                    path, off, pos,
                    f"record shape invalid (count {count}, length "
                    f"{length} != {want})",
                )
            if rpos != pos:
                raise WalCorruptError(
                    path, off, pos,
                    f"record position {rpos} does not chain (expected "
                    f"{pos})",
                )
            pos += count
            off += _REC_HEADER.size + length
        return pos, 0

    # ---------------------------------------------------------- append

    @property
    def tail(self) -> int:
        """Logical position after the last written (not necessarily
        fsynced) record."""
        return self._tail

    @property
    def durable_tail(self) -> int:
        """Logical position covered by the last fsync — the recovery
        guarantee boundary for `always`/`batch` acks."""
        return self._durable

    @property
    def base(self) -> int:
        """First logical position the WAL still holds (reclamation
        deletes whole segments below the floor)."""
        return self._segments[0][0] if self._segments else self._tail

    def fsync_lag(self) -> int:
        """Positions written but not yet fsynced (`tail -
        durable_tail`) — the journal's unfsynced backlog. Exported as
        an overload-plane backpressure signal (`serve/overload.py`):
        the serve frontend auto-registers this behind its
        `wal_lag_low/high` watermarks so admission throttles before
        the backlog (and the ship/ack pipeline behind it) can grow
        unbounded. GIL-atomic int reads; no lock needed."""
        return max(0, self._tail - self._durable)

    def _check_usable(self) -> None:
        if self._closed:
            raise WalError("WAL is closed")
        if self._failed is not None:
            raise WalError(
                f"WAL failed a previous write and is fenced: "
                f"{self._failed}"
            )

    def _rotate(self, base: int) -> None:
        """Finalize the active segment (flush + fsync: a rotated-away
        segment is immutable history) and start a new one at `base`."""
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
        path = os.path.join(self.dir, _segment_name(base))
        # nrlint: disable=lock-discipline — caller (append) holds the lock
        self._fh = open(path, "ab")
        self._fh.write(_SEG_HEADER.pack(_MAGIC, base, self.arg_width))
        self._segments.append((base, path))
        _fsync_dir(self.dir)

    def append(self, pos: int, ops: Sequence[tuple]) -> None:
        """Persist one combiner batch starting at logical `pos`.

        `pos` must equal the WAL tail (records chain densely) — except
        for the very first record of an empty WAL, which may start at
        any position (`attach_wal` backfills from the ring, recovery
        attaches at the recovered tail). Policy `always` fsyncs before
        returning, so the caller's ack is durable."""
        if not ops:
            return
        with self._lock:
            self._check_usable()
            fault_hook("wal-append", -1, self)
            pos = int(pos)
            if self._segments and pos != self._tail:
                raise WalError(
                    f"append at {pos} does not chain from WAL tail "
                    f"{self._tail} (gap or overlap)"
                )
            n = len(ops)
            opcodes = np.asarray([int(o[0]) for o in ops], np.int32)
            args = np.zeros((n, self.arg_width), np.int32)
            for i, o in enumerate(ops):
                vals = o[1:1 + self.arg_width]
                args[i, :len(vals)] = vals
            payload = (
                _REC_PREFIX.pack(pos, n)
                + opcodes.tobytes() + args.tobytes()
            )
            record = _REC_HEADER.pack(
                len(payload), zlib.crc32(payload)
            ) + payload
            if (self._fh is None
                    or self._fh.tell() >= self.segment_max_bytes):
                # `pos == self._tail` when segments exist (chain check
                # above); an empty WAL adopts the first record's pos
                if not self._segments:
                    self._tail = pos
                self._rotate(pos)
            start = self._fh.tell()
            try:
                self._fh.write(record)
                self._tail = pos + n
                if self.policy == "always":
                    self._fsync_locked()
            except OSError as e:
                self._tail = pos
                # roll the partial write back so the frame stays
                # parseable; if even that fails, fence the WAL — a
                # half-written record must never be appended past
                try:
                    self._fh.flush()
                    os.truncate(self._fh.fileno(), start)
                    self._fh.seek(start)
                except OSError:
                    self._failed = e
                raise WalError(f"WAL append failed: {e}") from e
            self._m_records.inc()
            self._m_appended.inc(n)

    def sync(self) -> int:
        """fsync buffered records; returns the new `durable_tail`.
        The serve frontend's per-batch durable-ack barrier
        (`ServeConfig(durability="batch")`)."""
        with self._lock:
            self._check_usable()
            if self._fh is None or self._durable >= self._tail:
                return self._durable
            self._fsync_locked()
            return self._durable

    def _fsync_locked(self) -> None:
        fault_hook("wal-fsync", -1, self)
        # injected clock (the satellite narrowing of the old
        # perf_counter exemption): under RealClock this is the same
        # monotonic interval; under SimClock the fsync span measures
        # virtual time like every other timed quantity in the
        # subsystem, so sim timelines stay coherent
        t0 = get_clock().now()
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError as e:
            # nrlint: disable=lock-discipline — caller (append/sync) holds the lock
            self._failed = e
            raise WalError(f"WAL fsync failed: {e}") from e
        dur = get_clock().now() - t0
        # nrlint: disable=lock-discipline — caller (append/sync) holds the lock
        self._durable = self._tail
        self._m_synced.inc()
        self._m_fsync.observe(dur)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.emit("wal-sync", duration_s=dur,
                        synced_to=self._durable)

    # ------------------------------------------------------------ read

    def records(self, start: int = 0) -> Iterator[WalRecord]:
        """Decode records at logical positions >= `start`, in order.
        Records straddling `start` are sliced. Reads fresh handles, so
        a live WAL can be scanned concurrently (flush first for
        buffered tails: `sync()` or policy `always`)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
            segments = list(self._segments)
        for base, path in segments:
            with open(path, "rb") as f:
                data = f.read()
            off = _SEG_HEADER.size
            while off + _REC_HEADER.size <= len(data):
                length, crc = _REC_HEADER.unpack_from(data, off)
                body = data[off + _REC_HEADER.size:
                            off + _REC_HEADER.size + length]
                if len(body) < length or zlib.crc32(body) != crc:
                    return  # unsynced torn tail of a live WAL
                pos, count = _REC_PREFIX.unpack_from(body, 0)
                opc = np.frombuffer(
                    body, np.int32, count, _REC_PREFIX.size
                )
                args = np.frombuffer(
                    body, np.int32, count * self.arg_width,
                    _REC_PREFIX.size + 4 * count,
                ).reshape(count, self.arg_width)
                if pos + count > start:
                    lo = max(0, start - pos)
                    yield WalRecord(pos + lo, opc[lo:].copy(),
                                    args[lo:].copy())
                off += _REC_HEADER.size + length

    # ------------------------------------------------------- reclaim

    def set_pin(self, name: str, pos: int) -> None:
        """Hold reclamation at or below logical `pos` under `name`.
        A streaming consumer (the replication shipper, `repl/`) pins
        its cursor BEFORE reading and advances the pin only after the
        read content is safely shipped, so reclamation can never
        outrun it. Re-pinning the same name moves it."""
        with self._lock:
            self._pins[name] = int(pos)

    def clear_pin(self, name: str) -> None:
        """Release a reclamation pin (missing names are a no-op)."""
        with self._lock:
            self._pins.pop(name, None)

    def pins(self) -> dict:
        """Current reclamation pins (name -> position)."""
        with self._lock:
            return dict(self._pins)

    def _pin_floor_locked(self, floor: int) -> int:
        if self._pins:
            floor = min(floor, min(self._pins.values()))
        return floor

    def reclaim(self, floor: int) -> int:
        """Delete whole segments strictly below logical `floor` (a
        segment is deletable only when a NEWER segment exists and
        starts at or below the floor). The floor is re-clamped to the
        pins UNDER the lock — a pin set between the caller computing
        its floor and this deletion still protects its segments (the
        reclaim-vs-ship race). Returns segments deleted."""
        deleted = 0
        with self._lock:
            floor = self._pin_floor_locked(int(floor))
            while (len(self._segments) >= 2
                   and self._segments[1][0] <= floor):
                base, path = self._segments.pop(0)
                os.remove(path)
                deleted += 1
            if deleted:
                _fsync_dir(self.dir)
        if deleted:
            self._m_reclaimed.inc(deleted)
            get_tracer().emit("wal-reclaim", deleted=deleted,
                              floor=floor)
        return deleted

    def maybe_reclaim(self, gc_head: int) -> int:
        """GC-head coupling (`core/replica._exec_round`): reclaim up to
        `min(gc_head, reclaim_floor, pins…)` — the log has logically
        consumed the prefix, a durable snapshot covers it, AND every
        attached streaming consumer has shipped past it. One
        uncontended lock acquire + O(1) when nothing is reclaimable
        (the per-round hot-path case); the pin floor must be read
        under the lock — iterating `_pins` while `clear_pin` pops
        concurrently raises."""
        floor = min(int(gc_head), self.reclaim_floor)
        with self._lock:
            floor = self._pin_floor_locked(floor)
            if len(self._segments) < 2 or self._segments[1][0] > floor:
                return 0
        return self.reclaim(floor)

    # ------------------------------------------------------- lifecycle

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.flush()
                    # the close-path fsync IS the durability critical
                    # section: _lock must stay held so no writer can
                    # append between the final flush and the fsync
                    # nrlint: disable=lock-held-across-blocking-call
                    os.fsync(self._fh.fileno())
                    self._durable = self._tail
                except OSError:
                    pass
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "WriteAheadLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "tail": self._tail,
                "durable_tail": self._durable,
                "base": self.base,
                "segments": len(self._segments),
                "policy": self.policy,
                "reclaim_floor": self.reclaim_floor,
                "pins": dict(self._pins),
            }

    # ------------------------------------------------- fault plumbing

    def _corrupt_tail_bytes(self) -> None:
        """`corrupt-bytes` fault action (`fault/inject.py`): flip one
        byte of the last record on disk so the next open must catch it
        through the CRC. Test machinery, deliberately blunt."""
        if self._fh is not None:
            self._fh.flush()
        if not self._segments:
            return
        path = self._segments[-1][1]
        size = os.path.getsize(path)
        if size <= _SEG_HEADER.size:
            return
        with open(path, "r+b") as f:
            f.seek(size - 3)
            b = f.read(1)
            f.seek(size - 3)
            f.write(bytes([b[0] ^ 0xFF]))
