"""Crash-consistent recovery: snapshot base + WAL tail, bit-identical.

The orchestrator over the two durable artifacts a fleet leaves on
disk:

- **snapshots** (`snap-<tail>.npz`, written by `save_durable_snapshot`
  through the hardened `core/checkpoint.py:save_snapshot`): the log
  ring + cursors + replica states at one position, digest-sealed.
- **the WAL** (`<dir>/wal/`, `durable/wal.py`): every combiner append
  since, with a durable tail bounded by fsync policy.

`recover_fleet` rebuilds a `NodeReplicated` after a crash:

1. load the NEWEST snapshot that passes integrity validation
   (`SnapshotCorruptError` candidates are skipped, not fatal — an
   older good snapshot plus a longer WAL replay reaches the same
   state, because replay is deterministic);
2. open the WAL (torn tails truncate here) and replay every record in
   `[snapshot_pos, durable_tail)` through the SAME combiner protocol
   live traffic uses (`_append_and_replay` → the dispatch scan /
   combined engines), so the restart is bit-identical to a fleet that
   never died;
3. re-attach the WAL at the recovered tail so the instance keeps
   journaling where it left off.

The recovery floor invariant: `save_durable_snapshot` raises the
WAL's `reclaim_floor` to the snapshot position AFTER the snapshot is
durably published, so at every instant the disk holds a valid base +
a WAL covering `[base, durable_tail)` — the crash window never has a
gap. The serve layer reopens mid-traffic state through
`ServeFrontend.from_recovery`, which wraps this.
"""

from __future__ import annotations

import dataclasses
import os
import re

import numpy as np

from node_replication_tpu.core.checkpoint import (
    SnapshotCorruptError,
    load_snapshot,
    peek_spec,
)
from node_replication_tpu.core.replica import NodeReplicated
from node_replication_tpu.durable.wal import WalError, WriteAheadLog
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer, span

_SNAP_RE = re.compile(r"^snap-(\d{20})\.npz$")

#: WAL subdirectory inside a durability directory.
WAL_SUBDIR = "wal"


def snapshot_path(directory: str, pos: int) -> str:
    return os.path.join(directory, f"snap-{int(pos):020d}.npz")


def list_snapshots(directory: str) -> list[tuple[int, str]]:
    """`(pos, path)` pairs, newest first."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _SNAP_RE.match(name)
        if m:
            out.append((int(m.group(1)),
                        os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def save_durable_snapshot(nr, directory: str,
                          keep: int = 2) -> str:
    """Checkpoint `nr` into `directory` as `snap-<tail>.npz` and raise
    the WAL reclaim floor to the snapshot position (segments wholly
    below it delete as GC head passes). Keeps the newest `keep`
    snapshots and prunes the rest — but only AFTER the new one is
    durably published, so a crash mid-prune still finds a valid base.
    Returns the snapshot path."""
    os.makedirs(directory, exist_ok=True)
    with nr._lock:  # pin tail across name + save (lock is reentrant)
        tail = int(np.asarray(nr.log.tail))
        path = snapshot_path(directory, tail)
        nr.checkpoint(path)
    get_registry().counter("recovery.snapshots").inc()
    get_tracer().emit("durable-snapshot", pos=tail, path=path)
    wal = getattr(nr, "wal", None)
    if wal is not None:
        wal.reclaim_floor = max(wal.reclaim_floor, tail)
        wal.maybe_reclaim(int(np.asarray(nr.log.head)))
    for _, old in list_snapshots(directory)[max(1, int(keep)):]:
        if old != path:
            os.remove(old)
    return path


@dataclasses.dataclass
class RecoveryReport:
    """What one `recover_fleet` run found and did (JSON-safe)."""

    directory: str
    snapshot: str | None
    snapshot_pos: int
    skipped_snapshots: list  # [(path, reason), ...] corrupt candidates
    wal_records: int
    wal_ops: int
    wal_truncated_bytes: int
    tail: int
    duration_s: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def recover_fleet(
    directory: str,
    dispatch,
    policy: str = "batch",
    attach: bool = True,
    nr_kwargs: dict | None = None,
) -> tuple[NodeReplicated, RecoveryReport]:
    """Rebuild a `NodeReplicated` from `directory` (snapshots + WAL).

    Missing/empty directory boots a fresh fleet (and starts journaling
    into it when `attach=True`). `nr_kwargs` configures the wrapper
    when no snapshot pins the spec (and engine/debug knobs always).
    The returned instance has the reopened WAL attached at its tail,
    so serving can resume immediately (`ServeFrontend.from_recovery`).
    """
    t0 = get_clock().now()
    kw = dict(nr_kwargs or {})
    os.makedirs(directory, exist_ok=True)
    skipped: list = []
    nr = None
    snap_path = None
    snap_pos = 0
    for pos, path in list_snapshots(directory):
        try:
            spec = peek_spec(path)
            cand = NodeReplicated(
                dispatch,
                n_replicas=spec.n_replicas,
                log_entries=spec.capacity,
                gc_slack=spec.gc_slack,
                **{k: v for k, v in kw.items()
                   if k not in ("n_replicas", "log_entries",
                                "gc_slack")},
            )
            _, cand.log, cand.states = load_snapshot(path, cand.states)
            nr, snap_path, snap_pos = cand, path, int(
                np.asarray(cand.log.tail)
            )
            break
        except SnapshotCorruptError as e:
            skipped.append((path, str(e)))
    if nr is None:
        nr = NodeReplicated(dispatch, **kw)
    wal = WriteAheadLog(
        os.path.join(directory, WAL_SUBDIR), policy=policy,
        arg_width=dispatch.arg_width,
    )
    if wal.tail > snap_pos and wal.base > snap_pos:
        raise WalError(
            f"WAL covers [{wal.base}, {wal.tail}) but the newest "
            f"valid snapshot is at {snap_pos}: entries "
            f"[{snap_pos}, {wal.base}) are on neither artifact "
            f"(reclaim outran the snapshot?)"
        )
    records = 0
    ops_replayed = 0
    with span("recovery", dir=directory, snapshot_pos=snap_pos,
              wal_tail=wal.tail) as sp:
        for rec in wal.records(start=snap_pos):
            expect = snap_pos + ops_replayed
            if rec.pos != expect:
                raise WalError(
                    f"WAL replay position {rec.pos} does not chain "
                    f"from recovered tail {expect}"
                )
            # the SAME combiner-round protocol live appends use:
            # GC-wait, encode, append, replay-to-target (no response
            # destinations — a crash drops in-flight deliveries,
            # exactly like `recover`'s crash semantics)
            nr._append_and_replay(rec.ops(), 0, [])
            records += 1
            ops_replayed += rec.count
        nr.sync()
        sp.add(records=records, ops=ops_replayed)
    if snap_path is not None:
        wal.reclaim_floor = max(wal.reclaim_floor, snap_pos)
    if attach:
        nr.attach_wal(wal)  # backfills [wal.tail, tail) when snapshot
        # was ahead of the WAL (policy `none`, lost unsynced tail)
    else:
        wal.close()
    dur = get_clock().now() - t0
    reg = get_registry()
    reg.counter("recovery.runs").inc()
    reg.counter("wal.replayed").inc(ops_replayed)
    reg.histogram("recovery.restore_s").observe(dur)
    report = RecoveryReport(
        directory=directory,
        snapshot=snap_path,
        snapshot_pos=snap_pos,
        skipped_snapshots=skipped,
        wal_records=records,
        wal_ops=ops_replayed,
        wal_truncated_bytes=wal.truncated_bytes,
        tail=int(np.asarray(nr.log.tail)),
        duration_s=dur,
    )
    get_tracer().emit(
        "recovery-done", snapshot_pos=snap_pos, records=records,
        ops=ops_replayed, tail=report.tail, duration_s=dur,
        skipped=len(skipped),
        truncated_bytes=wal.truncated_bytes,
    )
    return nr, report
