"""durable/: write-ahead persistence + crash-consistent recovery.

The durability plane (ISSUE 5): the repo's recovery property — any
replica is the deterministic fold of the log — made to survive the
PROCESS, not just a replica. Every combiner append is journaled into a
segmented, CRC-framed write-ahead log (`durable.wal`), snapshots are
fsync-published and digest-sealed (`core/checkpoint.py`), and a
restart replays snapshot + WAL tail through the same dispatch scan
live traffic uses (`durable.recovery`) — bit-identical to a fleet
that never died. The serve layer rides it for durable acks
(`ServeConfig(durability="batch"|"always")`: a future resolves only
after its records are fsynced) and reopens mid-traffic state with
`ServeFrontend.from_recovery`.

    from node_replication_tpu.durable import (
        WriteAheadLog, recover_fleet, save_durable_snapshot,
    )

    nr.attach_wal(WriteAheadLog(dir + "/wal", policy="batch"))
    ...traffic...
    save_durable_snapshot(nr, dir)      # base + floor for reclamation
    ...kill -9...
    nr2, report = recover_fleet(dir, dispatch)   # bit-identical
"""

from node_replication_tpu.durable.recovery import (
    RecoveryReport,
    WAL_SUBDIR,
    list_snapshots,
    recover_fleet,
    save_durable_snapshot,
    snapshot_path,
)
from node_replication_tpu.durable.txnlog import (
    DecisionLog,
    TxnIntentLog,
    TxnLogCorruptError,
)
from node_replication_tpu.durable.wal import (
    FSYNC_POLICIES,
    WalCorruptError,
    WalError,
    WalRecord,
    WriteAheadLog,
)

__all__ = [
    "DecisionLog",
    "FSYNC_POLICIES",
    "RecoveryReport",
    "TxnIntentLog",
    "TxnLogCorruptError",
    "WAL_SUBDIR",
    "WalCorruptError",
    "WalError",
    "WalRecord",
    "WriteAheadLog",
    "list_snapshots",
    "recover_fleet",
    "save_durable_snapshot",
    "snapshot_path",
]
