"""Delta-debugging shrinker: minimize a failing schedule.

A sweep failure arrives as a 30-60 step schedule; most steps are
noise. `shrink_case` runs the classic ddmin loop over `spec.steps`:
repeatedly try removing chunks (halving granularity down to single
steps), keep any candidate that still reproduces a violation of the
SAME property, and stop at a 1-minimal schedule — every remaining
step is load-bearing. The step vocabulary is closed under
subsequences by construction (`properties.py` interprets any step
defensively: a `promote` without a `kill` promotes anyway, an `apply`
with nothing shipped is a no-op), so every candidate is a valid case.

Determinism makes this sound: a candidate either reproduces or it
does not — there is no flaky middle, so no retries and no
probability calculus. Cost is bounded by `max_runs` interpreter runs.
"""

from __future__ import annotations

import dataclasses

from node_replication_tpu.sim.properties import (
    CaseResult,
    CaseSpec,
    run_case,
)


@dataclasses.dataclass
class ShrinkReport:
    original_steps: int
    shrunk_steps: int
    runs: int
    spec: CaseSpec
    result: CaseResult  # the shrunk spec's (still-failing) result

    def as_dict(self) -> dict:
        return {
            "original_steps": self.original_steps,
            "shrunk_steps": self.shrunk_steps,
            "runs": self.runs,
            "spec": self.spec.as_dict(),
            "violations": [v.as_dict()
                           for v in self.result.violations],
            "digest": self.result.digest,
        }


def _with_steps(spec: CaseSpec, steps: list) -> CaseSpec:
    return dataclasses.replace(spec, steps=list(steps))


def shrink_case(spec: CaseSpec, max_runs: int = 250) -> ShrinkReport:
    """ddmin over `spec.steps`, preserving at least one violation of
    the original run's property set. Returns the minimal spec plus
    its (failing) result."""
    base = run_case(spec)
    runs = 1
    if base.ok:
        raise ValueError("shrink_case needs a FAILING spec")
    props = {v.prop for v in base.violations}

    def fails(steps: list):
        nonlocal runs
        runs += 1
        res = run_case(_with_steps(spec, steps))
        if any(v.prop in props for v in res.violations):
            return res
        return None

    steps = list(spec.steps)
    best = base
    chunk = max(1, len(steps) // 2)
    while chunk >= 1 and runs < max_runs:
        i = 0
        shrunk_this_pass = False
        while i < len(steps) and runs < max_runs:
            candidate = steps[:i] + steps[i + chunk:]
            if not candidate:
                i += chunk
                continue
            res = fails(candidate)
            if res is not None:
                steps = candidate
                best = res
                shrunk_this_pass = True
                # retry the same offset: the next chunk slid into it
            else:
                i += chunk
        if chunk == 1 and not shrunk_this_pass:
            break
        if not shrunk_this_pass or chunk > 1:
            chunk = max(1, chunk // 2) if chunk > 1 else 0
    final = _with_steps(spec, steps)
    return ShrinkReport(
        original_steps=len(spec.steps),
        shrunk_steps=len(steps),
        runs=runs,
        spec=final,
        result=best,
    )
