"""Pure-numpy oracle twins of the bundled models.

The differential half of the property harness (`sim/properties.py`):
each oracle reimplements one model's transition semantics on host
numpy arrays, line-for-line against the jax model (`models/*.py`), so
a simulated run can check BOTH

- every acked response against the oracle's response at the same
  logical position (the sequential-consistency differential — the
  harness serializes submissions, so log order == submission order),
- the final device state bit-for-bit against the oracle's arrays
  (`arrays()` mirrors the model's state pytree leaf names, shapes,
  and dtypes exactly).

Keeping the oracles numpy-only is the point: they share NO code with
the system under test (no jax, no `Dispatch`, no scan/window
engines), so an engine bug cannot cancel itself out in the check.

Op encoding matches the wire form the wrappers take: `(opcode,
*args)` host tuples, write and read opcode namespaces separate (the
model-module constants: HM_PUT/HM_GET, ST_PUSH/ST_PEEK, ...).
"""

from __future__ import annotations

import numpy as np


class Oracle:
    """One model's host-side twin. `apply` mutates and returns the
    response; `read` answers a read opcode; `arrays()` exposes the
    exact state-pytree mirror; `copy()` forks (crash branches)."""

    model = "?"

    def apply(self, op: tuple) -> int:
        raise NotImplementedError

    def read(self, op: tuple) -> int:
        raise NotImplementedError

    def arrays(self) -> dict:
        raise NotImplementedError

    def copy(self) -> "Oracle":
        raise NotImplementedError


class HashmapOracle(Oracle):
    """`models/hashmap.py`: dense table, PUT/REMOVE/GET, `k % K`."""

    model = "hashmap"

    def __init__(self, n_keys: int):
        self.n = int(n_keys)
        self.values = np.zeros(self.n, np.int32)
        self.present = np.zeros(self.n, np.bool_)

    def apply(self, op):
        code, k = int(op[0]), int(op[1]) % self.n
        if code == 1:  # HM_PUT
            self.values[k] = np.int32(op[2])
            self.present[k] = True
            return 0
        if code == 2:  # HM_REMOVE
            was = int(self.present[k])
            self.values[k] = 0
            self.present[k] = False
            return was
        raise ValueError(f"unknown hashmap write opcode {code}")

    def read(self, op):
        k = int(op[1]) % self.n  # HM_GET
        return int(self.values[k]) if self.present[k] else -1

    def arrays(self):
        return {"values": self.values, "present": self.present}

    def copy(self):
        o = HashmapOracle(self.n)
        o.values = self.values.copy()
        o.present = self.present.copy()
        return o


class StackOracle(Oracle):
    """`models/stack.py`: fixed-capacity buffer + top cursor. Note the
    model's exact quirks: an overflowing push leaves the buffer
    untouched and responds -1; pop leaves the popped slot's bytes in
    place (only the cursor moves) — `arrays()` must mirror both for
    the bit-identity check to be honest."""

    model = "stack"

    def __init__(self, capacity: int):
        self.cap = int(capacity)
        self.buf = np.zeros(self.cap, np.int32)
        self.top = 0

    def apply(self, op):
        code = int(op[0])
        if code == 1:  # ST_PUSH
            if self.top < self.cap:
                self.buf[self.top] = np.int32(op[1])
                self.top += 1
                return self.top
            return -1
        if code == 2:  # ST_POP
            if self.top > 0:
                self.top -= 1
                return int(self.buf[self.top])
            return -1
        raise ValueError(f"unknown stack write opcode {code}")

    def read(self, op):
        code = int(op[0])
        if code == 1:  # ST_PEEK
            return int(self.buf[self.top - 1]) if self.top > 0 else -1
        if code == 2:  # ST_LEN
            return self.top
        raise ValueError(f"unknown stack read opcode {code}")

    def arrays(self):
        return {"buf": self.buf,
                "top": np.asarray(self.top, np.int32)}

    def copy(self):
        o = StackOracle(self.cap)
        o.buf = self.buf.copy()
        o.top = self.top
        return o


class QueueOracle(Oracle):
    """`models/queue.py`: bounded FIFO ring with monotone head/tail
    cursors (modulo indexing; dequeued slots keep their bytes)."""

    model = "queue"

    def __init__(self, capacity: int):
        self.cap = int(capacity)
        self.buf = np.zeros(self.cap, np.int32)
        self.head = 0
        self.tail = 0

    def apply(self, op):
        code = int(op[0])
        if code == 1:  # Q_ENQ
            n = self.tail - self.head
            if n < self.cap:
                self.buf[self.tail % self.cap] = np.int32(op[1])
                self.tail += 1
                return n + 1
            return -1
        if code == 2:  # Q_DEQ
            if self.tail > self.head:
                val = int(self.buf[self.head % self.cap])
                self.head += 1
                return val
            return -1
        raise ValueError(f"unknown queue write opcode {code}")

    def read(self, op):
        code = int(op[0])
        if code == 1:  # Q_FRONT
            if self.tail > self.head:
                return int(self.buf[self.head % self.cap])
            return -1
        if code == 2:  # Q_LEN
            return self.tail - self.head
        raise ValueError(f"unknown queue read opcode {code}")

    def arrays(self):
        return {
            "buf": self.buf,
            "head": np.asarray(self.head, np.int32),
            "tail": np.asarray(self.tail, np.int32),
        }

    def copy(self):
        o = QueueOracle(self.cap)
        o.buf = self.buf.copy()
        o.head = self.head
        o.tail = self.tail
        return o


class SeqRegOracle(Oracle):
    """`models/seqreg.py`: per-slot fetch-and-set (resp = previous
    value), the serve-layer sequence oracle."""

    model = "seqreg"

    def __init__(self, n_slots: int):
        self.n = int(n_slots)
        self.values = np.zeros(self.n, np.int32)

    def apply(self, op):
        s = int(op[1]) % self.n  # SR_SET
        old = int(self.values[s])
        self.values[s] = np.int32(op[2])
        return old

    def read(self, op):
        return int(self.values[int(op[1]) % self.n])  # SR_GET

    def arrays(self):
        return {"values": self.values}

    def copy(self):
        o = SeqRegOracle(self.n)
        o.values = self.values.copy()
        return o


_FACTORIES = {
    "hashmap": HashmapOracle,
    "stack": StackOracle,
    "queue": QueueOracle,
    "seqreg": SeqRegOracle,
}


def make_oracle(model: str, size: int) -> Oracle:
    """Build the oracle twin of `model` at table/capacity `size`."""
    try:
        return _FACTORIES[model](size)
    except KeyError:
        raise ValueError(
            f"no oracle for model {model!r} "
            f"(have: {', '.join(sorted(_FACTORIES))})"
        ) from None
