"""Byte-identical single-seed reproduction.

    python -m node_replication_tpu.sim.replay <seed> [filters]

regenerates the seed's `CaseSpec` (pass the SAME --models/--wrappers/
--flavors filters the sweep used, if any), runs it, and prints the
step-by-step event log, every violation, and the run digest. Running
it twice prints the same bytes — the whole point of the sim plane: a
failure seen once in a 1000-seed sweep is a unit test forever.

`--spec <artifact.json>` replays a schedule directly from an
`explore.py` artifact instead (e.g. the SHRUNK schedule), bypassing
generation.
"""

from __future__ import annotations

import argparse
import json
import sys

from node_replication_tpu.sim.properties import (
    FLAVORS,
    MODELS,
    WRAPPERS,
    CaseSpec,
    generate_case,
    run_case,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.sim.replay",
        description="replay one sim seed byte-identically",
    )
    ap.add_argument("seed", type=int, nargs="?", default=None)
    ap.add_argument("--models", default=",".join(MODELS))
    ap.add_argument("--wrappers", default=",".join(WRAPPERS))
    ap.add_argument("--flavors", default=",".join(FLAVORS))
    ap.add_argument("--spec", default=None,
                    help="replay the spec inside an explore.py "
                         "artifact JSON (field 'spec' or "
                         "'shrunk.spec') instead of regenerating")
    ap.add_argument("--json", action="store_true",
                    help="emit the result as JSON")
    args = ap.parse_args(argv)

    if args.spec is not None:
        with open(args.spec) as f:
            payload = json.load(f)
        d = payload.get("shrunk", {}).get("spec") or payload["spec"]
        spec = CaseSpec.from_dict(d)
    elif args.seed is not None:
        split = lambda v: tuple(p for p in v.split(",") if p)  # noqa: E731
        spec = generate_case(
            args.seed, models=split(args.models),
            wrappers=split(args.wrappers),
            flavors=split(args.flavors),
        )
    else:
        ap.error("need a seed or --spec")
        return 2

    res = run_case(spec)
    if args.json:
        print(json.dumps({
            "spec": spec.as_dict(),
            "events": res.events,
            "violations": [v.as_dict() for v in res.violations],
            "digest": res.digest,
        }, indent=2))
        return 0 if res.ok else 1

    print(f"case seed={spec.seed} {spec.model}/{spec.wrapper}/"
          f"{spec.flavor} R={spec.n_replicas} nlogs={spec.nlogs} "
          f"({len(spec.steps)} step(s))")
    for i, step in enumerate(spec.steps):
        evs = [e for e in res.events if e[0] == i]
        out = "; ".join(
            f"{kind} {kv}" if kv else kind for _, kind, kv in evs
        )
        print(f"  [{i:3d}] {step!r:<48s} -> {out}")
    tailevs = [e for e in res.events if e[0] == -1]
    if tailevs:
        print(f"  [end] " + "; ".join(
            f"{kind} {kv}" if kv else kind for _, kind, kv in tailevs))
    if res.violations:
        print("VIOLATIONS:")
        for v in res.violations:
            print(f"  - {v.prop} @ step {v.step}: {v.detail}")
    else:
        print("all properties held")
    print(f"digest {res.digest}")
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
