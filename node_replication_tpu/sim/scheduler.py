"""Seeded cooperative step-scheduler.

The determinism backbone of `sim/`: instead of letting the OS
interleave the runtime's background loops (serve workers, fault
medics, the WAL ship loop, the follower apply loop, the promotion
watcher), the simulation runs each loop body as an ACTOR — a callable
that performs one quantum of that loop's work and returns whether it
made progress — and this scheduler picks which actor runs next with a
seeded RNG. One seed => one interleaving => one byte-identical run,
which is what lets `explore.py` treat "which thread won the race" as
a search dimension instead of an accident of the GIL.

Actors are registered with a weight (relative pick probability) and
can be enabled/disabled as the simulated scenario evolves (a killed
primary's ship actor is disabled, a promoted follower's apply actor
too). The schedule — the exact sequence of actor names — is recorded
in `trace`, so a failing case's interleaving is part of its artifact.

`SimScheduler` is used in two places: `properties.py` uses one at
GENERATION time to weave per-lane step streams (client ops, ship
quanta, apply quanta, fault events) into a single schedule, and tests
use one at RUN time to step live actors directly.
"""

from __future__ import annotations

import random


class SimScheduler:
    """Weighted, seeded round-robin-by-chance over named actors."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        # name -> (fn, weight); insertion-ordered, and picks sort by
        # name, so registration order cannot perturb the schedule
        self._actors: dict[str, tuple] = {}
        self._enabled: set[str] = set()
        #: every quantum, in order: (step_index, actor_name, result)
        self.trace: list[tuple] = []

    # ---------------------------------------------------------- registry

    def add(self, name: str, fn, weight: float = 1.0,
            enabled: bool = True) -> None:
        if name in self._actors:
            raise ValueError(f"actor {name!r} already registered")
        if weight <= 0:
            raise ValueError("weight must be > 0")
        self._actors[name] = (fn, float(weight))
        if enabled:
            self._enabled.add(name)

    def enable(self, name: str) -> None:
        if name not in self._actors:
            raise KeyError(name)
        self._enabled.add(name)

    def disable(self, name: str) -> None:
        self._enabled.discard(name)

    def enabled(self) -> list[str]:
        return sorted(self._enabled)

    # ---------------------------------------------------------- stepping

    def pick(self) -> str | None:
        """Seeded weighted choice among enabled actors (None when none
        is enabled). Deterministic: candidates are sorted by name."""
        names = sorted(self._enabled)
        if not names:
            return None
        weights = [self._actors[n][1] for n in names]
        return self.rng.choices(names, weights=weights, k=1)[0]

    def step(self):
        """Run one quantum of one seeded-chosen actor; returns
        `(name, result)` (or None when nothing is enabled). The
        actor's return value is recorded verbatim in `trace` — by
        convention actors return a bool ("made progress") or a small
        JSON-able summary."""
        name = self.pick()
        if name is None:
            return None
        fn, _ = self._actors[name]
        result = fn()
        self.trace.append((len(self.trace), name, result))
        return name, result

    def run(self, max_steps: int, idle_limit: int | None = None) -> int:
        """Step up to `max_steps` quanta; with `idle_limit`, stop after
        that many CONSECUTIVE no-progress quanta (an actor result that
        is falsy counts as idle). Returns quanta run."""
        idle = 0
        for i in range(int(max_steps)):
            out = self.step()
            if out is None:
                return i
            if idle_limit is not None:
                idle = 0 if out[1] else idle + 1
                if idle >= idle_limit:
                    return i + 1
        return int(max_steps)
