"""Deliberately re-injectable bugs: proof the harness catches what it
claims to catch.

A property harness that has never failed proves nothing — maybe the
system is correct, maybe the checker is vacuous. Each canary here
re-opens one REAL bug class this repo already closed (or one crash
semantics the durability plane exists to prevent), behind a context
manager, so `explore.py --canary <name>` can assert that a bounded
seed sweep catches it, that the failing seed replays byte-identically,
and that the shrinker reduces it — the `sim-smoke` CI job runs
exactly that loop.

Canaries:

- ``reclaim-ignores-pins`` — re-opens the reclaim-vs-ship race PR 6
  closed (`durable/wal.py:reclaim` re-clamps the floor to the pins
  under the lock): reclamation ignores the shipper's pin, so a
  snapshot-floor + GC-head advance deletes WAL segments the feed has
  not shipped yet. Caught by the repl flavor as a ``replication-gap``
  (the follower hits a `FeedGapError`) once a seeded schedule lets
  the shipper lag across a snapshot+sync.
- ``ack-before-fsync`` — `WriteAheadLog.sync` advances `durable_tail`
  WITHOUT fsyncing (an ack that lies about durability). Caught by the
  crash flavor as ``durable-ack-survival``: the simulated kill -9
  truncates the active segment to its last *actually fsynced* size,
  so the lying acks vanish and recovery comes back below the
  "durable" tail.
- ``ack-before-decision`` — `DecisionLog.publish` silently drops the
  decision document (ISSUE 20): the 2PC coordinator proceeds to ack
  commit with NO durable commit point. Caught by the sharded flavor
  as ``txn-atomicity`` on a crash-variant ``stxn`` step: the
  coordinator dies right after its (now vanished) decision publish,
  and restart recovery presumed-aborts a transaction the coordinator
  had decided to commit.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def _reclaim_ignores_pins():
    from node_replication_tpu.durable import wal as wal_mod

    orig = wal_mod.WriteAheadLog._pin_floor_locked
    wal_mod.WriteAheadLog._pin_floor_locked = (
        lambda self, floor: floor  # the bug: pins no longer clamp
    )
    try:
        yield
    finally:
        wal_mod.WriteAheadLog._pin_floor_locked = orig


@contextlib.contextmanager
def _ack_before_fsync():
    from node_replication_tpu.durable import wal as wal_mod

    orig = wal_mod.WriteAheadLog.sync

    def lying_sync(self):
        with self._lock:
            self._check_usable()
            self._durable = self._tail  # the bug: no fsync happened
            return self._durable

    wal_mod.WriteAheadLog.sync = lying_sync
    try:
        yield
    finally:
        wal_mod.WriteAheadLog.sync = orig


@contextlib.contextmanager
def _ack_before_decision():
    from node_replication_tpu.durable import txnlog as txnlog_mod

    orig = txnlog_mod.DecisionLog.publish

    def lost_publish(self, txn, outcome, shards=()):
        return None  # the bug: the decision never reaches disk

    txnlog_mod.DecisionLog.publish = lost_publish
    try:
        yield
    finally:
        txnlog_mod.DecisionLog.publish = orig


CANARIES = {
    "reclaim-ignores-pins": _reclaim_ignores_pins,
    "ack-before-fsync": _ack_before_fsync,
    "ack-before-decision": _ack_before_decision,
}

#: the flavor whose property set catches each canary — `explore.py
#: --canary` narrows its sweep to this flavor so the catch is cheap
CANARY_FLAVOR = {
    "reclaim-ignores-pins": "repl",
    "ack-before-fsync": "crash",
    "ack-before-decision": "sharded",
}


def armed(name: str):
    """Context manager re-injecting canary bug `name`."""
    try:
        return CANARIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown canary {name!r} "
            f"(have: {', '.join(sorted(CANARIES))})"
        ) from None
