"""Case generation + step interpreter + the property catalog.

A **case** is one fully-seeded simulated scenario: a model, a wrapper
(`NodeReplicated` or `MultiLogReplicated`), a flavor (which subsystem
stack is under test), and a flat list of **steps** — the seeded
schedule. The interpreter (`run_case`) executes the steps one quantum
at a time on a single driver thread under an installed `SimClock`
(background loops are stepped cooperatively: the WAL shipper's
`_ship_once`, the follower's `_apply_once`, the promotion watcher's
`check()`), records every observable outcome into an event log, and
checks the run against a pure-numpy oracle (`sim/oracle.py`). The
same seed always produces the same spec, the same events, and the
same digest — `replay.py` rests on exactly this.

Flavors:

- ``wrapper`` — ops straight into the wrapper (`execute_mut_batch` /
  `execute`), faults at the replay/append/read-sync sites, silent
  corruption + divergence probe + repair-by-replay (NR, R=3).
- ``serve``   — closed-loop ops through a `ServeFrontend`; NR runs
  failover + the `ReplicaLifecycleManager` medic pipeline under
  serve-batch/append kills; CNR runs the same fault plans with
  failover off (typed rejections, worker survives).
- ``crash``   — NR + attached WAL; seeded kill -9 (flush-to-OS, then
  truncate the active segment to its last-fsynced size, plus an
  optional torn-tail remainder) followed by `recover_fleet`.
- ``repl``    — NR primary + WAL + `DirectoryFeed` + shipper +
  follower + promotion watcher, all stepped as scheduler quanta;
  seeded primary kill, heartbeat-silence detection in virtual time,
  election, epoch fence, promotion, post-failover serving.
- ``sharded`` — a 2-shard keyspace fleet (ISSUE 18): per shard an NR
  primary + WAL + feed + shipper + follower, fronted by the REAL
  `ShardRouter` (`concurrent=False` — sequential shard-ordered
  fan-out, so thread interleaving is not schedule noise). Routed
  writes/batches/reads, per-shard ship/apply lanes, and a seeded
  kill → typed-unavailability window → promotion → router re-home
  tail. Generated entirely from a FRESH rng stream, so every other
  flavor's schedule (and the canary-seed expectations) stays
  byte-identical. Grown by ISSUE 20 (more fresh streams): ``stxn``
  steps drive atomic cross-shard transactions through the REAL
  `TxnCoordinator` (optionally killing the coordinator right after
  its durable decision publish and simulating the restart recovery),
  and no-kill runs may end in an ``sreshard`` step — a live split of
  one congruence class onto the donor's promoted standby, with
  post-split traffic across the refined topology.

Property catalog (each violation carries the property name):

- ``resp-diff``          — an acked response differs from the oracle's
  at the same logical position.
- ``read-diff`` / ``fread-diff`` — a (bounded-staleness) read differs
  from the oracle at the replica's applied position.
- ``maybe-executed-honesty`` — a rejection that promised
  `maybe_executed=False` for an op the log provably holds.
- ``log-content``        — the ring's `[0, tail)` is not exactly the
  acked op sequence (lost, duplicated, or reordered entries).
- ``state-diff``         — final replica state is not bit-identical to
  the oracle's arrays.
- ``bit-identity``       — unfenced replicas disagree after sync.
- ``divergence-detect``  — an injected corruption the digest vote
  failed to name.
- ``durable-ack-survival`` — a crash/promotion lost an op that was
  fsync-acked (crash) or shipped-acked (repl).
- ``staleness-bound``    — a bounded read served below its bound.
- ``replication-gap``    — the follower observed a feed gap/corruption
  (the reclaim-vs-ship protection failing).
- ``zombie-unfenced``    — a superseded primary's shipper published
  past the promotion fence.
- ``shed-honesty``       — a shed response (`Overloaded`, an
  eviction, or a client-side `CircuitOpen`) for an op the log
  nonetheless holds — a shed MUST have zero log effect.
- ``priority-inversion`` — a CRITICAL op was shed while a
  lower-priority op sat queued (the overload plane's strict-priority
  eviction exists to make this impossible; the queue counts it at
  the shed decision point, under its lock).
- ``shard-isolation``    — a shard's ring holds a key outside its
  `key % N` congruence class, or a shard's final state is not the
  fold of EXACTLY the ops routed to it (an op leaked into the wrong
  shard's keyspace slice). The sharded flavor also reuses
  ``resp-diff`` (per-shard oracle), ``durable-ack-survival`` (a
  promotion lost a shipped-acked op), ``zombie-unfenced``, and
  ``log-content`` (lost/duplicated acks per shard).
- ``txn-atomicity``      — a cross-shard transaction half-applied: an
  acked txn op whose response (or read-back) diverges from the
  per-class oracle, an aborted txn with a visible per-key effect, or
  a DECIDED txn (the coordinator crashed one instruction after its
  durable decision publish) that restart recovery fails to re-drive
  to commit on every participant.
- ``reshard-exactness``  — after a live split, some key's read-back
  is not the fold of exactly its class's acked ops (a moved key lost,
  duplicated, or served from the wrong slice).

The serve flavor's ``burst`` steps drive the overload plane
deterministically: a paused frontend (workers not started) admits a
mixed-priority burst against a tiny adaptive limit — every
shed/evict/circuit decision lands on the driver thread — then starts,
drains, and the interpreter reads the ACTUAL ring slice back to fold
the oracle in true log order and check the two properties above plus
``resp-diff``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import random
import shutil
import tempfile

import numpy as np

from node_replication_tpu.sim.oracle import make_oracle
from node_replication_tpu.sim.scheduler import SimScheduler
from node_replication_tpu.utils.clock import SimClock, installed

MODELS = ("hashmap", "stack", "queue", "seqreg")
WRAPPERS = ("nr", "cnr")
FLAVORS = ("wrapper", "serve", "crash", "repl", "sharded")

#: canonical sizes — fixed per model so a sweep's cases share compiled
#: kernels (same shapes => jit cache hits; per-case cost stays low)
MODEL_SIZES = {"hashmap": 32, "stack": 24, "queue": 12, "seqreg": 16}
LOG_ENTRIES = 256
GC_SLACK = 32
#: tiny WAL segments in the repl flavor: rotation every few records,
#: so snapshot-floor reclamation has something to delete and the
#: reclaim-vs-ship pin protection is actually load-bearing
REPL_SEGMENT_BYTES = 256
CRASH_SEGMENT_BYTES = 1 << 10

_WRITE_FAULT_SITES = {
    "wrapper": ("replay", "append"),
    "serve": ("serve-batch", "append"),
}
_FAULT_ACTIONS = ("raise", "stall")


@dataclasses.dataclass
class CaseSpec:
    """One fully-seeded scenario (JSON-able; the shrinker edits
    `steps`, everything else is fixed by the seed)."""

    seed: int
    model: str
    wrapper: str  # "nr" | "cnr"
    flavor: str  # "wrapper" | "serve" | "crash" | "repl"
    n_replicas: int
    nlogs: int  # cnr only (1 for nr)
    steps: list
    #: serve-flavor pipeline overlap (`ServeConfig.pipeline_depth`):
    #: 0 = serial worker, 1 = assembly/completion split — drawn from a
    #: FRESH rng stream so every pre-overlap schedule (and canary
    #: artifact) stays byte-identical
    overlap: int = 0
    #: sharded flavor only: keyspace shard count (0 everywhere else,
    #: so pre-sharding failing-seed artifacts keep replaying)
    n_shards: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "CaseSpec":
        # defaulted fields stay optional so pre-overlap failing-seed
        # artifacts keep replaying byte-identically
        return cls(**{f.name: d[f.name]
                      for f in dataclasses.fields(cls)
                      if f.name in d})


@dataclasses.dataclass
class Violation:
    prop: str
    step: int  # index into spec.steps (-1 = end-of-case check)
    detail: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CaseResult:
    spec: CaseSpec
    violations: list
    events: list
    digest: str

    @property
    def ok(self) -> bool:
        return not self.violations


# ==========================================================================
# generation
# ==========================================================================


def _gen_write(rng: random.Random, model: str, size: int,
               uniq: int) -> list:
    """One mutating op; `uniq` tags payloads so every logged write is
    distinguishable (the log-content property needs exactness)."""
    if model == "hashmap":
        if rng.random() < 0.75:
            return [1, rng.randrange(size), uniq]  # HM_PUT
        return [2, rng.randrange(size), 0]  # HM_REMOVE
    if model == "stack":
        if rng.random() < 0.6:
            return [1, uniq, 0]  # ST_PUSH
        return [2, 0, 0]  # ST_POP
    if model == "queue":
        if rng.random() < 0.6:
            return [1, uniq, 0]  # Q_ENQ
        return [2, 0, 0]  # Q_DEQ
    if model == "seqreg":
        return [1, rng.randrange(size), uniq]  # SR_SET
    raise ValueError(model)


def _gen_unique_write(rng: random.Random, model: str, size: int,
                      uniq: int) -> list:
    """One INSERT-shaped mutating op with a unique payload — burst
    steps need every logged write distinguishable so the ring slice
    maps back to its request (POP/REMOVE ops all encode alike)."""
    if model in ("hashmap", "seqreg"):
        return [1, rng.randrange(size), uniq]  # PUT / SR_SET
    return [1, uniq, 0]  # ST_PUSH / Q_ENQ


def _gen_read(rng: random.Random, model: str, size: int) -> list:
    if model == "hashmap":
        return [1, rng.randrange(size), 0]  # HM_GET
    if model in ("stack", "queue"):
        return [rng.choice((1, 2)), 0, 0]  # PEEK/FRONT or LEN
    if model == "seqreg":
        return [1, rng.randrange(size), 0]  # SR_GET
    raise ValueError(model)


def generate_case(
    seed: int,
    models=MODELS,
    wrappers=WRAPPERS,
    flavors=FLAVORS,
) -> CaseSpec:
    """Derive one `CaseSpec` from `seed` (restricted to the given
    models/wrappers/flavors — `explore.py` passes its CLI filters, and
    `replay.py` must pass the SAME filters to reproduce a sweep's
    case)."""
    rng = random.Random(int(seed))
    # the durability and replication planes are NR surfaces: with
    # "nr" filtered out, those flavors are dropped from the pool
    # rather than silently overriding the wrapper filter. "sharded"
    # is excluded from the BASE pool so the original flavor draw (and
    # every pre-sharding schedule) stays byte-identical — sharded
    # cases come from the fresh-stream conversion below instead
    pool = [f for f in flavors
            if f != "sharded"
            and ("nr" in wrappers or f in ("wrapper", "serve"))]
    flavor = rng.choice(pool or ["wrapper"])
    if flavor in ("crash", "repl") or "cnr" not in wrappers:
        wrapper = "nr"
    else:
        wrapper = rng.choice(
            [w for w in ("nr", "nr", "cnr") if w in wrappers]
        )
    model = rng.choice(list(models))
    nlogs = 1
    if wrapper == "cnr" and model in ("hashmap", "seqreg"):
        nlogs = rng.choice((1, 2))
    with_corrupt = (
        wrapper == "nr" and flavor == "wrapper" and rng.random() < 0.4
    )
    R = 3 if with_corrupt else 2
    n = rng.randint(16, 36)
    # keyspace sharding (ISSUE 18): a FRESH rng stream decides
    # whether this seed becomes a sharded-fleet case — only serve/nr
    # base cases convert (or every seed, under an explicit
    # `flavors=("sharded",)` filter), and every draw the sharded
    # schedule needs comes from the fresh stream, so non-converted
    # seeds (and the canary expectations) stay byte-identical
    if "sharded" in flavors and "nr" in wrappers:
        srng = random.Random(int(seed) ^ 0x54A8D)
        if not pool or (flavor == "serve" and wrapper == "nr"
                        and srng.random() < 0.3):
            return _generate_sharded(seed, srng, models)
    uniq = 1
    steps: list = []

    def w(fault=None):
        nonlocal uniq
        op = _gen_write(rng, model, MODEL_SIZES[model], uniq)
        uniq += 1
        rid = rng.randrange(R)
        if fault is None:
            steps.append(["w", rid, op])
        else:
            steps.append(["wf", rid, fault[0], fault[1], op])

    def r():
        steps.append(
            ["r", rng.randrange(R),
             _gen_read(rng, model, MODEL_SIZES[model])]
        )

    if flavor in ("wrapper", "serve"):
        kills = 0
        for _ in range(n):
            x = rng.random()
            if x < 0.55:
                w()
            elif x < 0.75:
                r()
            elif x < 0.85 and kills < 2:
                kills += 1
                w(fault=(rng.choice(_WRITE_FAULT_SITES[flavor]),
                         rng.choice(_FAULT_ACTIONS)))
            elif x < 0.92 and flavor == "wrapper":
                steps.append(["rf", rng.randrange(R),
                              _gen_read(rng, model,
                                        MODEL_SIZES[model])])
            elif with_corrupt:
                steps.append(["corrupt", rng.randrange(R)])
                steps.append(["probe"])
            else:
                w()
        if flavor == "serve" and wrapper == "nr":
            # overload bursts (a FRESH rng stream: the base schedule
            # above — and every other flavor's — stays byte-identical
            # to the pre-overload generator, so failing-seed artifacts
            # and canary expectations survive)
            brng = random.Random(int(seed) ^ 0xB0057)
            buniq = 100_000  # disjoint from the w() uniq range
            for _ in range(brng.randrange(1, 3)):
                burst = []
                for _ in range(brng.randrange(8, 15)):
                    prio = brng.choices((0, 1, 2),
                                        weights=(1, 2, 2))[0]
                    burst.append([prio, _gen_unique_write(
                        brng, model, MODEL_SIZES[model], buniq)])
                    buniq += 1
                steps.append(["burst", burst])
        steps.append(["sync"])
        overlap = 0
        if flavor == "serve":
            # pipelined serving (ISSUE 14): half the serve cases run
            # the assembly/completion split at depth 1, so the
            # 1000-seed sweep races the two-stage handoff for free.
            # A FRESH rng stream keeps every existing schedule (and
            # the canary expectations) byte-identical.
            orng = random.Random(int(seed) ^ 0x0E87A9)
            overlap = int(orng.random() < 0.5)
        return CaseSpec(seed, model, wrapper, flavor, R, nlogs, steps,
                        overlap=overlap)

    if flavor == "crash":
        crashes = 0
        for i in range(n):
            x = rng.random()
            if x < 0.5:
                w()
            elif x < 0.62:
                r()
            elif x < 0.78:
                steps.append(["wal-sync"])
            elif x < 0.86:
                steps.append(["snapshot"])
            elif crashes < 2 and i > 4:
                crashes += 1
                # lose: drop everything past the last fsync; extra:
                # torn-tail remainder bytes kept past that point
                steps.append(["crash", int(rng.random() < 0.6),
                              rng.randrange(64)])
            else:
                w()
        steps.append(["sync"])
        return CaseSpec(seed, model, wrapper, flavor, R, nlogs, steps)

    # repl: weave the client/durability/ship/apply/watch lanes with a
    # seeded cooperative scheduler — the schedule IS the interleaving
    sched = SimScheduler(seed=rng.randrange(1 << 30))
    sched.add("w", lambda: w() or True, weight=3.0)
    sched.add("r", lambda: r() or True, weight=1.0)
    sched.add("wal-sync", lambda: steps.append(["wal-sync"]) or True,
              weight=1.2)
    sched.add("ship", lambda: steps.append(["ship"]) or True,
              weight=1.2)
    sched.add("apply", lambda: steps.append(["apply"]) or True,
              weight=1.2)
    sched.add("fread", lambda: steps.append(
        ["fread", _gen_read(rng, model, MODEL_SIZES[model]),
         rng.choice((2, 4, 8))]) or True, weight=0.8)
    sched.add("watch", lambda: steps.append(["watch", 1]) or True,
              weight=0.5)
    sched.run(n + 10)
    # reclamation pressure mid-stream: snapshot raises the floor, the
    # sync right after advances the GC head past it — only the ship
    # pin now protects unshipped segments (the reclaim-vs-ship race
    # the canary re-opens)
    cut = rng.randrange(len(steps) // 2, len(steps))
    steps[cut:cut] = [["snapshot"], ["sync"]]
    # wire-fault lane (ISSUE 12): the follower consumes the feed
    # through an in-memory `PipeTransport` twin of the socket client,
    # and a FRESH rng stream inserts disconnect/reconnect windows and
    # partitions — stream gaps, duplicate delivery after a rewound
    # reconnect, and frozen-heartbeat silence are now part of the
    # 1000-seed sweep. The fresh stream keeps the base repl schedule
    # (and the canary-seed expectations) byte-identical to the
    # pre-transport generator; every disconnect is paired with a
    # reconnect BEFORE the kill/promote tail, so promotion always
    # fences over a live pipe.
    prng = random.Random(int(seed) ^ 0x7197E)
    if prng.random() < 0.6:
        p = prng.randrange(2, max(3, len(steps) - 2))
        gap = prng.randrange(0, 4)
        rew = prng.choice((0, 2, 4, 8))
        steps[p:p] = [["disconnect"]]
        q = min(p + 1 + gap, len(steps))
        steps[q:q] = [["reconnect", rew]]
    if prng.random() < 0.35:
        p2 = prng.randrange(1, max(2, len(steps) - 1))
        steps[p2:p2] = [["partition", prng.randrange(1, 3),
                         prng.choice((0, 4))]]
    if rng.random() < 0.7:
        steps.append(["wal-sync"])
        if rng.random() < 0.7:
            steps.append(["ship"])
        steps.append(["kill"])
        for _ in range(9):
            steps.append(["watch", 2])  # 2 virtual ticks per quantum
        steps.append(["promote"])
        if rng.random() < 0.5:
            steps.append(["zombie-ship"])
        for _ in range(rng.randrange(2, 6)):
            op = _gen_write(rng, model, MODEL_SIZES[model], uniq)
            uniq += 1
            steps.append(["w", 0, op])
        steps.append(["fread",
                      _gen_read(rng, model, MODEL_SIZES[model]), 0])
    else:
        steps += [["wal-sync"], ["ship"], ["apply"], ["apply"]]
    return CaseSpec(seed, model, wrapper, flavor, R, nlogs, steps)


def _generate_sharded(seed: int, srng: random.Random,
                      models) -> CaseSpec:
    """One sharded-fleet schedule, drawn ENTIRELY from the fresh
    stream `srng` (the base stream's consumption up to the conversion
    point is identical either way, so non-converted seeds replay
    byte-identically). Keyed models only — `args[0]` is the routing
    key, and stack/queue ops would degenerate onto one shard."""
    keyed = [m for m in ("hashmap", "seqreg") if m in models]
    model = srng.choice(keyed or ["hashmap"])
    size = MODEL_SIZES[model]
    n_shards = 2
    uniq = 1
    steps: list = []

    def wop() -> list:
        nonlocal uniq
        op = _gen_write(srng, model, size, uniq)
        uniq += 1
        return op

    for _ in range(srng.randint(18, 34)):
        x = srng.random()
        if x < 0.40:
            steps.append(["sw", wop()])
        elif x < 0.55:
            steps.append(
                ["sbatch", [wop() for _ in range(srng.randrange(2, 6))]]
            )
        elif x < 0.70:
            steps.append(["sread", _gen_read(srng, model, size)])
        elif x < 0.80:
            steps.append(["swal", srng.randrange(n_shards)])
        elif x < 0.90:
            steps.append(["sship", srng.randrange(n_shards)])
        else:
            steps.append(["sapply", srng.randrange(n_shards)])
    body_end = len(steps)
    killed = srng.random() < 0.7
    if killed:
        # kill → typed-unavailability window (writes keyed into the
        # victim's congruence class surface `ShardUnavailable`; the
        # survivor keeps acking — the isolation half of the property)
        # → promotion → router re-home → post-failover serving
        victim = srng.randrange(n_shards)
        steps.append(["swal", victim])
        if srng.random() < 0.7:
            steps.append(["sship", victim])
        steps.append(["skill", victim])
        for _ in range(srng.randrange(2, 5)):
            steps.append(["sw", wop()])
        steps.append(["spromote", victim])
        if srng.random() < 0.5:
            steps.append(["szombie", victim])
        for _ in range(srng.randrange(2, 6)):
            steps.append(["sw", wop()])
    else:
        for s in range(n_shards):
            steps += [["swal", s], ["sship", s], ["sapply", s]]
    # cross-shard transactions + online resharding (ISSUE 20): drawn
    # from ANOTHER fresh stream, so every pre-txn sharded schedule
    # (and the existing canary expectations) stays byte-identical.
    # Txn steps insert only into the pre-kill body — the coordinator
    # is exercised against live shards; the kill window's typed
    # unavailability is the abort path, covered by crafted tests.
    trng = random.Random(int(seed) ^ 0x77C27)
    tuniq = 50_000  # disjoint from the wop() uniq range
    if trng.random() < 0.65:
        txn_steps = []
        for _ in range(trng.randrange(1, 3)):
            # adjacent keys straddle the mod-2 congruence: the txn is
            # genuinely cross-shard, so the 2PC path (not the
            # single-group fast path) is what runs
            k0 = trng.randrange(size - 1)
            ops = [[1, k0, tuniq], [1, k0 + 1, tuniq + 1]]
            tuniq += 2
            if trng.random() < 0.5:
                ops.append([1, trng.randrange(size), tuniq])
                tuniq += 1
            # crash=1: the coordinator dies right after its durable
            # decision publish — recovery must re-drive the commit
            txn_steps.append(["stxn", ops,
                              int(trng.random() < 0.4)])
        for st in reversed(txn_steps):
            steps.insert(trng.randrange(0, body_end + 1), st)
    if not killed and trng.random() < 0.5:
        # live split of one congruence class (no-kill runs only: the
        # donor needs a promotable standby), then post-split traffic
        # across the refined topology
        steps.append(["sreshard", trng.randrange(n_shards)])
        for _ in range(trng.randrange(2, 5)):
            steps.append(["sw", [1, trng.randrange(size), tuniq]])
            tuniq += 1
    return CaseSpec(seed, model, "nr", "sharded", 1, 1, steps,
                    n_shards=n_shards)


# ==========================================================================
# interpretation
# ==========================================================================


def _make_dispatch(model: str):
    from node_replication_tpu.models import (
        make_hashmap,
        make_queue,
        make_seqreg,
        make_stack,
    )

    maker = {"hashmap": make_hashmap, "stack": make_stack,
             "queue": make_queue, "seqreg": make_seqreg}[model]
    return maker(MODEL_SIZES[model])


def _key_mapper(opcode, args):
    return args[0]


def _digest(spec: CaseSpec, events: list) -> str:
    blob = json.dumps([spec.as_dict(), events], sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


class _Run:
    """Mutable interpreter state for one case (one driver thread)."""

    def __init__(self, spec: CaseSpec):
        self.spec = spec
        self.dispatch = _make_dispatch(spec.model)
        self.oracle = make_oracle(spec.model, MODEL_SIZES[spec.model])
        self.events: list = []
        self.violations: list = []
        self.applied: list = []  # ops in log order (host ground truth)
        self.tokens: dict = {}
        self.tmp: str | None = None
        # flavor plumbing, filled by _build
        self.wr = None
        self.fe = None
        self.mgr = None
        self.wal = None
        self.synced_sizes: dict = {}
        self.feed = None
        self.pipe = None
        self.shipper = None
        self.follower = None
        self.pm = None
        self.oracle_f = None
        self.breaker = None  # per-case client circuit breaker (burst)
        self.fpos = 0
        self.primary_dead = False
        self.promoted = False
        self.shipped_acked = 0
        self.pre_kill_cursor = 0
        # sharded flavor: one primary stack per shard, behind the
        # real router (filled by _build)
        self.shards: list = []  # per-shard plumbing dicts
        self.router = None
        self.smap = None
        self.sh_oracle: list = []  # per-shard oracle
        self.sh_applied: list = []  # per-shard acked ops, in order
        self.sh_dead: list = []
        self.sh_promoted: list = []
        self.sh_pre_cursor: list = []
        self.sh_acked: list = []  # shipped-acked floor at kill time
        # cross-shard txn + reshard plumbing (ISSUE 20)
        self.decisions = None  # DecisionLog shared by the fleet
        self.coord = None  # TxnCoordinator, built on first stxn
        self.sh_txn: list = []  # per-shard TxnParticipant
        self.sh_txn_extra: list = []  # refined-class participants
        self.resharded = False
        self.recipient = None  # the promoted donor follower
        self.reshard_donor = -1

    # ------------------------------------------------------------ plumbing

    def ev(self, i: int, kind: str, **kv) -> None:
        self.events.append([i, kind, kv])

    def vio(self, prop: str, i: int, detail: str) -> None:
        self.violations.append(Violation(prop, i, detail))

    def tail(self) -> int:
        if self.spec.wrapper == "cnr":
            return int(np.asarray(self.wr.ml.tail).sum())
        return int(np.asarray(self.wr.log.tail))

    def token(self, rid: int):
        if rid not in self.tokens:
            self.tokens[rid] = self.wr.register(rid)
        return self.tokens[rid]

    def _build(self):
        from node_replication_tpu.core.cnr import MultiLogReplicated
        from node_replication_tpu.core.replica import NodeReplicated

        spec = self.spec
        if spec.flavor == "sharded":
            self._build_sharded()
            return
        if spec.wrapper == "cnr":
            self.wr = MultiLogReplicated(
                self.dispatch, _key_mapper, nlogs=spec.nlogs,
                n_replicas=spec.n_replicas, log_entries=LOG_ENTRIES,
                gc_slack=GC_SLACK,
            )
        else:
            self.wr = NodeReplicated(
                self.dispatch, n_replicas=spec.n_replicas,
                log_entries=LOG_ENTRIES, gc_slack=GC_SLACK,
            )
        if spec.flavor in ("crash", "repl"):
            from node_replication_tpu.durable.wal import WriteAheadLog

            self.tmp = tempfile.mkdtemp(prefix="nr-sim-")
            seg = (CRASH_SEGMENT_BYTES if spec.flavor == "crash"
                   else REPL_SEGMENT_BYTES)
            self.wal = WriteAheadLog(
                os.path.join(self.tmp, "wal"), policy="batch",
                arg_width=self.dispatch.arg_width,
                segment_max_bytes=seg,
            )
            self.wr.attach_wal(self.wal)
        if spec.flavor == "serve":
            from node_replication_tpu.serve.frontend import (
                ServeConfig,
                ServeFrontend,
            )

            failover = spec.wrapper == "nr"
            self.fe = ServeFrontend(
                self.wr,
                ServeConfig(batch_linger_s=0.0, queue_depth=64,
                            failover=failover,
                            pipeline_depth=spec.overlap),
            )
            if failover:
                from node_replication_tpu.fault.repair import (
                    ReplicaLifecycleManager,
                )

                self.mgr = ReplicaLifecycleManager(self.wr, self.fe)
        if spec.flavor == "wrapper" and spec.wrapper == "nr":
            from node_replication_tpu.fault.repair import (
                ReplicaLifecycleManager,
            )

            self.mgr = ReplicaLifecycleManager(self.wr)
        if spec.flavor == "repl":
            from node_replication_tpu.repl.feed import DirectoryFeed
            from node_replication_tpu.repl.follower import Follower
            from node_replication_tpu.repl.promote import (
                PromotionManager,
            )
            from node_replication_tpu.repl.shipper import (
                ReplicationShipper,
            )
            from node_replication_tpu.repl.transport import (
                PipeTransport,
            )
            from node_replication_tpu.serve.frontend import ServeConfig

            self.feed = DirectoryFeed(
                os.path.join(self.tmp, "feed"),
                arg_width=self.dispatch.arg_width,
            )
            self.shipper = ReplicationShipper(
                self.wal, self.feed, auto_start=False,
            )
            # the follower (and the promotion watcher) consume the
            # feed through the deterministic transport twin, so the
            # disconnect/reconnect/partition steps model exactly what
            # a `SocketFeed` client exhibits over a flaky wire
            self.pipe = PipeTransport(self.feed, rewind=4)
            self.follower = Follower(
                self.dispatch, self.pipe,
                directory=os.path.join(self.tmp, "flw"),
                config=ServeConfig(durability="batch",
                                   batch_linger_s=0.0),
                auto_start=False,
                nr_kwargs={"n_replicas": 1,
                           "log_entries": LOG_ENTRIES,
                           "gc_slack": GC_SLACK},
            )
            self.pm = PromotionManager(
                self.pipe, [self.follower],
                heartbeat_timeout_s=0.5, check_interval_s=0.1,
            )
            self.oracle_f = make_oracle(self.spec.model,
                                        MODEL_SIZES[self.spec.model])

    def _build_sharded(self):
        from node_replication_tpu.core.replica import NodeReplicated
        from node_replication_tpu.durable.wal import WriteAheadLog
        from node_replication_tpu.repl.feed import DirectoryFeed
        from node_replication_tpu.repl.follower import Follower
        from node_replication_tpu.repl.shipper import (
            ReplicationShipper,
        )
        from node_replication_tpu.serve.frontend import (
            ServeConfig,
            ServeFrontend,
        )
        from node_replication_tpu.shard.ring import ShardMap
        from node_replication_tpu.shard.router import (
            LocalBackend,
            ShardRouter,
        )

        from node_replication_tpu.durable.txnlog import DecisionLog
        from node_replication_tpu.shard.txn import TxnParticipant

        spec = self.spec
        self.tmp = tempfile.mkdtemp(prefix="nr-sim-")
        self.smap = ShardMap(spec.n_shards)
        self.decisions = DecisionLog(
            os.path.join(self.tmp, "decisions")
        )
        backends: dict = {}
        for s in range(spec.n_shards):
            base = os.path.join(self.tmp, f"s{s}")
            nr = NodeReplicated(
                self.dispatch, n_replicas=1,
                log_entries=LOG_ENTRIES, gc_slack=GC_SLACK,
            )
            wal = WriteAheadLog(
                os.path.join(base, "wal"), policy="batch",
                arg_width=self.dispatch.arg_width,
                segment_max_bytes=REPL_SEGMENT_BYTES,
            )
            nr.attach_wal(wal)
            feed = DirectoryFeed(
                os.path.join(base, "feed"),
                arg_width=self.dispatch.arg_width,
            )
            shipper = ReplicationShipper(wal, feed, auto_start=False)
            fe = ServeFrontend(
                nr,
                ServeConfig(batch_linger_s=0.0, queue_depth=64,
                            durability="batch"),
            )
            follower = Follower(
                self.dispatch, feed,
                directory=os.path.join(base, "flw"),
                config=ServeConfig(durability="batch",
                                   batch_linger_s=0.0),
                auto_start=False,
                nr_kwargs={"n_replicas": 1,
                           "log_entries": LOG_ENTRIES,
                           "gc_slack": GC_SLACK},
            )
            self.shards.append({"nr": nr, "wal": wal, "feed": feed,
                                "shipper": shipper, "fe": fe,
                                "follower": follower})
            txn = TxnParticipant(
                s, fe, self.smap, os.path.join(base, "txn"),
                decisions=self.decisions, wal=wal,
            )
            self.sh_txn.append(txn)
            backends[s] = LocalBackend(s, fe, self.smap,
                                       participant=txn)
            self.sh_oracle.append(
                make_oracle(spec.model, MODEL_SIZES[spec.model])
            )
            self.sh_applied.append([])
            self.sh_dead.append(False)
            self.sh_promoted.append(False)
            self.sh_pre_cursor.append(0)
            self.sh_acked.append(0)
        # sequential shard-ordered fan-out: the sim's determinism knob
        self.router = ShardRouter(self.smap, backends,
                                  concurrent=False)

    def _teardown(self):
        for t in self.sh_txn + self.sh_txn_extra:
            try:
                t.close()
            except Exception:
                pass
        for sh in self.shards:
            try:
                sh["fe"].close(drain=False)
            except Exception:
                pass
            sh["follower"].close()
            try:
                sh["wal"].clear_pin(sh["shipper"].pin_name)
            except Exception:
                pass
        if self.fe is not None:
            self.fe.close()
        if self.mgr is not None:
            self.mgr.wait_idle(30)
        if self.follower is not None:
            self.follower.close()
        if self.shipper is not None and self.wal is not None:
            try:
                self.wal.clear_pin(self.shipper.pin_name)
            except Exception:
                pass
        if self.tmp is not None:
            shutil.rmtree(self.tmp, ignore_errors=True)

    # ------------------------------------------------------------- helpers

    def _one_shot_plan(self, site: str, action: str):
        from node_replication_tpu.fault.inject import (
            FaultPlan,
            FaultSpec,
        )

        return FaultPlan(
            [FaultSpec(site=site, action=action, rid=-1, after=0)],
            seed=self.spec.seed,
        )

    def _record_applied(self, op: list) -> None:
        self.oracle.apply(op)
        self.applied.append(list(op))

    def _advance_oracle_f(self, to: int, i: int) -> None:
        """Fold the follower's oracle up to applied position `to`."""
        if to > len(self.applied):
            self.vio("replication-gap", i,
                     f"follower applied {to} > primary history "
                     f"{len(self.applied)}")
            to = len(self.applied)
        for op in self.applied[self.fpos:to]:
            self.oracle_f.apply(op)
        self.fpos = max(self.fpos, to)

    # ----------------------------------------------------------- op steps

    def _write_target(self):
        """(callable, kind) for the current write path."""
        if self.spec.flavor == "serve" or self.promoted:
            fe = (self.follower.frontend if self.promoted
                  else self.fe)

            def call(op, rid):
                return fe.submit(tuple(op), rid=rid).result()

            return call

        def call(op, rid):
            return self.wr.execute_mut_batch([tuple(op)], rid)[0]

        return call

    def do_write(self, i: int, rid: int, op: list,
                 fault: tuple | None = None) -> None:
        if self.spec.flavor == "repl" and self.primary_dead \
                and not self.promoted:
            self.ev(i, "w-unavailable")
            return
        if self.promoted:
            rid = 0  # the follower fleet serves one replica
        wr = self.follower.nr if self.promoted else self.wr
        tail0 = (int(np.asarray(wr.log.tail))
                 if self.spec.wrapper == "nr" or self.promoted
                 else self.tail())
        call = self._write_target()
        err = None
        try:
            if fault is not None:
                with self._one_shot_plan(*fault).armed():
                    resp = call(op, rid)
            else:
                resp = call(op, rid)
        except Exception as e:  # typed edges + injected faults
            err = e
        if err is not None:
            if self.mgr is not None:
                self.mgr.wait_idle(30)
            tail1 = (int(np.asarray(wr.log.tail))
                     if self.spec.wrapper == "nr" or self.promoted
                     else self.tail())
            applied_now = tail1 > tail0
            from node_replication_tpu.serve.errors import ReplicaFailed

            if (isinstance(err, ReplicaFailed)
                    and not err.maybe_executed and applied_now):
                self.vio(
                    "maybe-executed-honesty", i,
                    f"maybe_executed=False but the log advanced "
                    f"{tail0}->{tail1}",
                )
            if applied_now:
                # the op reached the log; only its response was lost.
                # It replays LAZILY (the next combine/sync round), so
                # force the round to completion before the oracle
                # folds it — otherwise a later read legally observes
                # the pre-op state and the differential would flag
                # correct behavior
                wr.sync()
                self._record_applied(op)
            self.ev(i, "w-err", err=type(err).__name__,
                    applied=int(applied_now))
            return
        expect = self.oracle.apply(op)
        self.applied.append(list(op))
        if int(resp) != int(expect):
            self.vio("resp-diff", i,
                     f"op {op} -> {int(resp)}, oracle {int(expect)}")
        self.ev(i, "w", resp=int(resp))

    def do_read(self, i: int, rid: int, op: list,
                fault: tuple | None = None) -> None:
        if self.spec.flavor == "repl" and (self.primary_dead
                                           and not self.promoted):
            self.ev(i, "r-unavailable")
            return
        try:
            if self.promoted:
                val = self.follower.frontend.read(tuple(op), rid=0)
            elif self.fe is not None:
                val = self.fe.read(tuple(op), rid=rid)
            else:
                if fault is not None:
                    with self._one_shot_plan(*fault).armed():
                        val = self.wr.execute(tuple(op),
                                              self.token(rid))
                else:
                    val = self.wr.execute(tuple(op), self.token(rid))
        except Exception as e:
            self.ev(i, "r-err", err=type(e).__name__)
            return
        expect = self.oracle.read(op)
        if int(val) != int(expect):
            self.vio("read-diff", i,
                     f"read {op} on r{rid} -> {int(val)}, "
                     f"oracle {int(expect)}")
        self.ev(i, "r", val=int(val))

    # ------------------------------------------------------ burst steps

    def do_burst(self, i: int, specs: list) -> None:
        """One overload burst (serve flavor, NR): a PAUSED temporary
        frontend (tiny adaptive limit, priorities) admits the whole
        mixed-priority burst on the driver thread — every shed /
        eviction / circuit decision is deterministic — then starts,
        drains, and closes. The ACTUAL ring slice is read back to
        fold the oracle in true log order; checks `shed-honesty`
        (every rejected op absent from the log), `priority-inversion`
        (queue-measured), and `resp-diff` on the completed futures."""
        if self.spec.flavor != "serve" or self.spec.wrapper != "nr":
            self.ev(i, "burst-skip")
            return
        from node_replication_tpu.core.log import ring_slice
        from node_replication_tpu.serve.client import CircuitBreaker
        from node_replication_tpu.serve.errors import (
            CircuitOpen,
            Overloaded,
        )
        from node_replication_tpu.serve.frontend import (
            ServeConfig,
            ServeFrontend,
        )
        from node_replication_tpu.serve.overload import OverloadConfig

        if self.breaker is None:
            # SimClock time does not advance on its own, so an opened
            # circuit stays open for the rest of the case — which is
            # exactly the zero-log-effect path the property wants hit
            self.breaker = CircuitBreaker(failure_threshold=3,
                                          cooldown_s=30.0)
        tail0 = int(np.asarray(self.wr.log.tail))
        cfg = ServeConfig(
            queue_depth=6, batch_max_ops=4, batch_linger_s=0.0,
            overload=OverloadConfig(target_delay_s=0.005,
                                    min_limit=2),
        )
        fe = ServeFrontend(self.wr, cfg, rids=[0], auto_start=False)
        aw = int(self.wr.spec.arg_width)

        def key_of(op) -> tuple:
            """Normalize an op to the ring's (opcode, *args[:aw])
            width — the same padding `_check_ring` applies."""
            key = [int(op[0])] + [int(x) for x in op[1:1 + aw]]
            key += [0] * (1 + aw - len(key))
            return tuple(key)

        futs: list = []  # (index, op, future|None, outcome)
        for k, (prio, op) in enumerate(specs):
            op = list(op)
            try:
                self.breaker.before_call()
            except CircuitOpen:
                futs.append((k, op, None, "copen"))
                continue
            try:
                fut = fe.submit(tuple(op), rid=0, priority=int(prio))
            except Overloaded:
                self.breaker.record_failure()
                futs.append((k, op, None, "shed"))
                continue
            self.breaker.record_success()
            futs.append((k, op, fut, "admitted"))
        fe.start()
        fe.drain(timeout=30)
        stats = fe.stats()
        fe.close(drain=True)
        outcomes: list = []
        completed: dict[tuple, tuple] = {}  # op -> (index, resp)
        rejected: list[tuple] = []  # (index, op, kind)
        for k, op, fut, outcome in futs:
            if fut is None:
                outcomes.append([k, outcome])
                rejected.append((k, op, outcome))
                continue
            exc = fut.exception(timeout=30)
            if exc is not None:
                kind = ("evicted"
                        if isinstance(exc, Overloaded) else
                        f"err-{type(exc).__name__}")
                outcomes.append([k, kind])
                rejected.append((k, op, kind))
                continue
            outcomes.append([k, "completed"])
            completed[key_of(op)] = (k, int(fut.result()))
        if stats["priority_inversions"]:
            self.vio("priority-inversion", i,
                     f"{stats['priority_inversions']} CRITICAL "
                     f"shed(s) while lower-priority ops sat queued")
        tail1 = int(np.asarray(self.wr.log.tail))
        if tail1 - tail0 != len(completed):
            self.vio("shed-honesty", i,
                     f"log advanced {tail1 - tail0} but "
                     f"{len(completed)} op(s) completed — a rejected "
                     f"op left a log effect (or an acked one none)")
        ring_ops: list[list] = []
        if tail1 > tail0:
            opcodes, args = ring_slice(self.wr.spec, self.wr.log,
                                       tail0, tail1)
            aw = args.shape[1]
            for k in range(tail1 - tail0):
                ring_ops.append(
                    [int(opcodes[k])] + [int(x) for x in args[k]]
                )
        seen = set()
        for rop in ring_ops:
            key = tuple(rop)  # already (opcode, *args[:aw]); unique
            expect = self.oracle.apply(key)
            self.applied.append(list(key))
            hit = completed.pop(key, None)
            if hit is None or key in seen:
                self.vio("shed-honesty", i,
                         f"log holds {list(key)} which no completed "
                         f"burst op acked (shed/evicted/circuit-open "
                         f"op with a log effect, or a duplicate)")
                continue
            seen.add(key)
            if int(hit[1]) != int(expect):
                self.vio("resp-diff", i,
                         f"burst op {list(key)} -> {hit[1]}, oracle "
                         f"{int(expect)}")
        for key, (k, resp) in completed.items():
            self.vio("shed-honesty", i,
                     f"burst op {list(key)} acked {resp} but the log "
                     f"never recorded it")
        for k, op, kind in rejected:
            if key_of(op) in seen:
                self.vio("shed-honesty", i,
                         f"{kind} op {op} found in the log")
        self.ev(i, "burst", outcomes=outcomes,
                shed=int(stats["shed"]),
                evicted=int(stats["evicted"]),
                applied=len(ring_ops),
                breaker=self.breaker.state)

    # -------------------------------------------------------- fault steps

    def do_corrupt(self, i: int, rid: int) -> None:
        from node_replication_tpu.fault.inject import corrupt_states

        if self.spec.wrapper != "nr":
            self.ev(i, "corrupt-skip")
            return
        self.wr.states = corrupt_states(self.wr.states, rid,
                                        seed=self.spec.seed)
        self._corrupted = rid
        self.ev(i, "corrupt", rid=rid)

    def do_probe(self, i: int) -> None:
        if self.mgr is None or self.spec.wrapper != "nr":
            self.ev(i, "probe-skip")
            return
        named = self.mgr.probe()
        rid = getattr(self, "_corrupted", None)
        if rid is not None:
            if rid not in named:
                self.vio("divergence-detect", i,
                         f"corrupted r{rid} not named by the vote "
                         f"(named {named})")
            self._corrupted = None
        self.ev(i, "probe", named=[int(x) for x in named])

    # ------------------------------------------------------ durable steps

    def do_wal_sync(self, i: int) -> None:
        if self.wal is None or self.primary_dead:
            self.ev(i, "wal-sync-skip")
            return
        pos = self.wr.wal_sync()
        if self.wal._segments:
            path = self.wal._segments[-1][1]
            self.synced_sizes[path] = os.path.getsize(path)
        self.ev(i, "wal-sync", durable=int(pos))

    def do_snapshot(self, i: int) -> None:
        from node_replication_tpu.durable.recovery import (
            save_durable_snapshot,
        )

        if self.wal is None or self.primary_dead:
            self.ev(i, "snapshot-skip")
            return
        save_durable_snapshot(self.wr, self.tmp)
        self.ev(i, "snapshot", pos=len(self.applied))

    def do_crash(self, i: int, lose: int, extra: int) -> None:
        """Simulated kill -9 + restart: what the OS page cache held
        survives (flush), anything after the last fsync optionally
        does not (truncate to the recorded fsynced size, plus an
        `extra`-byte torn remainder for the recovery scan to chop)."""
        from node_replication_tpu.durable.recovery import recover_fleet

        if self.wal is None:
            self.ev(i, "crash-skip")
            return
        durable = self.wal.durable_tail
        with self.wal._lock:
            if self.wal._fh is not None:
                self.wal._fh.flush()
        if lose and self.wal._segments:
            path = self.wal._segments[-1][1]
            cur = os.path.getsize(path)
            base = self.synced_sizes.get(path, 0)
            keep = min(cur, base + (int(extra) % 64))
            os.truncate(path, keep)
        with self.wal._lock:
            if self.wal._fh is not None:
                self.wal._fh.close()
                self.wal._fh = None
        # the old wrapper is the corpse; recover from disk
        nr2, report = recover_fleet(
            self.tmp, self.dispatch, policy="batch", attach=True,
            nr_kwargs={"n_replicas": self.spec.n_replicas,
                       "log_entries": LOG_ENTRIES,
                       "gc_slack": GC_SLACK},
        )
        T = int(report.tail)
        if T < durable:
            self.vio("durable-ack-survival", i,
                     f"recovered tail {T} < fsync-acked {durable}")
        if T > len(self.applied):
            self.vio("log-content", i,
                     f"recovered tail {T} > ops ever applied "
                     f"{len(self.applied)}")
            T = len(self.applied)
        self.applied = self.applied[:T]
        self.oracle = make_oracle(self.spec.model,
                                  MODEL_SIZES[self.spec.model])
        for op in self.applied:
            self.oracle.apply(op)
        self.wr = nr2
        self.wal = nr2.wal
        self.tokens = {}
        self.synced_sizes = {}
        if self.wal._segments:
            path = self.wal._segments[-1][1]
            self.synced_sizes[path] = os.path.getsize(path)
        state = nr2.verify(lambda s: s)
        self._check_arrays(state, self.oracle, i)
        self.ev(i, "crash", recovered=T, durable=int(durable),
                lost=int(lose))

    # --------------------------------------------------------- repl steps

    def do_ship(self, i: int, zombie: bool = False) -> None:
        from node_replication_tpu.repl.feed import EpochFencedError

        if self.shipper is None:
            self.ev(i, "ship-skip")
            return
        if not zombie and (self.primary_dead or self.promoted):
            self.ev(i, "ship-skip")
            return
        cur0 = self.shipper.cursor
        try:
            self.shipper._ship_once()
        except EpochFencedError:
            self.ev(i, "ship-fenced")
            return
        except Exception as e:
            self.vio("replication-gap", i,
                     f"ship failed: {type(e).__name__}: {e}")
            return
        if zombie and self.shipper.cursor > self.pre_kill_cursor:
            self.vio("zombie-unfenced", i,
                     f"superseded shipper published "
                     f"{self.pre_kill_cursor}->{self.shipper.cursor} "
                     f"past the promotion fence")
        self.ev(i, "ship", shipped=int(self.shipper.cursor - cur0),
                cursor=int(self.shipper.cursor))

    def do_apply(self, i: int) -> None:
        if self.follower is None or self.promoted:
            self.ev(i, "apply-skip")
            return
        try:
            n = self.follower._apply_once()
        except Exception as e:
            self.vio("replication-gap", i,
                     f"follower apply failed: "
                     f"{type(e).__name__}: {e}")
            return
        ap = self.follower.applied_pos()
        self._advance_oracle_f(ap, i)
        self.ev(i, "apply", records=int(n), applied=int(ap))

    def do_fread(self, i: int, op: list, max_lag: int) -> None:
        from node_replication_tpu.serve.errors import StaleRead

        if self.follower is None:
            self.ev(i, "fread-skip")
            return
        try:
            val, applied, bound = self.follower.read_result(
                tuple(op), rid=0, max_lag_pos=int(max_lag),
                wait_s=0.0,
            )
        except StaleRead as e:
            self.ev(i, "fread-stale", applied=int(e.applied_pos),
                    bound=int(e.min_pos))
            return
        except Exception as e:
            self.ev(i, "fread-err", err=type(e).__name__)
            return
        if applied < bound:
            self.vio("staleness-bound", i,
                     f"read served at {applied} below bound {bound}")
        self._advance_oracle_f(self.follower.applied_pos(), i)
        expect = self.oracle_f.read(op)
        if int(val) != int(expect):
            self.vio("fread-diff", i,
                     f"follower read {op} -> {int(val)}, oracle "
                     f"{int(expect)} at {self.follower.applied_pos()}")
        self.ev(i, "fread", val=int(val), applied=int(applied),
                bound=int(bound))

    def do_watch(self, i: int, ticks: int, clock: SimClock) -> None:
        if self.pm is None:
            self.ev(i, "watch-skip")
            return
        clock.advance(0.1 * int(ticks))
        state = self.pm.check()
        self.ev(i, "watch", state=state)

    # ---------------------------------------------------- transport steps

    def do_pipe(self, i: int, action: str, rewind: int = 0) -> None:
        """`disconnect` / `reconnect` on the transport twin: while
        down, polls go quiet and the cached heartbeat freezes; a
        rewound reconnect re-delivers applied records (the duplicate
        path the follower must absorb idempotently)."""
        if self.pipe is None:
            self.ev(i, f"{action}-skip")
            return
        if action == "disconnect":
            self.pipe.disconnect()
            self.ev(i, "disconnect")
        else:
            self.pipe.reconnect(int(rewind))
            self.ev(i, "reconnect", rewind=int(rewind))

    def do_partition(self, i: int, ticks: int, rewind: int,
                     clock: SimClock) -> None:
        """A bounded partition: disconnect, let virtual time pass
        under the promotion watcher (the frozen heartbeat reads as
        silence — strikes accrue exactly as over a dead socket), then
        heal with a rewound reconnect."""
        if self.pipe is None:
            self.ev(i, "partition-skip")
            return
        self.pipe.disconnect()
        state = None
        for _ in range(int(ticks)):
            clock.advance(0.1)
            if self.pm is not None:
                state = self.pm.check()
        self.pipe.reconnect(int(rewind))
        self.ev(i, "partition", ticks=int(ticks), state=state)

    def do_kill(self, i: int) -> None:
        if self.shipper is None or self.primary_dead:
            self.ev(i, "kill-skip")
            return
        self.primary_dead = True
        self.pre_kill_cursor = int(self.shipper.cursor)
        self.shipped_acked = min(int(self.wal.durable_tail),
                                 self.pre_kill_cursor)
        self.ev(i, "kill", durable=int(self.wal.durable_tail),
                shipped=self.pre_kill_cursor,
                acked=self.shipped_acked)

    def do_promote(self, i: int) -> None:
        from node_replication_tpu.fault.health import QUARANTINED

        if self.follower is None or self.promoted:
            self.ev(i, "promote-skip")
            return
        try:
            if (self.pm is not None
                    and self.pm.health.state(self.pm.health_rid)
                    == QUARANTINED):
                rep = self.pm.promote_now(detect_s=0.0)
                applied = int(rep.applied_pos)
                epoch = int(rep.new_epoch)
                detected = 1
            else:
                rep = self.follower.promote()
                applied = int(rep["applied"])
                epoch = int(rep["epoch"])
                detected = 0
        except Exception as e:
            self.vio("replication-gap", i,
                     f"promotion failed: {type(e).__name__}: {e}")
            return
        self.promoted = True
        if applied < self.shipped_acked:
            self.vio("durable-ack-survival", i,
                     f"promoted follower applied {applied} < "
                     f"shipped-acked {self.shipped_acked}")
        self._advance_oracle_f(applied, i)
        # the follower's history is now the authority: the dead
        # primary's unshipped suffix is legally gone
        self.applied = self.applied[:applied]
        self.fpos = min(self.fpos, applied)
        self.oracle = self.oracle_f
        self.ev(i, "promote", applied=applied, epoch=epoch,
                detected=detected)

    # ------------------------------------------------------ sharded steps

    def _shard_of(self, op: list) -> int:
        return self.smap.shard_of_op(tuple(op))

    def _class_fe(self, c: int):
        """The serving frontend for congruence class `c` — a base
        shard's primary (or its promoted follower), an alias class
        riding its base shard after a split, or the split recipient."""
        n0 = len(self.shards)
        if c >= n0:
            d = c - n0
            if d == self.reshard_donor:
                return self.recipient.frontend
            sh = self.shards[d]
            return (sh["follower"].frontend if self.sh_promoted[d]
                    else sh["fe"])
        sh = self.shards[c]
        return (sh["follower"].frontend if self.sh_promoted[c]
                else sh["fe"])

    def _participants(self) -> list:
        return [t for t in self.sh_txn + self.sh_txn_extra
                if t is not None]

    def _fold_shard_ack(self, i: int, s: int, op: list,
                        resp) -> None:
        """Fold one router-acked op into shard `s`'s oracle. Keys are
        disjoint across shards (the `key % N` congruence), so the
        per-shard fold in submission order IS the global fold."""
        expect = self.sh_oracle[s].apply(op)
        self.sh_applied[s].append(list(op))
        if int(resp) != int(expect):
            self.vio("resp-diff", i,
                     f"shard {s} op {op} -> {int(resp)}, oracle "
                     f"{int(expect)}")

    def do_sw(self, i: int, op: list) -> None:
        s = self._shard_of(op)
        try:
            resp = self.router.call(tuple(op))
        except Exception as e:  # typed routing/availability edges
            self.ev(i, "sw-err", shard=s, err=type(e).__name__)
            return
        self._fold_shard_ack(i, s, op, resp)
        self.ev(i, "sw", shard=s, resp=int(resp))

    def do_sbatch(self, i: int, ops: list) -> None:
        """One multi-shard batch through the router: per-op outcomes
        (the CNR non-atomic cross-shard contract) — a dead shard's
        slots error while the survivor's slots commit and must still
        match the oracle."""
        out = self.router.execute_batch(
            [tuple(op) for op in ops], return_exceptions=True,
        )
        results: list = []
        for op, r in zip(ops, out):
            s = self._shard_of(op)
            if isinstance(r, BaseException):
                results.append([s, "err", type(r).__name__])
                continue
            self._fold_shard_ack(i, s, op, r)
            results.append([s, "ok", int(r)])
        self.ev(i, "sbatch", results=results)

    def do_sread(self, i: int, op: list) -> None:
        s = self._shard_of(op)
        fe = self._class_fe(s)
        try:
            val = fe.read(tuple(op), rid=0)
        except Exception as e:
            self.ev(i, "sread-err", shard=s, err=type(e).__name__)
            return
        expect = self.sh_oracle[s].read(op)
        if int(val) != int(expect):
            self.vio("read-diff", i,
                     f"shard {s} read {op} -> {int(val)}, oracle "
                     f"{int(expect)}")
        self.ev(i, "sread", shard=s, val=int(val))

    def do_swal(self, i: int, s: int) -> None:
        if self.sh_dead[s]:
            self.ev(i, "swal-skip", shard=s)
            return
        pos = self.shards[s]["nr"].wal_sync()
        self.ev(i, "swal", shard=s, durable=int(pos))

    def do_sship(self, i: int, s: int, zombie: bool = False) -> None:
        from node_replication_tpu.repl.feed import EpochFencedError

        sh = self.shards[s]
        if not zombie and (self.sh_dead[s] or self.sh_promoted[s]):
            self.ev(i, "sship-skip", shard=s)
            return
        cur0 = int(sh["shipper"].cursor)
        try:
            sh["shipper"]._ship_once()
        except EpochFencedError:
            self.ev(i, "sship-fenced", shard=s)
            return
        except Exception as e:
            self.vio("replication-gap", i,
                     f"shard {s} ship failed: "
                     f"{type(e).__name__}: {e}")
            return
        cur = int(sh["shipper"].cursor)
        if zombie and cur > self.sh_pre_cursor[s]:
            self.vio("zombie-unfenced", i,
                     f"shard {s}'s superseded shipper published "
                     f"{self.sh_pre_cursor[s]}->{cur} past the "
                     f"promotion fence")
        self.ev(i, "sship", shard=s, shipped=cur - cur0, cursor=cur)

    def do_sapply(self, i: int, s: int) -> None:
        sh = self.shards[s]
        if self.sh_promoted[s]:
            self.ev(i, "sapply-skip", shard=s)
            return
        try:
            n = sh["follower"]._apply_once()
        except Exception as e:
            self.vio("replication-gap", i,
                     f"shard {s} follower apply failed: "
                     f"{type(e).__name__}: {e}")
            return
        ap = int(sh["follower"].applied_pos())
        if ap > len(self.sh_applied[s]):
            self.vio("replication-gap", i,
                     f"shard {s} follower applied {ap} > acked "
                     f"history {len(self.sh_applied[s])}")
        self.ev(i, "sapply", shard=s, records=int(n), applied=ap)

    def do_skill(self, i: int, s: int) -> None:
        sh = self.shards[s]
        if self.sh_dead[s]:
            self.ev(i, "skill-skip", shard=s)
            return
        sh["fe"].close(drain=True)
        self.sh_dead[s] = True
        self.sh_pre_cursor[s] = int(sh["shipper"].cursor)
        self.sh_acked[s] = min(int(sh["wal"].durable_tail),
                               self.sh_pre_cursor[s])
        self.ev(i, "skill", shard=s,
                durable=int(sh["wal"].durable_tail),
                shipped=self.sh_pre_cursor[s],
                acked=self.sh_acked[s])

    def do_spromote(self, i: int, s: int) -> None:
        from node_replication_tpu.shard.router import LocalBackend

        sh = self.shards[s]
        if self.sh_promoted[s]:
            self.ev(i, "spromote-skip", shard=s)
            return
        try:
            rep = sh["follower"].promote()
            applied = int(rep["applied"])
            epoch = int(rep["epoch"])
        except Exception as e:
            self.vio("replication-gap", i,
                     f"shard {s} promotion failed: "
                     f"{type(e).__name__}: {e}")
            return
        self.sh_promoted[s] = True
        self.sh_dead[s] = True
        if applied < self.sh_acked[s]:
            self.vio("durable-ack-survival", i,
                     f"shard {s} promoted at {applied} < "
                     f"shipped-acked {self.sh_acked[s]}")
        if applied > len(self.sh_applied[s]):
            self.vio("replication-gap", i,
                     f"shard {s} promoted at {applied} > acked "
                     f"history {len(self.sh_applied[s])}")
            applied = len(self.sh_applied[s])
        # the follower's history is now the authority for this
        # shard's slice; the dead primary's unshipped suffix is
        # legally gone — truncate and refold the per-shard oracle
        self.sh_applied[s] = self.sh_applied[s][:applied]
        self.sh_oracle[s] = make_oracle(
            self.spec.model, MODEL_SIZES[self.spec.model]
        )
        for op in self.sh_applied[s]:
            self.sh_oracle[s].apply(op)
        # re-home the router: re-publish the bumped map and point the
        # victim's slot at the promoted follower's frontend — the
        # other shards' backends never change (isolation)
        new_map = self.smap.with_address(s, None)
        self.router.repoint(
            s, LocalBackend(s, sh["follower"].frontend, new_map),
            new_map=new_map,
        )
        self.smap = new_map
        for t in self._participants():
            t.set_map(new_map)
        if s < len(self.sh_txn):
            self.sh_txn[s].set_frontend(sh["follower"].frontend,
                                        wal=sh["follower"].nr.wal)
        self.ev(i, "spromote", shard=s, applied=applied, epoch=epoch,
                map_version=int(new_map.version))

    # ------------------------------------------------------- txn steps

    def do_stxn(self, i: int, ops: list, crash: int) -> None:
        """One atomic cross-shard transaction through the REAL
        `TxnCoordinator` (presumed-abort 2PC over the sim's backends,
        intents/decisions on the case's tmp dir). `crash=1` kills the
        coordinator at the `txn-decide` fault site — one instruction
        AFTER its durable decision publish — then simulates the
        restart: epoch bump + every participant resolving in-doubt
        state from the decision log. Property ``txn-atomicity``: a
        decided txn re-drives to commit on every shard; an aborted
        one leaves ZERO per-key effect."""
        if self.router is None:
            self.ev(i, "stxn-skip")
            return
        if self.coord is None:
            from node_replication_tpu.shard.txn import TxnCoordinator

            self.coord = TxnCoordinator(
                self.router, os.path.join(self.tmp, "decisions"),
                name="sim",
            )
        tops = [tuple(op) for op in ops]
        shards = sorted({self._shard_of(list(op)) for op in tops})
        plan = (self._one_shot_plan("txn-decide", "raise")
                if crash else None)
        err = None
        results = None
        try:
            if plan is not None:
                with plan.armed():
                    results = self.coord.execute_txn(tops)
            else:
                results = self.coord.execute_txn(tops)
        except Exception as e:
            err = e
        if err is not None and plan is not None and plan.fired:
            # the coordinator REACHED its decision point (the fault
            # site sits one line past the durable publish), so the
            # commit is decided: restart recovery must re-drive it —
            # a resolve to anything else means the decision record
            # was lost (the ack-before-decision bug class)
            epoch = self.decisions.bump_epoch()
            outcomes: dict = {}
            for t in self._participants():
                outcomes.update(t.resolve_in_doubt(
                    decisions=self.decisions, epoch=epoch))
            self.coord = None  # the old generation died with it
            if set(outcomes.values()) != {"commit"}:
                self.vio("txn-atomicity", i,
                         f"decided txn resolved {outcomes} after "
                         f"coordinator restart — the durable commit "
                         f"decision did not survive")
                self.ev(i, "stxn-lost", shards=shards)
                return
            for op in tops:
                s = self._shard_of(list(op))
                self.sh_oracle[s].apply(list(op))
                self.sh_applied[s].append(list(op))
            self.ev(i, "stxn-recovered", shards=shards)
            return
        if err is not None:
            # aborted (conflict / unavailability / in-doubt before
            # the decision): atomicity demands ZERO visible effect —
            # read every touched key back through its serving path
            for op in tops:
                s = self._shard_of(list(op))
                try:
                    val = self._class_fe(s).read(
                        (1, int(op[1]), 0), rid=0)
                except Exception:
                    continue  # dead shard: nothing readable to leak
                expect = self.sh_oracle[s].read([1, int(op[1]), 0])
                if int(val) != int(expect):
                    self.vio("txn-atomicity", i,
                             f"aborted txn left key {int(op[1])} = "
                             f"{int(val)} (expected {int(expect)})")
            self.ev(i, "stxn-abort", err=type(err).__name__,
                    shards=shards)
            return
        for op, r in zip(tops, results):
            s = self._shard_of(list(op))
            expect = self.sh_oracle[s].apply(list(op))
            self.sh_applied[s].append(list(op))
            if int(r) != int(expect):
                self.vio("txn-atomicity", i,
                         f"txn op {list(op)} -> {int(r)}, oracle "
                         f"{int(expect)}")
        self.ev(i, "stxn", shards=shards,
                resps=[int(r) for r in results])

    def do_sreshard(self, i: int, donor: int) -> None:
        """Live split of class `donor` (mod N) into `{donor,
        donor+N}` (mod 2N), mirroring `shard/reshard.py`: catch the
        standby up, stage backends (+ participants) for every refined
        class, adopt the refined map, promote the follower into the
        moved class. Per-class bookkeeping refolds under the new
        congruence; the end-of-case check for resharded runs is
        ``reshard-exactness`` (global per-key read-back)."""
        from node_replication_tpu.shard.router import LocalBackend
        from node_replication_tpu.shard.txn import TxnParticipant

        donor = int(donor)
        if (self.resharded or donor >= len(self.shards)
                or self.sh_dead[donor] or self.sh_promoted[donor]):
            self.ev(i, "sreshard-skip", donor=donor)
            return
        sh = self.shards[donor]
        # catch-up: cooperative stepping stands in for the background
        # ship/apply lanes (bounded — the history is finite)
        target = len(self.sh_applied[donor])
        for _ in range(200):
            if int(sh["follower"].applied_pos()) >= target:
                break
            sh["nr"].wal_sync()
            sh["shipper"]._ship_once()
            sh["follower"]._apply_once()
        if int(sh["follower"].applied_pos()) < target:
            self.vio("replication-gap", i,
                     f"shard {donor} standby stuck at "
                     f"{sh['follower'].applied_pos()} < {target}")
            return
        n0 = len(self.shards)
        moved = donor + n0
        new_map = self.smap.refine()
        for d in range(n0):
            if d == donor:
                continue
            q = self.shards[d]
            t = TxnParticipant(
                d + n0, q["fe"], new_map,
                os.path.join(self.tmp, f"r{d + n0}", "txn"),
                decisions=self.decisions, wal=q["wal"],
            )
            self.sh_txn_extra.append(t)
            self.router.attach_backend(
                d + n0,
                LocalBackend(d + n0, q["fe"], new_map,
                             participant=t),
            )
        rt = TxnParticipant(
            moved, sh["follower"].frontend, new_map,
            os.path.join(self.tmp, f"r{moved}", "txn"),
            decisions=self.decisions, wal=sh["follower"].nr.wal,
        )
        self.sh_txn_extra.append(rt)
        self.router.attach_backend(
            moved,
            LocalBackend(moved, sh["follower"].frontend, new_map,
                         participant=rt),
        )
        self.router.adopt(new_map, reason=f"sim-split-s{donor}")
        try:
            rep = sh["follower"].promote()
        except Exception as e:
            self.vio("replication-gap", i,
                     f"split promotion failed: "
                     f"{type(e).__name__}: {e}")
            return
        applied = int(rep["applied"])
        if applied < target:
            self.vio("reshard-exactness", i,
                     f"recipient promoted at {applied} < acked "
                     f"history {target}")
        # refold the per-class bookkeeping under the refined
        # congruence: per-shard order is preserved and classes are
        # disjoint, so the refined folds are exact
        C = 2 * n0
        old_applied = self.sh_applied
        self.sh_applied = [[] for _ in range(C)]
        for s in range(n0):
            for op in old_applied[s]:
                c = new_map.shard_of_op(tuple(op))
                self.sh_applied[c].append(op)
        self.sh_oracle = [
            make_oracle(self.spec.model, MODEL_SIZES[self.spec.model])
            for _ in range(C)
        ]
        for c in range(C):
            for op in self.sh_applied[c]:
                self.sh_oracle[c].apply(op)
        self.sh_dead += [False] * n0
        self.sh_promoted += [False] * n0
        self.sh_pre_cursor += [0] * n0
        self.sh_acked += [0] * n0
        self.smap = new_map
        for t in self._participants():
            t.set_map(new_map)
        self.resharded = True
        self.recipient = sh["follower"]
        self.reshard_donor = donor
        self.ev(i, "sreshard", donor=donor, moved=moved,
                map_version=int(new_map.version), applied=applied)

    # ---------------------------------------------------------- end state

    def _check_arrays(self, state, oracle, i: int,
                      prop: str = "state-diff") -> None:
        import jax

        expect = oracle.arrays()
        leaves = {}
        if isinstance(state, dict):
            leaves = state
        else:  # pytree fallback
            leaves = {str(k): v for k, v in
                      enumerate(jax.tree.leaves(state))}
        for name, arr in expect.items():
            if name not in leaves:
                self.vio(prop, i, f"state leaf {name!r} missing")
                continue
            got = np.asarray(leaves[name])
            if got.shape != arr.shape or not np.array_equal(
                    got, np.asarray(arr, got.dtype)):
                self.vio(
                    prop, i,
                    f"state leaf {name!r} diverges from the oracle "
                    f"(got {got.tolist()!r:.120s} want "
                    f"{np.asarray(arr).tolist()!r:.120s})",
                )

    def _check_ring(self, nr, expect_ops: list, i: int) -> None:
        from node_replication_tpu.core.log import ring_slice

        tail = int(np.asarray(nr.log.tail))
        if tail != len(expect_ops):
            self.vio("log-content", i,
                     f"log tail {tail} != acked op count "
                     f"{len(expect_ops)}")
            return
        if tail == 0:
            return
        opcodes, args = ring_slice(nr.spec, nr.log, 0, tail)
        aw = args.shape[1]
        for k, op in enumerate(expect_ops):
            want = [int(op[0])] + [int(x) for x in op[1:1 + aw]]
            want += [0] * (1 + aw - len(want))
            got = [int(opcodes[k])] + [int(x) for x in args[k]]
            if got != want:
                self.vio("log-content", i,
                         f"log[{k}] = {got} != acked {want}")
                return

    def _check_shard_slice(self, nr, s: int, i: int) -> None:
        """Every key in shard `s`'s ring must be ≡ s (mod N) — an op
        leaked into the wrong shard's keyspace slice is the
        fleet-level routing invariant breaking, named directly."""
        from node_replication_tpu.core.log import ring_slice

        tail = int(np.asarray(nr.log.tail))
        if tail == 0:
            return
        _opcodes, args = ring_slice(nr.spec, nr.log, 0, tail)
        for k in range(tail):
            key = int(args[k][0])
            if key % self.spec.n_shards != s:
                self.vio("shard-isolation", i,
                         f"shard {s} log[{k}] holds key {key} "
                         f"(owner shard "
                         f"{key % self.spec.n_shards})")
                return

    def _finalize_sharded(self) -> None:
        if self.resharded:
            # the refined classes interleave the donor's pre-split
            # records across two histories, so the per-shard ring and
            # array checks no longer apply — the reshard contract is
            # GLOBAL read-back exactness: every key serves the fold
            # of exactly its class's acked ops (zero lost, zero
            # duplicated, zero re-homed into the wrong slice)
            size = MODEL_SIZES[self.spec.model]
            for k in range(size):
                c = self.smap.shard_of(k)
                try:
                    val = self._class_fe(c).read((1, k, 0), rid=0)
                except Exception as e:
                    self.vio("reshard-exactness", -1,
                             f"key {k} (class {c}) unreadable after "
                             f"split: {type(e).__name__}")
                    continue
                expect = self.sh_oracle[c].read([1, k, 0])
                if int(val) != int(expect):
                    self.vio("reshard-exactness", -1,
                             f"key {k} (class {c}) -> {int(val)}, "
                             f"fold of acked ops {int(expect)}")
            return
        for s in range(self.spec.n_shards):
            sh = self.shards[s]
            if self.sh_promoted[s]:
                nr = sh["follower"].nr
                nr.sync()
                self._check_shard_slice(nr, s, -1)
                self._check_arrays(nr.verify(lambda st: st),
                                   self.sh_oracle[s], -1,
                                   prop="shard-isolation")
                self._check_ring(nr, self.sh_applied[s], -1)
                continue
            if not self.sh_dead[s]:
                sh["fe"].close()
            nr = sh["nr"]
            nr.sync()
            self._check_shard_slice(nr, s, -1)
            self._check_arrays(nr.verify(lambda st: st),
                               self.sh_oracle[s], -1,
                               prop="shard-isolation")
            self._check_ring(nr, self.sh_applied[s], -1)
            # the follower's state must be a PREFIX fold of exactly
            # this shard's acked ops (no lost/dup/foreign records)
            ap = int(sh["follower"].applied_pos())
            if ap > len(self.sh_applied[s]):
                self.vio("replication-gap", -1,
                         f"shard {s} follower applied {ap} > acked "
                         f"history {len(self.sh_applied[s])}")
                continue
            f_oracle = make_oracle(self.spec.model,
                                   MODEL_SIZES[self.spec.model])
            for op in self.sh_applied[s][:ap]:
                f_oracle.apply(op)
            fnr = sh["follower"].nr
            fnr.sync()
            self._check_shard_slice(fnr, s, -1)
            self._check_arrays(fnr.verify(lambda st: st),
                               f_oracle, -1, prop="shard-isolation")
            self._check_ring(fnr, self.sh_applied[s][:ap], -1)

    def finalize(self) -> None:
        spec = self.spec
        if spec.flavor == "sharded":
            self._finalize_sharded()
            return
        if spec.flavor == "repl":
            if not self.promoted and not self.primary_dead:
                # drain: finish shipping/applying what is already
                # durable so the follower checks run at a fixed point
                # (over a live pipe — a shrunk case may have stripped
                # the generator's paired reconnect)
                if self.pipe is not None:
                    self.pipe.reconnect(0)
                for _ in range(4):
                    self.do_wal_sync(-1)
                    self.do_ship(-1)
                    self.do_apply(-1)
            if self.promoted:
                self.follower.nr.sync()
                self._check_arrays(
                    self.follower.nr.verify(lambda s: s),
                    self.oracle, -1)
                self._check_ring(self.follower.nr, self.applied, -1)
            else:
                self.wr.sync()
                if not self.wr.replicas_equal():
                    self.vio("bit-identity", -1,
                             "replicas disagree after sync")
                self._check_arrays(self.wr.verify(lambda s: s),
                                   self.oracle, -1)
                self._check_ring(self.wr, self.applied, -1)
                ap = self.follower.applied_pos()
                self._advance_oracle_f(ap, -1)
                self.follower.nr.sync()
                self._check_arrays(
                    self.follower.nr.verify(lambda s: s),
                    self.oracle_f, -1)
                self._check_ring(self.follower.nr,
                                 self.applied[:ap], -1)
            return
        if self.fe is not None:
            self.fe.close()
            self.fe = None
        self.wr.sync()
        if not self.wr.replicas_equal():
            self.vio("bit-identity", -1,
                     "replicas disagree after sync")
        self._check_arrays(self.wr.verify(lambda s: s), self.oracle,
                           -1)
        if spec.wrapper == "nr":
            self._check_ring(self.wr, self.applied, -1)


def run_case(spec: CaseSpec) -> CaseResult:
    """Interpret one spec deterministically; returns the result with
    the violation list, the event log, and the run digest (same spec
    => same digest, the byte-identical-replay contract)."""
    run = _Run(spec)
    clock = SimClock()
    with installed(clock):
        run._build()
        try:
            for i, step in enumerate(spec.steps):
                kind = step[0]
                if kind == "w":
                    run.do_write(i, int(step[1]), list(step[2]))
                elif kind == "wf":
                    run.do_write(i, int(step[1]), list(step[4]),
                                 fault=(step[2], step[3]))
                elif kind == "r":
                    run.do_read(i, int(step[1]), list(step[2]))
                elif kind == "rf":
                    run.do_read(i, int(step[1]), list(step[2]),
                                fault=("read-sync", "raise"))
                elif kind == "burst":
                    run.do_burst(i, list(step[1]))
                elif kind == "corrupt":
                    run.do_corrupt(i, int(step[1]))
                elif kind == "probe":
                    run.do_probe(i)
                elif kind == "sync":
                    if run.wr is not None and not run.primary_dead:
                        run.wr.sync()
                    run.ev(i, "sync")
                elif kind == "wal-sync":
                    run.do_wal_sync(i)
                elif kind == "snapshot":
                    run.do_snapshot(i)
                elif kind == "crash":
                    run.do_crash(i, int(step[1]), int(step[2]))
                elif kind == "ship":
                    run.do_ship(i)
                elif kind == "zombie-ship":
                    run.do_ship(i, zombie=True)
                elif kind == "apply":
                    run.do_apply(i)
                elif kind == "fread":
                    run.do_fread(i, list(step[1]), int(step[2]))
                elif kind == "watch":
                    run.do_watch(i, int(step[1]), clock)
                elif kind == "disconnect":
                    run.do_pipe(i, "disconnect")
                elif kind == "reconnect":
                    run.do_pipe(i, "reconnect", int(step[1]))
                elif kind == "partition":
                    run.do_partition(i, int(step[1]), int(step[2]),
                                     clock)
                elif kind == "kill":
                    run.do_kill(i)
                elif kind == "promote":
                    run.do_promote(i)
                elif kind == "sw":
                    run.do_sw(i, list(step[1]))
                elif kind == "sbatch":
                    run.do_sbatch(i, [list(o) for o in step[1]])
                elif kind == "sread":
                    run.do_sread(i, list(step[1]))
                elif kind == "swal":
                    run.do_swal(i, int(step[1]))
                elif kind == "sship":
                    run.do_sship(i, int(step[1]))
                elif kind == "sapply":
                    run.do_sapply(i, int(step[1]))
                elif kind == "skill":
                    run.do_skill(i, int(step[1]))
                elif kind == "spromote":
                    run.do_spromote(i, int(step[1]))
                elif kind == "szombie":
                    run.do_sship(i, int(step[1]), zombie=True)
                elif kind == "stxn":
                    run.do_stxn(i, [list(o) for o in step[1]],
                                int(step[2]))
                elif kind == "sreshard":
                    run.do_sreshard(i, int(step[1]))
                else:
                    raise ValueError(f"unknown step kind {kind!r}")
            run.finalize()
        finally:
            run._teardown()
    return CaseResult(
        spec=spec,
        violations=run.violations,
        events=run.events,
        digest=_digest(spec, run.events),
    )
