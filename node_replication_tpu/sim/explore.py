"""Seed-sweep CLI: the `sim-smoke` gate.

    python -m node_replication_tpu.sim.explore --seeds 1000

generates and runs one `CaseSpec` per seed (models x wrappers x
flavors per the filters), reports the coverage matrix, and exits
nonzero on any property violation — writing each failing seed's full
artifact (spec + events + violations + shrunk schedule + digest) as
JSON under `--out` so CI can upload it and a human can replay it:

    python -m node_replication_tpu.sim.replay <seed>

Canary mode (`--canary <name>`) inverts the contract: it re-injects a
known bug (`sim/canary.py`), narrows the sweep to the flavor that
must catch it, and exits 0 only when (1) some seed catches the bug,
(2) that seed REPLAYS byte-identically (same digest twice), and
(3) the shrinker reduces the schedule — the harness proving, in CI,
that it can catch what it claims to catch.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from node_replication_tpu.sim import canary as canary_mod
from node_replication_tpu.sim.properties import (
    FLAVORS,
    MODELS,
    WRAPPERS,
    generate_case,
    run_case,
)
from node_replication_tpu.sim.shrink import shrink_case


def _csv(value: str, allowed) -> tuple:
    parts = tuple(p.strip() for p in value.split(",") if p.strip())
    bad = [p for p in parts if p not in allowed]
    if bad:
        raise argparse.ArgumentTypeError(
            f"unknown {bad} (allowed: {', '.join(allowed)})"
        )
    return parts


def _artifact(out_dir: str, seed: int, payload: dict) -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"failing-seed-{seed}.json")
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
    return path


def _sharded(args) -> int:
    """Split the seed range over `--procs` child sweeps (same seed ->
    same case; sharding is pure parallelism). Children stream their
    output through; the parent fails if any child fails."""
    import subprocess

    procs = max(1, int(args.procs))
    total = args.seeds
    base = args.seed_start
    chunks = []
    for i in range(procs):
        lo = base + (total * i) // procs
        hi = base + (total * (i + 1)) // procs
        if hi > lo:
            chunks.append((lo, hi - lo))
    children = []
    for lo, n in chunks:
        cmd = [
            sys.executable, "-m", "node_replication_tpu.sim.explore",
            "--seeds", str(n), "--seed-start", str(lo),
            "--procs", "1",
            "--models", ",".join(args.models),
            "--wrappers", ",".join(args.wrappers),
            "--flavors", ",".join(args.flavors),
            "--max-failures", str(args.max_failures),
            "--progress", str(args.progress),
        ]
        if args.out:
            cmd += ["--out", args.out]
        if args.no_shrink:
            cmd += ["--no-shrink"]
        children.append(subprocess.Popen(cmd))
    rc = 0
    for (lo, n), p in zip(chunks, children):
        code = p.wait()
        if code != 0:
            print(f"shard [{lo}, {lo + n}) exited {code}")
            rc = 1
    print(f"{len(chunks)} shard(s) done; "
          + ("FAILURES found" if rc else "all clean"))
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.sim.explore",
        description="seeded property sweep over the sim harness",
    )
    ap.add_argument("--seeds", type=int, default=200,
                    help="number of seeds to sweep (default 200)")
    ap.add_argument("--seed-start", type=int, default=0)
    ap.add_argument("--models", default=",".join(MODELS),
                    type=lambda v: _csv(v, MODELS))
    ap.add_argument("--wrappers", default=",".join(WRAPPERS),
                    type=lambda v: _csv(v, WRAPPERS))
    ap.add_argument("--flavors", default=",".join(FLAVORS),
                    type=lambda v: _csv(v, FLAVORS))
    ap.add_argument("--canary", default=None,
                    choices=sorted(canary_mod.CANARIES),
                    help="re-inject a known bug; exit 0 iff the sweep "
                         "catches it, replays it byte-identically, "
                         "and shrinks it")
    ap.add_argument("--out", default=None,
                    help="directory for failing-seed JSON artifacts")
    ap.add_argument("--max-failures", type=int, default=5,
                    help="stop after this many failing seeds")
    ap.add_argument("--no-shrink", action="store_true")
    ap.add_argument("--progress", type=int, default=200,
                    help="print a progress line every N seeds")
    ap.add_argument("--procs", type=int, default=1,
                    help="shard the seed range over N worker "
                         "processes (seed->case mapping is "
                         "unchanged; this only parallelizes)")
    args = ap.parse_args(argv)

    if args.procs > 1:
        if args.canary:
            # a canary sweep stops at the first catch and then runs
            # the replay/shrink verification in-context — sharding
            # would race shards past the catch; run serial, loudly
            print("--canary runs single-process (--procs ignored)")
        else:
            return _sharded(args)

    models, wrappers, flavors = args.models, args.wrappers, args.flavors
    if args.canary:
        flavors = (canary_mod.CANARY_FLAVOR[args.canary],)
        print(f"canary {args.canary!r}: sweeping flavor "
              f"{flavors[0]!r} until caught")

    import contextlib

    ctx = (canary_mod.armed(args.canary) if args.canary
           else contextlib.nullcontext())
    t0 = time.monotonic()
    matrix: dict = {}
    failures: list = []
    ran = 0
    with ctx:
        for seed in range(args.seed_start,
                          args.seed_start + args.seeds):
            spec = generate_case(seed, models=models,
                                 wrappers=wrappers, flavors=flavors)
            res = run_case(spec)
            ran += 1
            key = (spec.model, spec.wrapper, spec.flavor)
            ok, bad = matrix.get(key, (0, 0))
            matrix[key] = (ok + (1 if res.ok else 0),
                           bad + (0 if res.ok else 1))
            if args.progress and ran % args.progress == 0:
                print(f"  ... {ran}/{args.seeds} seeds, "
                      f"{len(failures)} failing, "
                      f"{time.monotonic() - t0:.1f}s", flush=True)
            if res.ok:
                continue
            failures.append((seed, spec, res))
            print(f"seed {seed} FAILED "
                  f"[{spec.model}/{spec.wrapper}/{spec.flavor}] "
                  f"digest {res.digest}:")
            for v in res.violations:
                print(f"  - {v.prop} @ step {v.step}: {v.detail}")
            if args.canary or len(failures) >= args.max_failures:
                break

        # post-process failures INSIDE the canary context (the bug
        # must stay re-injected for the replay and the shrink runs):
        # replay-determinism check + shrink + artifact. Canary mode
        # REQUIRES all three to succeed.
        verdict_ok = not failures
        for seed, spec, res in failures:
            replay = run_case(generate_case(
                seed, models=models, wrappers=wrappers,
                flavors=flavors))
            identical = replay.digest == res.digest
            print(f"\nseed {seed}: replay digest "
                  f"{'IDENTICAL' if identical else 'DIVERGED'} "
                  f"({res.digest})")
            payload = {
                "seed": seed,
                "filters": {"models": list(models),
                            "wrappers": list(wrappers),
                            "flavors": list(flavors)},
                "canary": args.canary,
                "spec": spec.as_dict(),
                "violations": [v.as_dict() for v in res.violations],
                "digest": res.digest,
                "replay_identical": identical,
            }
            shrunk_ok = True
            if not args.no_shrink:
                rep = shrink_case(spec)
                shrunk_ok = rep.shrunk_steps < rep.original_steps
                print(f"seed {seed}: shrunk {rep.original_steps} -> "
                      f"{rep.shrunk_steps} step(s) in "
                      f"{rep.runs} run(s):")
                for s in rep.spec.steps:
                    print(f"    {s}")
                for v in rep.result.violations:
                    print(f"  still: {v.prop}: {v.detail}")
                payload["shrunk"] = rep.as_dict()
            if args.out:
                path = _artifact(args.out, seed, payload)
                print(f"seed {seed}: artifact written to {path}")
            if args.canary:
                verdict_ok = identical and shrunk_ok

    dur = time.monotonic() - t0
    print(f"\nswept {ran} seed(s) in {dur:.1f}s "
          f"({ran / max(dur, 1e-9):.1f}/s), "
          f"{len(failures)} failing")
    for (m, w, f), (ok, bad) in sorted(matrix.items()):
        print(f"  {m:>8s} x {w:>3s} x {f:>7s}: {ok} ok"
              + (f", {bad} FAIL" if bad else ""))

    if args.canary:
        if not failures:
            print(f"\ncanary {args.canary!r} SURVIVED the sweep — "
                  f"the harness missed a known bug")
            return 1
        if not verdict_ok:
            print(f"\ncanary {args.canary!r} caught, but replay/"
                  f"shrink verification failed")
            return 1
        print(f"\ncanary {args.canary!r} caught, replayed "
              f"byte-identically, and shrunk — harness verified")
        return 0
    return 0 if verdict_ok else 1


if __name__ == "__main__":
    sys.exit(main())
