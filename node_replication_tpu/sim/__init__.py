"""Deterministic chaos: virtual time + seeded schedules + an
oracle-differential property harness over serve + fault + durable +
repl.

PRs 3-6 built the production surface; every robustness gate they left
behind (`bench.py --chaos/--crash/--follower`) explores exactly ONE
wall-clock interleaving per run. This package is the other half of
the FoundationDB simulation-testing idea: make time injectable
(`utils/clock.py` + `SimClock`), drive every background loop —
serve workers, fault medics, the WAL shipper, the follower apply
loop, the promotion watcher — one quantum at a time on a seeded
cooperative schedule, and check every run against a pure-Python
oracle. A single seed then fully determines the interleaving, so

    python -m node_replication_tpu.sim.explore --seeds 1000

sweeps a thousand adversarial schedules in seconds-per-hundred, any
failure replays byte-identically from its seed

    python -m node_replication_tpu.sim.replay <seed>

and the delta-debugging shrinker (`sim/shrink.py`) minimizes the
op/fault schedule before a human ever looks at it.

Modules:

- `scheduler.py` — the seeded cooperative step-scheduler.
- `oracle.py`    — pure-numpy twins of the bundled models.
- `properties.py`— case generation + the step interpreter + the
  property catalog (response differential, log-content exactness,
  maybe-executed honesty, bit-identity, durable-ack survival,
  bounded staleness, zombie fencing).
- `explore.py`   — the seed-sweep CLI (the `sim-smoke` CI gate).
- `replay.py`    — byte-identical single-seed reproduction.
- `shrink.py`    — ddmin over a failing schedule.
- `canary.py`    — deliberately re-injectable bugs that prove the
  harness can catch what it claims to catch.
"""

from node_replication_tpu.sim.oracle import make_oracle
from node_replication_tpu.sim.properties import (
    CaseResult,
    CaseSpec,
    Violation,
    generate_case,
    run_case,
)
from node_replication_tpu.sim.scheduler import SimScheduler

__all__ = [
    "CaseResult",
    "CaseSpec",
    "SimScheduler",
    "Violation",
    "generate_case",
    "make_oracle",
    "run_case",
]
