"""AST infrastructure shared by every nrlint rule.

Three pieces of project knowledge live here so the rules stay small:

1. **Suppressions** — `# nrlint: disable=<rule>[,<rule>]` comments,
   parsed with `tokenize` so string literals can't spoof them. A
   suppression covers its own line and the line directly below it (for
   a standalone comment above a long statement).

2. **Name resolution** — per-module import maps plus simple local-alias
   tracking (`exec_fn = log_catchup_all if ... else log_exec_all`), so
   rules can ask "what does this expression denote?" in dotted form
   (`jax.jit`, `numpy.asarray`, `node_replication_tpu.core.log.
   log_exec_all`).

3. **Traced-closure inference** — the set of function scopes that
   execute under JAX tracing. Seeds: jit-family decorators, functions
   (and lambdas) referenced inside the arguments of
   `jax.jit`/`jax.vmap`/`lax.scan`/`lax.cond`/`lax.switch`/
   `pallas_call`/`checkify.checkify`/... calls anywhere in the analyzed
   set, and every transition/window function registered on a
   `Dispatch(...)` constructor (those run in-trace by contract). The
   closure then propagates through the project call graph to a
   fixpoint, across modules, so e.g. `_exec_one` (called by the jitted
   `log_exec_all`) is traced without any annotation.

Host-side escape hatch: a region guarded by an `isinstance(...,
jax.core.Tracer)` test (the project's eager-only idiom, see
`core/log.py:_catchup_union_plan`) is exempt from traced-context rules —
the author is explicitly branching on trace-vs-eager there.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

# The project package: imports under this root resolve cross-module.
PROJECT_PACKAGE = "node_replication_tpu"

# Calls whose function-valued arguments enter JAX tracing.
TRACING_CALLS = frozenset({
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.associative_scan",
    "jax.lax.map",
    "jax.experimental.checkify.checkify",
    "jax.experimental.pallas.pallas_call",
    "jax.experimental.shard_map.shard_map",
    f"{PROJECT_PACKAGE}.utils.checks.checked",
})

# `Dispatch(...)` keyword args whose values are in-trace transition or
# window functions (`make_state` runs eagerly at init and is excluded).
DISPATCH_FN_KWARGS = frozenset({
    "write_ops", "read_ops", "window_apply", "window_plan",
    "window_merge",
})
DISPATCH_FN_POSARGS = (2, 3)  # (name, make_state, write_ops, read_ops)

_SUPPRESS_RE = re.compile(
    r"#\s*nrlint:\s*disable\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)
_SUPPRESS_MENTION_RE = re.compile(r"#\s*nrlint\b")


def parse_suppressions(
    source: str,
) -> tuple[dict[int, frozenset[str]], list[int]]:
    """Parse suppression comments.

    Returns `(suppressions, malformed)`: line number -> rule ids
    suppressed there, plus the lines of malformed `# nrlint` comments.
    ONLY the exact `# nrlint: disable=<rule>[,<rule>]` form suppresses —
    a typo (`disable host-sync-in-jit`, `nrlint disable=...`) must not
    silently disarm every rule on the line, so any other `# nrlint`
    mention is reported as malformed instead."""
    out: dict[int, frozenset[str]] = {}
    malformed: list[int] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = frozenset(
                    r.strip() for r in m.group(1).split(",")
                )
                out[line] = out.get(line, frozenset()) | rules
            elif _SUPPRESS_MENTION_RE.search(tok.string):
                malformed.append(line)
    except (tokenize.TokenError, SyntaxError):
        pass
    return out, malformed


def _module_name_for(path: str) -> str:
    """Dotted module name, walking up through __init__.py packages."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if parts[0] == "__init__":
        parts = parts[1:] or parts
    return ".".join(reversed(parts))


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class ModuleInfo:
    """One parsed file: tree with parent links, imports, defs, aliases."""

    def __init__(self, path: str, source: str | None = None):
        self.path = path
        if source is None:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.module_name = _module_name_for(path)
        self.suppressions, self.malformed_suppressions = (
            parse_suppressions(source)
        )
        # parent links (NodeVisitor-free: one walk)
        self.tree._nrl_parent = None  # type: ignore[attr-defined]
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._nrl_parent = node  # type: ignore[attr-defined]
        # imports: local name -> dotted target
        self.imports: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.imports[a.asname] = a.name
                    else:
                        # `import jax.numpy` binds the root name `jax`
                        root = a.name.split(".")[0]
                        self.imports[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )
        # module-level function defs by name
        self.top_defs: dict[str, ast.AST] = {
            n.name: n
            for n in self.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

    # -------------------------------------------------- tree navigation

    def parent(self, node: ast.AST) -> ast.AST | None:
        return getattr(node, "_nrl_parent", None)

    def enclosing_functions(self, node: ast.AST):
        """Innermost-first chain of enclosing function-like scopes."""
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                yield cur
            cur = self.parent(cur)

    def enclosing_class(self, node: ast.AST) -> ast.ClassDef | None:
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            if isinstance(cur, _FUNC_NODES):
                return None
            cur = self.parent(cur)
        return None

    def in_eager_guard(self, node: ast.AST) -> bool:
        """Inside an `if` whose test mentions `Tracer` — the project's
        explicit trace-vs-eager branch; exempt from traced-context
        rules on both arms (the author is handling the split)."""
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, ast.If) and "Tracer" in ast.dump(cur.test):
                return True
            cur = self.parent(cur)
        return False

    # ----------------------------------------------------- name lookup

    def dotted(self, node: ast.AST) -> str | None:
        """Resolve `Name`/`Attribute` chains to a dotted external name
        through the import map (`lax.scan` -> `jax.lax.scan`)."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        base = self.imports.get(cur.id)
        if base is None:
            return None
        parts.append(base)
        return ".".join(reversed(parts))


@dataclasses.dataclass
class Diagnostic:
    path: str
    line: int
    col: int
    rule_id: str
    severity: str
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule_id} {self.severity}: {self.message}{tag}"
        )


class Project:
    """All analyzed modules + the cross-module traced closure."""

    def __init__(self, modules: list[ModuleInfo]):
        self.modules = modules
        self.by_name = {m.module_name: m for m in modules}
        # project symbol table: dotted name -> (module, def node)
        self.symbols: dict[str, tuple[ModuleInfo, ast.AST]] = {}
        for m in modules:
            for name, node in m.top_defs.items():
                self.symbols[f"{m.module_name}.{name}"] = (m, node)
        self.dispatch_fns: set[int] = set()  # id() of def nodes
        self._traced: set[int] = set()       # id() of function scopes
        # id(scope) -> name -> [("def"|"alias", node), ...] in walk
        # order; built lazily so each scope body is walked ONCE no
        # matter how many names resolve inside it (the naive re-walk
        # was quadratic and dominated whole-package build time)
        self._scope_index: dict[int, dict[str, list]] = {}
        self._infer()

    # ------------------------------------------------------- queries

    def is_traced_scope(self, fn: ast.AST) -> bool:
        return id(fn) in self._traced

    def traced_context(self, mod: ModuleInfo, node: ast.AST):
        """The innermost traced function scope enclosing `node`, or
        None. Any enclosing traced scope counts: a nested def inside a
        traced function executes in-trace when called."""
        for fn in mod.enclosing_functions(node):
            if id(fn) in self._traced:
                return fn
        return None

    def is_dispatch_fn(self, fn: ast.AST) -> bool:
        return id(fn) in self.dispatch_fns

    # ------------------------------------------------------ inference

    def _mark(self, fn: ast.AST, worklist: list) -> None:
        if id(fn) not in self._traced:
            self._traced.add(id(fn))
            worklist.append(fn)

    def _scope_names(self, scope: ast.AST) -> dict[str, list]:
        """Name -> [(kind, node)] for defs and single-Name-target
        assigns anywhere under `scope`, in the same statement-major
        walk order the resolver historically observed."""
        idx = self._scope_index.get(id(scope))
        if idx is None:
            idx = {}
            body = scope.body if isinstance(scope.body, list) else []
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        idx.setdefault(node.name, []).append(
                            ("def", node)
                        )
                    elif (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                    ):
                        idx.setdefault(node.targets[0].id, []).append(
                            ("alias", node)
                        )
            self._scope_index[id(scope)] = idx
        return idx

    def _resolve_callable_name(
        self, mod: ModuleInfo, at: ast.AST, name: str,
        seen: set[str] | None = None,
    ) -> list[ast.AST]:
        """Candidate def nodes a bare name may denote at `at`: local
        defs and simple aliases in enclosing scopes, module-level defs,
        then project imports."""
        seen = seen if seen is not None else set()
        if name in seen:
            return []
        seen.add(name)
        out: list[ast.AST] = []
        scopes = list(mod.enclosing_functions(at))
        for scope in scopes:
            for kind, node in self._scope_names(scope).get(name, ()):
                if kind == "def":
                    out.append(node)
                else:
                    # alias: union every name its value mentions.
                    # A name being CALLED in the value
                    # (`replay = make_replay(...)`) is a maker run
                    # at setup time: the alias denotes whatever it
                    # RETURNS, so contribute the maker's nested
                    # defs, not the maker's own host-side body.
                    called = {
                        id(c.func)
                        for c in ast.walk(node.value)
                        if isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Name)
                    }
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Load
                        ):
                            hits = self._resolve_callable_name(
                                mod, at, sub.id, seen
                            )
                            if id(sub) in called:
                                for fn in hits:
                                    out.extend(
                                        s for s in ast.walk(fn)
                                        if s is not fn
                                        and isinstance(
                                            s, _FUNC_NODES
                                        )
                                    )
                            else:
                                out.extend(hits)
                        elif isinstance(sub, ast.Lambda):
                            out.append(sub)
            if out:
                return out
        if name in mod.top_defs:
            return [mod.top_defs[name]]
        target = mod.imports.get(name)
        if target and target.startswith(PROJECT_PACKAGE):
            hit = self.symbols.get(target)
            if hit:
                return [hit[1]]
        return out

    def _mark_callable_expr(
        self, mod: ModuleInfo, expr: ast.AST, worklist: list
    ) -> None:
        """Mark every function a jit-family argument expression could
        denote: lambdas inside it, plus every loaded Name resolved.

        A Name that is being CALLED inside the expression
        (`jax.jit(make_step(...))`) is a maker running at setup time,
        not the traced callable; the traced callable is whatever it
        returns, so the maker's NESTED defs are marked instead of the
        maker's own (host-side) body."""
        called_makers = {
            id(node.func)
            for node in ast.walk(expr)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
        }
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                self._mark(node, worklist)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                for fn in self._resolve_callable_name(
                    mod, expr, node.id
                ):
                    if id(node) in called_makers:
                        for sub in ast.walk(fn):
                            if sub is not fn and isinstance(
                                sub, _FUNC_NODES
                            ):
                                self._mark(sub, worklist)
                    else:
                        self._mark(fn, worklist)

    def _seed(self, worklist: list) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        target = dec.func if isinstance(
                            dec, ast.Call
                        ) else dec
                        d = mod.dotted(target)
                        if d in TRACING_CALLS:
                            self._mark(node, worklist)
                        elif (
                            isinstance(dec, ast.Call)
                            and mod.dotted(dec.func)
                            in ("functools.partial", "partial")
                            and any(
                                mod.dotted(a) in TRACING_CALLS
                                for a in dec.args
                            )
                        ):
                            self._mark(node, worklist)
                if not isinstance(node, ast.Call):
                    continue
                d = mod.dotted(node.func)
                if d in TRACING_CALLS:
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        self._mark_callable_expr(mod, arg, worklist)
                elif d is not None and (
                    d == f"{PROJECT_PACKAGE}.ops.encoding.Dispatch"
                    or d.endswith(".Dispatch")
                    or d == "Dispatch"
                ):
                    self._seed_dispatch(mod, node, worklist)

    def _seed_dispatch(
        self, mod: ModuleInfo, call: ast.Call, worklist: list
    ) -> None:
        exprs: list[ast.AST] = []
        for i in DISPATCH_FN_POSARGS:
            if i < len(call.args):
                exprs.append(call.args[i])
        for kw in call.keywords:
            if kw.arg in DISPATCH_FN_KWARGS:
                exprs.append(kw.value)
        for expr in exprs:
            for node in ast.walk(expr):
                fns: list[ast.AST] = []
                if isinstance(node, ast.Lambda):
                    fns = [node]
                elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ):
                    fns = self._resolve_callable_name(
                        mod, expr, node.id
                    )
                for fn in fns:
                    self.dispatch_fns.add(id(fn))
                    self._mark(fn, worklist)

    def _infer(self) -> None:
        worklist: list = []
        self._seed(worklist)
        # propagate through the call graph: calls made inside a traced
        # scope (by bare name or project-dotted name) mark their defs
        owner: dict[int, ModuleInfo] = {}
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, _FUNC_NODES):
                    owner[id(node)] = mod
        while worklist:
            fn = worklist.pop()
            mod = owner.get(id(fn))
            if mod is None:
                continue
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    if isinstance(node.func, ast.Name):
                        for cand in self._resolve_callable_name(
                            mod, node, node.func.id
                        ):
                            self._mark(cand, worklist)
                    else:
                        d = mod.dotted(node.func)
                        if d and d.startswith(PROJECT_PACKAGE):
                            hit = self.symbols.get(d)
                            if hit:
                                self._mark(hit[1], worklist)
