"""nrlint rule registry + every shipped rule.

Each rule is a function `(mod: ModuleInfo, project: Project) ->
Iterable[Diagnostic]` registered with `@rule(id, severity, summary)`.
Rule ids are kebab-case and stable: they are the suppression currency
(`# nrlint: disable=<id>`), so renaming one invalidates suppressions.

The rules encode PROJECT invariants, not general Python style — each
docstring says which convention it machine-checks and where that
convention is documented.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Callable, Iterable, Iterator

from node_replication_tpu.analysis.astutil import (
    Diagnostic,
    ModuleInfo,
    PROJECT_PACKAGE,
    Project,
)

ERROR = "error"
WARNING = "warning"
INFO = "info"
SEVERITY_ORDER = {INFO: 0, WARNING: 1, ERROR: 2}


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    summary: str
    check: Callable[[ModuleInfo, Project], Iterable[Diagnostic]]


RULES: dict[str, Rule] = {}


def rule(rule_id: str, severity: str, summary: str):
    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id}")
        RULES[rule_id] = Rule(rule_id, severity, summary, fn)
        return fn

    return deco


def _diag(mod: ModuleInfo, node: ast.AST, rule_id: str,
          message: str) -> Diagnostic:
    return Diagnostic(
        path=mod.path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1,
        rule_id=rule_id,
        severity=RULES[rule_id].severity,
        message=message,
    )


def _receiver_tail(expr: ast.AST) -> str | None:
    """Last component of a receiver expression: `self._m_batch` ->
    `_m_batch`, `tracer` -> `tracer`. A ternary receiver reports
    whichever arm matches ((_m_a if c else _m_b).inc())."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.IfExp):
        return _receiver_tail(expr.body) or _receiver_tail(expr.orelse)
    return None


def _base_name(expr: ast.AST) -> str | None:
    """Innermost Name of an attribute/subscript chain."""
    cur = expr
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        cur = cur.value
    return cur.id if isinstance(cur, ast.Name) else None


def _through_at(expr: ast.AST) -> bool:
    """Chain passes through `.at` — jnp's FUNCTIONAL update protocol
    (`x.at[i].add(v)` returns a new array, it mutates nothing)."""
    cur = expr
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        if isinstance(cur, ast.Attribute) and cur.attr == "at":
            return True
        cur = cur.value
    return False


# --------------------------------------------------------------------------
# host-sync-in-jit
# --------------------------------------------------------------------------

_HOST_SYNC_DOTTED = {
    "jax.device_get": "jax.device_get forces a device->host transfer",
    "jax.block_until_ready": "blocking on device values",
    "numpy.asarray": "np.asarray materializes the array on host",
    "numpy.array": "np.array materializes the array on host",
}
_HOST_SYNC_METHODS = {
    "item": ".item() is a device->host scalar readback",
    "block_until_ready": ".block_until_ready() blocks on device work",
}


@rule(
    "host-sync-in-jit", ERROR,
    "device->host sync inside traced (jit/vmap/lax/pallas) code",
)
def host_sync_in_jit(mod: ModuleInfo,
                     project: Project) -> Iterator[Diagnostic]:
    """The hot-path contract (BENCH_NOTES methodology, `utils/fence.py`):
    no host synchronization inside traced code. `.item()`,
    `np.asarray`, `jax.device_get`, `block_until_ready` either fail at
    trace time or silently constant-fold one trace-time value into the
    compiled program. Host readbacks belong in the host-side loops
    (`NodeReplicated._exec_round`), never in functions reachable from
    `jax.jit`/`_build_jits`. An `isinstance(..., jax.core.Tracer)`
    guard marks an explicit eager-only region and is exempt."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if project.traced_context(mod, node) is None:
            continue
        if mod.in_eager_guard(node):
            continue
        d = mod.dotted(node.func)
        if d in _HOST_SYNC_DOTTED:
            yield _diag(
                mod, node, "host-sync-in-jit",
                f"{d}() inside traced code: "
                f"{_HOST_SYNC_DOTTED[d]}; traced values must stay on "
                f"device (use jnp, or hoist to the host loop)",
            )
        elif (
            d is None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _HOST_SYNC_METHODS
        ):
            yield _diag(
                mod, node, "host-sync-in-jit",
                f".{node.func.attr}() inside traced code: "
                f"{_HOST_SYNC_METHODS[node.func.attr]}; hoist to the "
                f"host loop or keep the value symbolic",
            )


# --------------------------------------------------------------------------
# scalar-cast-in-jit
# --------------------------------------------------------------------------


@rule(
    "scalar-cast-in-jit", ERROR,
    "int()/float()/bool() on a non-constant inside traced code",
)
def scalar_cast_in_jit(mod: ModuleInfo,
                       project: Project) -> Iterator[Diagnostic]:
    """`int(x)`/`float(x)`/`bool(x)` on a traced array is a concretization
    error at trace time (`TracerBoolConversionError` and friends) — or,
    on a trace-time-constant, silently bakes one value into the
    compiled program. Use `jnp.int32(...)`-style casts (stay symbolic)
    or hoist the readback to host code. Constant literals are fine."""
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("int", "float", "bool")
            and node.args
        ):
            continue
        if project.traced_context(mod, node) is None:
            continue
        if mod.in_eager_guard(node):
            continue
        a = node.args[0]
        if isinstance(a, ast.Constant) or (
            isinstance(a, ast.UnaryOp)
            and isinstance(a.operand, ast.Constant)
        ):
            continue
        yield _diag(
            mod, node, "scalar-cast-in-jit",
            f"{node.func.id}() on a non-constant inside traced code "
            f"concretizes a tracer (raises or constant-folds); use a "
            f"jnp dtype cast or hoist to the host loop",
        )


# --------------------------------------------------------------------------
# raw-checkify-check
# --------------------------------------------------------------------------


@rule(
    "raw-checkify-check", ERROR,
    "checkify.check() used directly instead of utils.checks.check",
)
def raw_checkify_check(mod: ModuleInfo,
                       project: Project) -> Iterator[Diagnostic]:
    """A live `checkify.check` inside a jit that was never
    `checked()`-functionalized is a trace-time crash (see
    `utils/checks.py`). The project convention is `utils.checks.check`,
    which is armed only inside `debug_checks(True)` so release traces
    are bit-identical to the unchecked program. Direct `checkify.check`
    calls bypass that zero-cost-off contract."""
    if mod.path.replace("\\", "/").endswith("utils/checks.py"):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if mod.dotted(node.func) == "jax.experimental.checkify.check":
            yield _diag(
                mod, node, "raw-checkify-check",
                "raw checkify.check() bypasses the debug_checks() "
                "arming contract; use node_replication_tpu.utils."
                "checks.check (zero cost when disarmed)",
            )


# --------------------------------------------------------------------------
# obs-in-traced
# --------------------------------------------------------------------------

_OBS_FACTORIES = ("get_tracer", "get_registry", "span")
_OBS_METHODS = ("emit", "inc", "observe")
_OBS_RECEIVER_RE = re.compile(r"(^_?m_|_m_|tracer|metric|recorder)",
                              re.IGNORECASE)


@rule(
    "obs-in-traced", ERROR,
    "tracer/metrics call reachable from traced code",
)
def obs_in_traced(mod: ModuleInfo,
                  project: Project) -> Iterator[Diagnostic]:
    """Tracer and metrics calls (`obs.*`) are host-side: inside traced
    code they run once per TRACE (not per step) and their locks/IO have
    no device equivalent — silent no-ops at best, counter lies at
    worst. Instrument the host loops (`_exec_round`, `combine`), never
    functions reachable from jit. Deliberate per-trace counters (the
    `core/log.py` engine-dispatch family) carry justified
    suppressions."""
    if f"{PROJECT_PACKAGE}.obs" in mod.module_name:
        return  # the obs layer itself is host-side by construction
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if project.traced_context(mod, node) is None:
            continue
        if mod.in_eager_guard(node):
            continue
        d = mod.dotted(node.func)
        if d and d.startswith(PROJECT_PACKAGE) and (
            d.rsplit(".", 1)[-1] in _OBS_FACTORIES
        ):
            yield _diag(
                mod, node, "obs-in-traced",
                f"{d.rsplit('.', 1)[-1]}() inside traced code runs "
                f"once per trace, not per step; move it to the host "
                f"loop",
            )
            continue
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _OBS_METHODS
        ):
            continue
        recv = node.func.value
        tail = _receiver_tail(recv)
        if isinstance(recv, ast.Call):
            rd = mod.dotted(recv.func)
            if rd and rd.rsplit(".", 1)[-1] in _OBS_FACTORIES:
                tail = "tracer"
        if tail and _OBS_RECEIVER_RE.search(tail):
            yield _diag(
                mod, node, "obs-in-traced",
                f"{tail}.{node.func.attr}() inside traced code counts "
                f"per trace/compile, not per execution; hoist to the "
                f"host loop (or suppress with the per-trace rationale)",
            )


# --------------------------------------------------------------------------
# mutable-capture-in-dispatch
# --------------------------------------------------------------------------

_MUTATORS = frozenset({
    "append", "extend", "insert", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "add", "clear", "sort", "reverse",
})


@rule(
    "mutable-capture-in-dispatch", ERROR,
    "Python-side mutation / mutable capture in a Dispatch transition",
)
def mutable_capture_in_dispatch(
    mod: ModuleInfo, project: Project
) -> Iterator[Diagnostic]:
    """`Dispatch` transition and window functions are PURE by contract
    (`ops/encoding.py`): `(state, args) -> (state, resp)` with no
    Python-side effects. Mutating a captured object (a closure dict, a
    module global, a mutable default) or the state argument itself
    executes once at trace time and then never again — replicas
    silently diverge from the replayed log. Build new pytrees; keep
    every Python object you mutate local to the call."""
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            continue
        if not project.is_dispatch_fn(fn):
            continue
        name = getattr(fn, "name", "<lambda>")
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {
                a.arg
                for a in (
                    list(fn.args.posonlyargs) + list(fn.args.args)
                    + list(fn.args.kwonlyargs)
                )
            }
            for default in (
                list(fn.args.defaults) + list(fn.args.kw_defaults)
            ):
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    yield _diag(
                        mod, default, "mutable-capture-in-dispatch",
                        f"{name}: mutable default argument is shared "
                        f"across every call of a pure transition",
                    )
        else:
            params = {a.arg for a in fn.args.args}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        # Names REBOUND in the body (plain Name-store targets: fresh
        # locals, loop vars, and `state = dict(state)`-style parameter
        # rebinds to a fresh copy — the pure idiom must not be
        # flagged). Subscript/attribute stores do not rebind and are
        # exactly what the checks below look for.
        assigned: set[str] = set()
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Name) and isinstance(
                    n.ctx, ast.Store
                ):
                    assigned.add(n.id)
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    yield _diag(
                        mod, node, "mutable-capture-in-dispatch",
                        f"{name}: {'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                        f" rebinds state outside the pure transition",
                    )
                elif isinstance(node, (ast.Subscript, ast.Attribute)) \
                        and isinstance(node.ctx, ast.Store):
                    base = _base_name(node.value)
                    if base is None or base in assigned:
                        continue
                    what = (
                        "its state argument" if base in params
                        else f"captured/global '{base}'"
                    )
                    yield _diag(
                        mod, node, "mutable-capture-in-dispatch",
                        f"{name}: mutates {what} in place; transitions "
                        f"must return new pytrees (trace-time-only "
                        f"effect => replica divergence)",
                    )
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                    and not _through_at(node.func.value)
                ):
                    base = _base_name(node.func.value)
                    if base is None or base in assigned:
                        continue
                    target = (
                        "its state argument" if base in params
                        else f"captured/global '{base}'"
                    )
                    yield _diag(
                        mod, node, "mutable-capture-in-dispatch",
                        f"{name}: .{node.func.attr}() mutates "
                        f"{target}; pure transitions must not "
                        f"mutate non-local objects",
                    )


# --------------------------------------------------------------------------
# wall-clock-time
# --------------------------------------------------------------------------


@rule(
    "wall-clock-time", WARNING,
    "time.time() where a monotonic clock is required",
)
def wall_clock_time(mod: ModuleInfo,
                    project: Project) -> Iterator[Diagnostic]:
    """Recorder/watchdog paths order and difference timestamps; wall
    clocks step (NTP, suspend) and make durations negative and stall
    detection lie. Use `time.monotonic()` for ordering and
    `time.perf_counter()` for durations (`obs/recorder.py` module
    docstring). The one legitimate wall-clock use — a correlation
    field next to a monotonic stamp — carries a justified
    suppression."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and (
            mod.dotted(node.func) == "time.time"
        ):
            yield _diag(
                mod, node, "wall-clock-time",
                "time.time() steps with the wall clock; use "
                "time.monotonic()/time.perf_counter() for ordering "
                "and durations (wall-clock correlation fields need a "
                "justified suppression)",
            )


# --------------------------------------------------------------------------
# ring-index-unmasked
# --------------------------------------------------------------------------

_CURSOR_TOKENS = ("tail", "head", "ltail", "ctail", "pos", "start")
_RING_BASES = ("log", "ml")
_RING_ATTRS = ("opcodes", "args")


def _mentions_cursor(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        if ident and any(tok in ident for tok in _CURSOR_TOKENS):
            return True
    return False


def _is_masked(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.BinOp) and isinstance(
            n.op, (ast.BitAnd, ast.Mod)
        ):
            return True
    return False


def _local_aliases(mod: ModuleInfo, node: ast.AST) -> dict[str, ast.AST]:
    """name -> value expr for simple single-target assignments in the
    innermost enclosing function (one-level dataflow for index vars)."""
    for fn in mod.enclosing_functions(node):
        out: dict[str, ast.AST] = {}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for n in ast.walk(stmt):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                ):
                    out[n.targets[0].id] = n.value
        return out
    return {}


@rule(
    "ring-index-unmasked", WARNING,
    "ring-buffer subscript from cursor math without & mask / % capacity",
)
def ring_index_unmasked(mod: ModuleInfo,
                        project: Project) -> Iterator[Diagnostic]:
    """Logical log positions are monotone int64 cursors; the physical
    slot is ALWAYS `pos & (L-1)` (`core/log.py` module docstring,
    `nr/src/log.rs:194-196`). Indexing `log.opcodes`/`log.args` (or a
    `*_ring` array) with unmasked cursor math reads the wrong slot as
    soon as the ring wraps — a bug no test with a small op count can
    see. `jnp.where`/`lax.cond` selection on cursor validity does not
    substitute for masking the slot index itself."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Subscript):
            continue
        target = node.value
        if isinstance(target, ast.Attribute) and target.attr == "at":
            target = target.value  # x.at[idx] scatter/gather form
        is_ring = False
        if isinstance(target, ast.Attribute) and (
            target.attr in _RING_ATTRS
        ):
            base = _base_name(target.value)
            if base in _RING_BASES or (
                base is not None and base.endswith("_ring")
            ):
                is_ring = True
        elif isinstance(target, ast.Name) and (
            target.id.endswith("_ring")
        ):
            is_ring = True
        if not is_ring:
            continue
        idx = node.slice
        aliases = _local_aliases(mod, node)
        exprs: list[ast.AST] = [idx]
        for n in ast.walk(idx):
            if isinstance(n, ast.Name) and n.id in aliases:
                exprs.append(aliases[n.id])
        if any(_mentions_cursor(e) for e in exprs) and not any(
            _is_masked(e) for e in exprs
        ):
            yield _diag(
                mod, node, "ring-index-unmasked",
                "ring subscript derived from cursor math without "
                "`& mask` / `% capacity`: wrong slot after the ring "
                "wraps (mask the physical index, cf. core/log.py)",
            )


# --------------------------------------------------------------------------
# lock-discipline
# --------------------------------------------------------------------------


def _is_locked_method(method: ast.AST) -> bool:
    """Decorated with `@_locked` (or any `*locked*` wrapper): the whole
    method body is one `with self._lock` region (`core/replica._locked`)."""
    for dec in getattr(method, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = None
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute):
            name = target.attr
        if name and "locked" in name:
            return True
    return False


def _lock_withs(method: ast.AST, lock_attrs: set[str]) -> list[ast.With]:
    out = []
    for node in ast.walk(method):
        if isinstance(node, ast.With):
            for item in node.items:
                ce = item.context_expr
                if (
                    isinstance(ce, ast.Attribute)
                    and ce.attr in lock_attrs
                    and isinstance(ce.value, ast.Name)
                    and ce.value.id == "self"
                ):
                    out.append(node)
    return out


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _is_effective_store(mod: ModuleInfo, node: ast.Attribute) -> bool:
    if isinstance(node.ctx, ast.Store):
        return True
    parent = mod.parent(node)
    # self.x[i] = v  /  self.x[i] += v: the Subscript is the store
    # target, the Attribute itself is a Load
    return (
        isinstance(parent, ast.Subscript)
        and parent.value is node
        and isinstance(parent.ctx, ast.Store)
    )


@rule(
    "lock-discipline", ERROR,
    "guarded shared attribute accessed outside the instance lock",
)
def lock_discipline(mod: ModuleInfo,
                    project: Project) -> Iterator[Diagnostic]:
    """Lockset inference over `with self._lock` regions: any `self.X`
    WRITTEN under a class's lock somewhere is a guarded attribute; a
    write to it outside the lock (in any method but `__init__`), or a
    read outside the lock in a method that also takes the lock
    (check-then-act race), is a combiner-discipline violation. This is
    the threaded combiner/reader contract of `core/replica.py` and
    `core/cnr.py` (one combiner at a time — the flat-combining lock),
    and the same pass covers `obs/`. Intentional lock-free fast paths
    (e.g. a racy-but-benign enabled check) carry justified
    suppressions."""
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        lock_attrs: set[str] = set()
        for m in methods:
            for node in ast.walk(m):
                attr = _self_attr(node)
                if (
                    attr
                    and attr.endswith("_lock")
                    and isinstance(node.ctx, ast.Store)
                ):
                    lock_attrs.add(attr)
        if not lock_attrs:
            continue
        guarded: set[str] = set()
        for m in methods:
            regions = (
                [m] if _is_locked_method(m)
                else _lock_withs(m, lock_attrs)
            )
            for region in regions:
                for node in ast.walk(region):
                    attr = _self_attr(node)
                    if attr and attr not in lock_attrs and (
                        _is_effective_store(mod, node)
                    ):
                        guarded.add(attr)
        if not guarded:
            continue
        for m in methods:
            if m.name == "__init__" or _is_locked_method(m):
                continue
            regions = _lock_withs(m, lock_attrs)
            region_ids = {id(r) for r in regions}
            for node in ast.walk(m):
                attr = _self_attr(node)
                if attr not in guarded:
                    continue
                inside = False
                cur = mod.parent(node)
                while cur is not None and cur is not m:
                    if id(cur) in region_ids:
                        inside = True
                        break
                    cur = mod.parent(cur)
                if inside:
                    continue
                if _is_effective_store(mod, node):
                    yield _diag(
                        mod, node, "lock-discipline",
                        f"{cls.name}.{m.name}: self.{attr} is written "
                        f"under the lock elsewhere but written here "
                        f"without it",
                    )
                elif regions:
                    yield _diag(
                        mod, node, "lock-discipline",
                        f"{cls.name}.{m.name}: self.{attr} read "
                        f"outside the lock in a method that takes it "
                        f"(check-then-act race)",
                    )


# --------------------------------------------------------------------------
# blocking-in-handler
# --------------------------------------------------------------------------

_HANDLER_KWARGS = ("callback", "on_done", "on_response")
_HANDLER_REGISTRARS = ("add_done_callback",)
# kwarg-based registration counts only on serve-shaped calls
# (frontend.submit/call): an unscoped `callback=` match would drag
# third-party callback APIs (scipy's `minimize(..., callback=)`,
# timers, ...) under an ERROR-severity serve rule
_HANDLER_KWARG_METHODS = ("submit", "call")
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep() stalls the batch worker",
    "jax.block_until_ready": "host sync stalls the batch worker",
    "jax.device_get": "device->host transfer stalls the batch worker",
    "numpy.asarray": "host materialization stalls the batch worker",
    "numpy.array": "host materialization stalls the batch worker",
}
_BLOCKING_METHODS = {
    "block_until_ready": "host sync stalls the batch worker",
    "item": "device->host scalar readback stalls the batch worker",
    "result": "waiting on a future from the worker thread that must "
              "resolve it is a deadlock",
    "wait": "a blocking wait stalls the batch worker",
    "sleep": "sleeping stalls the batch worker",
}


def _handler_functions(mod: ModuleInfo) -> dict[str, ast.AST]:
    """name/id -> function node for every serve handler in the module:
    arguments to `<x>.add_done_callback(...)` and values of
    `callback=`/`on_done=`/`on_response=` kwargs, resolved to same-
    module defs (or inline lambdas) — `self._on_done`-style bound
    methods resolve by their method name — CLOSED transitively over
    same-module calls (plain `helper()` and `self._helper()` alike):
    a handler that delegates its sleep to a helper is still a firing
    handler."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Lambda
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    defs[tgt.id] = node.value
    roots: list[ast.AST] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _HANDLER_REGISTRARS
            and node.args
        ):
            roots.append(node.args[0])
        fname = None
        if isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        elif isinstance(node.func, ast.Name):
            fname = node.func.id
        if fname in _HANDLER_KWARG_METHODS:
            for kw in node.keywords:
                if kw.arg in _HANDLER_KWARGS:
                    roots.append(kw.value)
    handlers: dict[str, ast.AST] = {}
    queue: list[tuple[str, ast.AST]] = []
    for i, r in enumerate(roots):
        if isinstance(r, ast.Lambda):
            queue.append((f"<lambda#{i}>", r))
        elif isinstance(r, ast.Name) and r.id in defs:
            queue.append((r.id, defs[r.id]))
        elif isinstance(r, ast.Attribute) and r.attr in defs:
            # bound method: frontend.submit(cb=self._on_done) — match
            # by method name (the linter's usual name-based precision)
            queue.append((r.attr, defs[r.attr]))
    while queue:
        name, fn = queue.pop()
        if name in handlers:
            continue
        handlers[name] = fn
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for n in ast.walk(stmt):
                if not isinstance(n, ast.Call):
                    continue
                callee = None
                if isinstance(n.func, ast.Name):
                    callee = n.func.id
                elif (
                    isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in ("self", "cls")
                ):
                    callee = n.func.attr
                if callee is not None and callee in defs:
                    queue.append((callee, defs[callee]))
    return handlers


def _first_own_param(fn: ast.AST) -> str | None:
    """The handler's own-future parameter (first arg, `self`/`cls`
    skipped): `.result()` on IT is non-blocking by construction —
    callbacks run only after resolution — and is exempt."""
    args = fn.args
    params = [a.arg for a in
              (list(getattr(args, "posonlyargs", [])) + list(args.args))]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    return params[0] if params else None


@rule(
    "blocking-in-handler", ERROR,
    "blocking call (sleep/host-sync/future-wait) in a serve handler",
)
def blocking_in_handler(mod: ModuleInfo,
                        project: Project) -> Iterator[Diagnostic]:
    """Serve done-callbacks run ON the batch worker thread that
    resolves the future (`serve/future.py`): a handler that sleeps,
    host-syncs, or waits on ANOTHER future stalls — or deadlocks —
    the combiner loop for EVERY queued request on that replica.
    Handlers must only hand work off (append to a queue, set an
    event, update a counter). Covers functions registered via
    `add_done_callback(fn)` or passed as `callback=`/`on_done=`/
    `on_response=` kwargs of serve-shaped calls (`submit`/`call`),
    including same-module helpers they call. `.result()` on the
    handler's OWN future argument is the sanctioned read-the-response
    idiom (already resolved, returns instantly) and does not fire."""
    for name, fn in sorted(_handler_functions(mod).items()):
        label = getattr(fn, "name", name)
        own = _first_own_param(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "result"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == own
                ):
                    continue  # own-future read: non-blocking
                d = mod.dotted(node.func)
                if d in _BLOCKING_DOTTED:
                    yield _diag(
                        mod, node, "blocking-in-handler",
                        f"{label}: {d}() in a serve handler body — "
                        f"{_BLOCKING_DOTTED[d]}; hand off to a queue "
                        f"instead",
                    )
                elif (
                    d is None
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BLOCKING_METHODS
                ):
                    yield _diag(
                        mod, node, "blocking-in-handler",
                        f"{label}: .{node.func.attr}() in a serve "
                        f"handler body — "
                        f"{_BLOCKING_METHODS[node.func.attr]}; hand "
                        f"off to a queue instead",
                    )


# --------------------------------------------------------------------------
# swallowed-worker-exception
# --------------------------------------------------------------------------

# Sinks that legitimately RECORD a worker exception instead of eating
# it: future/queue delivery methods and the fault/ health-report API.
_EXC_SINK_ATTRS = frozenset({
    "_reject", "_resolve", "set_exception", "set_result",
    "put", "put_nowait", "append", "appendleft", "add",
    "enqueue_resps", "record",
})
_EXC_HEALTH_ATTRS = frozenset({
    "report_worker_exception", "report_exception", "report_stall",
    "report_failure", "quarantine", "transition",
    "_fail_replica", "fail_replica", "on_replica_failed",
    # repl/ worker threads (shipper ship loop, follower apply loop,
    # promotion watch): `_record_failure` is their sanctioned report
    # path — it stores the error for barrier/read callers AND calls
    # the health API, so a handler routing through it has surfaced
    # the failure
    "_record_failure", "record_failure",
})
_BROAD_EXC_NAMES = frozenset({"Exception", "BaseException"})


def _thread_target_functions(mod: ModuleInfo,
                             project: Project) -> dict[str, ast.AST]:
    """name -> function node for every thread-target in the module —
    `target=` arguments of `threading.Thread(...)` calls (plain names
    resolved through enclosing scopes and aliases, `self._worker_loop`
    bound methods by method name, inline lambdas) — closed transitively
    over same-module calls (`helper()` / `self._helper()`): a worker
    loop that delegates its batch to a helper still runs that helper on
    the worker thread."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    roots: list[ast.AST] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = mod.dotted(node.func)
        is_thread = d == "threading.Thread" or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "Thread"
        )
        if not is_thread:
            continue
        for kw in node.keywords:
            if kw.arg == "target":
                roots.append(kw.value)
    targets: dict[str, ast.AST] = {}
    queue: list[tuple[str, ast.AST]] = []
    for i, r in enumerate(roots):
        if isinstance(r, ast.Lambda):
            queue.append((f"<lambda#{i}>", r))
        elif isinstance(r, ast.Name):
            for fn in project._resolve_callable_name(mod, r, r.id):
                queue.append((getattr(fn, "name", r.id), fn))
        elif isinstance(r, ast.Attribute) and r.attr in defs:
            queue.append((r.attr, defs[r.attr]))
    while queue:
        name, fn = queue.pop()
        if name in targets:
            continue
        targets[name] = fn
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for n in ast.walk(stmt):
                if not isinstance(n, ast.Call):
                    continue
                callee = None
                if isinstance(n.func, ast.Name):
                    callee = n.func.id
                elif (
                    isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in ("self", "cls")
                ):
                    callee = n.func.attr
                if callee is not None and callee in defs:
                    queue.append((callee, defs[callee]))
    return targets


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    """`except:`, `except Exception:`, `except BaseException:` (alone
    or in a tuple)."""
    t = handler.type
    if t is None:
        return True
    exprs = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in exprs:
        name = None
        if isinstance(e, ast.Name):
            name = e.id
        elif isinstance(e, ast.Attribute):
            name = e.attr
        if name in _BROAD_EXC_NAMES:
            return True
    return False


def _handler_records_failure(handler: ast.ExceptHandler) -> bool:
    """The handler body re-raises, records to a future/queue sink, or
    calls a health-report API — any of which surfaces the failure."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            attr = None
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
            elif isinstance(node.func, ast.Name):
                attr = node.func.id
            if attr in _EXC_SINK_ATTRS or attr in _EXC_HEALTH_ATTRS:
                return True
    return False


@rule(
    "swallowed-worker-exception", ERROR,
    "broad except in a thread-target/worker-loop swallows the failure",
)
def swallowed_worker_exception(mod: ModuleInfo,
                               project: Project) -> Iterator[Diagnostic]:
    """A `threading.Thread` target (or a helper it calls on the worker
    thread) that catches `except:` / `except Exception:` and neither
    re-raises, records to a future/sink (`_reject`, `set_exception`,
    `put`, ...), nor reports to the health API
    (`report_worker_exception`, `_fail_replica`, ...) eats the replica
    failure silently — the exact pattern that turns a dead serve
    worker into an unexplained hang (`serve/frontend.py` worker
    contract; `fault/health.py` is the sanctioned report path).
    Logging alone does not count: a log line resolves no future and
    quarantines no replica."""
    for name, fn in sorted(_thread_target_functions(mod,
                                                    project).items()):
        label = getattr(fn, "name", name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(node):
                continue
            if _handler_records_failure(node):
                continue
            yield _diag(
                mod, node, "swallowed-worker-exception",
                f"{label}: broad except in a worker-thread function "
                f"neither re-raises, records to a future/sink, nor "
                f"reports replica health — the failure is silently "
                f"swallowed; reject the futures or call a "
                f"health-report API",
            )


# --------------------------------------------------------------------------
# time-in-traced
# --------------------------------------------------------------------------


@rule(
    "time-in-traced", ERROR,
    "clock read inside traced code (executes once, at trace time)",
)
def time_in_traced(mod: ModuleInfo,
                   project: Project) -> Iterator[Diagnostic]:
    """A `time.*()` read inside traced code runs exactly once — while
    tracing — and its value is frozen into the compiled program; every
    subsequent step reuses the stale stamp. Timing belongs in the host
    loop, around (and fencing) the device call (`obs/recorder.py`
    spans)."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = mod.dotted(node.func)
        if not d or not d.startswith("time."):
            continue
        if project.traced_context(mod, node) is None:
            continue
        if mod.in_eager_guard(node):
            continue
        yield _diag(
            mod, node, "time-in-traced",
            f"{d}() inside traced code is evaluated once at trace "
            f"time and frozen into the program; time on the host side "
            f"of the dispatch",
        )


# --------------------------------------------------------------------------
# non-durable-publish
# --------------------------------------------------------------------------

_PUBLISH_FNS = ("os.replace", "os.rename")
_SAVEZ_FNS = ("numpy.savez", "numpy.savez_compressed")


def _binary_write_mode(call: ast.Call) -> bool:
    """`open(...)` whose mode constant creates/truncates a BINARY file
    (`wb`, `xb`, `w+b`, ...) — the write half of a publish sequence."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return (
        isinstance(mode, str)
        and "b" in mode
        and any(c in mode for c in "wx")
    )


def _walk_scope(node: ast.AST, _root: bool = True):
    """Walk a scope WITHOUT descending into nested function scopes
    (each function is analyzed as its own publish sequence)."""
    if not _root and isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    ):
        return
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _walk_scope(child, _root=False)


@rule(
    "non-durable-publish", WARNING,
    "atomic-rename publish of a written file with no fsync between",
)
def non_durable_publish(mod: ModuleInfo,
                        project: Project) -> Iterator[Diagnostic]:
    """The durable-publish convention (`core/checkpoint.py:
    save_snapshot`, `durable/wal.py`): a file published by atomic
    rename must be fsynced FIRST — `os.replace` orders the directory
    entry, not the data blocks, so a crash between rename and
    writeback publishes a name that points at a torn or empty file
    (exactly the published-but-empty snapshot failure recovery cannot
    distinguish from corruption). Flags, per function scope:

    - a binary-create `open(..., "wb"/"xb"/...)` followed by
      `os.replace`/`os.rename` with no `os.fsync` between them;
    - a bare `np.savez`/`np.savez_compressed` straight to a path
      (anything but a handle bound from `open()` in the same scope):
      writing the final name directly has no atomic publish at all —
      write to an fsynced tmp file and rename it in.

    Text-mode rewrites (CSV upgrades) and append-only handles are out
    of scope: they are not publish points for recovery-critical state.
    """
    scopes = [mod.tree] + [
        n for n in ast.walk(mod.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        open_lines: list[int] = []
        open_bound: set[str] = set()
        fsync_lines: list[int] = []
        publishes: list[ast.Call] = []
        savez_calls: list[tuple[ast.Call, str]] = []
        for node in _walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id == "open"
                    and _binary_write_mode(node)):
                open_lines.append(node.lineno)
                parent = mod.parent(node)
                if (isinstance(parent, ast.withitem)
                        and isinstance(parent.optional_vars,
                                       ast.Name)):
                    open_bound.add(parent.optional_vars.id)
                elif (isinstance(parent, ast.Assign)
                      and len(parent.targets) == 1
                      and isinstance(parent.targets[0], ast.Name)):
                    open_bound.add(parent.targets[0].id)
            dotted = mod.dotted(fn)
            if dotted == "os.fsync":
                fsync_lines.append(node.lineno)
            elif dotted in _PUBLISH_FNS:
                publishes.append(node)
            elif dotted in _SAVEZ_FNS:
                savez_calls.append((node, dotted.split(".")[-1]))
        for node in publishes:
            prior = [lo for lo in open_lines if lo < node.lineno]
            if not prior:
                continue
            lo = max(prior)
            if any(lo <= lf < node.lineno for lf in fsync_lines):
                continue
            yield _diag(
                mod, node, "non-durable-publish",
                "os.replace/os.rename publishes a file written at "
                f"line {lo} with no os.fsync between write and "
                "rename; a crash can publish a torn/empty file — "
                "fsync the handle before renaming (and the directory "
                "after, for the entry itself)",
            )
        for node, name in savez_calls:
            first = node.args[0] if node.args else None
            if first is None or (
                isinstance(first, ast.Name) and first.id in open_bound
            ):
                continue
            yield _diag(
                mod, node, "non-durable-publish",
                f"np.{name} writes directly to its final path (no "
                "atomic publish, no fsync): write into an open tmp-"
                "file handle, fsync it, then os.replace into place "
                "(core/checkpoint.py:save_snapshot is the template)",
            )


# --------------------------------------------------------------------------
# raw-clock-in-subsystem
# --------------------------------------------------------------------------

#: package directories whose timed waits must route through the
#: injectable clock (the simulation contract, `utils/clock.py`)
_CLOCKED_SUBSYSTEMS = ("serve", "fault", "repl", "durable", "shard")

_RAW_CLOCK_CALLS = {
    "time.monotonic": "time.monotonic() reads the OS clock directly",
    "time.sleep": "time.sleep() blocks on the OS clock directly",
    # the old blanket perf_counter exemption is narrowed to ops/bench
    # paths (outside this rule's scope anyway): inside a clock-routed
    # subsystem even a pure duration probe must follow the injected
    # clock, or a simulated run's durations (batch times, repair
    # latencies, fsync spans) are measured against the WRONG clock —
    # the sim-flavor bug this rule exists to prevent
    "time.perf_counter": "time.perf_counter() measures against the "
                         "OS clock directly",
}

#: receiver tails that denote a threading.Condition in this codebase
#: (`self._cond`, `self._lock`-as-Condition, a local `cond`); `clock`
#: receivers are the sanctioned routing and never match
_CONDITION_TOKENS = ("cond", "lock")


def _clocked_subsystem(path: str) -> str | None:
    parts = re.split(r"[\\/]+", path)
    for name in _CLOCKED_SUBSYSTEMS:
        if name in parts[:-1]:
            return name
    return None


@rule(
    "raw-clock-in-subsystem", WARNING,
    "direct time.monotonic/time.sleep/Condition.wait in a "
    "clock-routed subsystem (serve/, fault/, repl/, durable/, "
    "shard/)",
)
def raw_clock_in_subsystem(mod: ModuleInfo,
                           project: Project) -> Iterator[Diagnostic]:
    """The simulation contract (`utils/clock.py`, `sim/`): every timed
    wait in serve/, fault/, repl/, and durable/ routes through the
    process-global injectable clock — `get_clock().now()/.sleep()/
    .wait(cond, timeout)` — so `SimClock` can substitute virtual time
    and a seeded schedule fully determines which timeouts fire. A
    direct `time.monotonic()`, `time.sleep()`, or `Condition.wait()`
    in those packages is invisible to the simulator: the component
    would block on (or stamp with) real time mid-simulation, and the
    deterministic-replay property dies silently. `time.perf_counter()`
    is flagged too — a duration probe inside a clocked subsystem
    measures simulated work against the wrong clock (its exemption is
    narrowed to ops/bench paths, which sit outside this rule's path
    scope anyway). `Thread.join` and `Event.wait` stay exempt
    (real-thread barriers). The raw clock legitimately lives in
    `utils/clock.py` itself and in obs/ (whose wall/mono stamps are
    correlation fields) — both outside this rule's path scope."""
    sub = _clocked_subsystem(mod.path)
    if sub is None:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = mod.dotted(node.func)
        if dotted in _RAW_CLOCK_CALLS:
            yield _diag(
                mod, node, "raw-clock-in-subsystem",
                f"{_RAW_CLOCK_CALLS[dotted]} inside {sub}/; route "
                "through the injectable clock "
                "(utils/clock.py:get_clock) so simulated runs stay "
                "deterministic",
            )
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "wait"):
            continue
        tail = _receiver_tail(fn.value)
        if tail is None:
            continue
        low = tail.lower()
        if any(tok in low for tok in _CONDITION_TOKENS) and (
            "clock" not in low
        ):
            yield _diag(
                mod, node, "raw-clock-in-subsystem",
                f"direct Condition.wait on `{tail}` inside {sub}/; "
                "use get_clock().wait(cond, timeout) so SimClock can "
                "wake the waiter when virtual time passes its "
                "deadline",
            )


# --------------------------------------------------------------------------
# unbounded-growth-in-subsystem
# --------------------------------------------------------------------------

#: package directories whose worker loops must bound every accumulator
#: (the overload-plane memory contract: per-replica memory is
#: O(queue_depth + batch), never load-proportional)
_GROWTH_SUBSYSTEMS = ("serve", "repl")

_APPEND_METHODS = ("append", "appendleft", "extend", "extendleft")
_DRAIN_METHODS = ("pop", "popleft", "clear", "popitem")

#: identifier fragments that mark a bound/watermark comparison
_BOUND_TOKENS = ("depth", "maxlen", "watermark", "bound", "limit",
                 "capacity", "max_")


def _unbounded_init_attrs(cls_node: ast.ClassDef) -> set[str]:
    """`self.X` attributes a class's `__init__` binds to an unbounded
    container: `[]`, `list()`, or `deque()` without `maxlen`."""
    attrs: set[str] = set()
    for item in cls_node.body:
        if not (isinstance(item, ast.FunctionDef)
                and item.name == "__init__"):
            continue
        for node in ast.walk(item):
            if not isinstance(node, ast.Assign):
                continue
            names = [a for a in map(_self_attr, node.targets)
                     if a is not None]
            if not names:
                continue
            v = node.value
            unbounded = isinstance(v, ast.List) and not v.elts
            if isinstance(v, ast.Call):
                fn = v.func
                callee = (
                    fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None
                )
                if callee in ("deque", "list") and not any(
                        kw.arg == "maxlen" for kw in v.keywords):
                    unbounded = True
            if unbounded:
                attrs.update(names)
    return attrs


def _drained_attrs(cls_node: ast.ClassDef) -> set[str]:
    """Attributes the class pops/clears SOMEWHERE — a drained
    container is a queue, not an accumulator."""
    out: set[str] = set()
    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in _DRAIN_METHODS):
            continue
        attr = _self_attr(fn.value)
        if attr is not None:
            out.add(attr)
    return out


def _has_bound_check(fn: ast.AST) -> bool:
    """A comparison over `len(...)` or over a bound/watermark-named
    value anywhere in the function — the shape every honest
    depth/watermark check takes."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "len"):
                return True
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None and any(
                    tok in name.lower() for tok in _BOUND_TOKENS):
                return True
    return False


@rule(
    "unbounded-growth-in-subsystem", WARNING,
    "worker-loop accumulator in serve//repl/ grows without a bound "
    "or watermark check",
)
def unbounded_growth_in_subsystem(
        mod: ModuleInfo, project: Project) -> Iterator[Diagnostic]:
    """The overload-plane memory contract (`serve/overload.py`):
    per-replica memory is O(queue_depth + batch), never
    load-proportional — so every container a serve/ or repl/ WORKER
    LOOP appends to must be bounded. Fires on `self.X.append/extend`
    inside a thread-target function (or a helper it calls on the
    worker thread, the `swallowed-worker-exception` closure) when `X`
    was initialized as a bare `[]`/`list()`/`deque()` (no `maxlen`)
    and neither (a) the enclosing function compares a `len(...)` or a
    bound/watermark-named value (an admission/depth check), nor (b)
    the class drains the container somewhere (`pop`/`popleft`/
    `clear` — a queue, not an accumulator). An unbounded worker-side
    accumulator is exactly how apply lag, ship backlog, or a retry
    queue eats the heap under sustained overload — bound it, or wire
    it to a watermark the admission controller can see."""
    parts = re.split(r"[\\/]+", mod.path)
    if not any(s in parts[:-1] for s in _GROWTH_SUBSYSTEMS):
        return
    unbounded: set[str] = set()
    drained: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            unbounded |= _unbounded_init_attrs(node)
            drained |= _drained_attrs(node)
    growers = unbounded - drained
    if not growers:
        return
    for name, fn in sorted(_thread_target_functions(mod,
                                                    project).items()):
        if _has_bound_check(fn):
            continue
        label = getattr(fn, "name", name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            call_fn = node.func
            if not (isinstance(call_fn, ast.Attribute)
                    and call_fn.attr in _APPEND_METHODS):
                continue
            attr = _self_attr(call_fn.value)
            if attr is None or attr not in growers:
                continue
            yield _diag(
                mod, node, "unbounded-growth-in-subsystem",
                f"{label}: self.{attr}.{call_fn.attr}() on the worker "
                f"thread with no bound or watermark check and no "
                f"drain path — under sustained overload this "
                f"accumulator grows with load; cap it (deque(maxlen=)"
                f"), drain it, or gate the append on a depth/"
                f"watermark the admission controller enforces",
            )


# --------------------------------------------------------------------------
# host-transfer-in-sharded-path
# --------------------------------------------------------------------------

#: package directories whose exec paths run over mesh-sharded state
#: (the replica axis lives across devices there — parallel/mesh.py)
_SHARDED_PATH_DIRS = ("core", "parallel")

#: function names that ARE the exec path: replay rounds, catch-up
#: loops, fused steps, and the explicit-collective programs
_SHARDED_FN_RE = re.compile(r"(exec|catchup|replay|shmap|step)")

#: identifier fragments that denote mesh-sharded state leaves: replica
#: states and the log's ring arrays. Cursor readbacks (ltails/tail/
#: head/ctail — a few hundred bytes) are the sanctioned host syncs of
#: the exec loop and never match.
_SHARDED_STATE_TOKENS = ("states", "opcodes")

_TRANSFER_DOTTED = {
    "numpy.asarray": "np.asarray gathers the sharded array to host",
    "numpy.array": "np.array gathers the sharded array to host",
    "jax.device_get": "jax.device_get gathers the sharded array "
                      "to host",
}


def _mentions_sharded_state(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and any(tok in name for tok in _SHARDED_STATE_TOKENS):
            return True
    return False


@rule(
    "host-transfer-in-sharded-path", WARNING,
    "np.asarray/.item()/device_get on mesh-sharded state in a "
    "core//parallel/ exec path",
)
def host_transfer_in_sharded_path(
        mod: ModuleInfo, project: Project) -> Iterator[Diagnostic]:
    """The mesh-fleet placement contract (`parallel/mesh.py:place`):
    replica states and the log's ring arrays live sharded across the
    mesh's devices, so a host materialization of them inside an exec
    path (`_exec_round`, catch-up loops, the shard_map/ring programs,
    the fused steps) is an ALL-GATHER of the whole fleet through the
    host — O(R x state) bytes over PCIe/ICI per round, exactly the
    transfer the sharding exists to avoid, and silently correct so no
    test catches it. Scoped to core/ and parallel/ functions whose
    name marks them as exec-path (exec/catchup/replay/shmap/step);
    flags `np.asarray`/`np.array`/`jax.device_get` calls and `.item()`
    readbacks whose operand mentions a sharded-state leaf (`states`,
    `opcodes`). Cursor readbacks (`ltails`/`tail`/`head`/`ctail`) are
    the exec loop's sanctioned host syncs and stay clean; deliberate
    host bridges (`ring_slice`, checkpointing, `verify`) live outside
    the scoped function names."""
    parts = re.split(r"[\\/]+", mod.path)
    if not any(d in parts[:-1] for d in _SHARDED_PATH_DIRS):
        return
    # collect the scoped functions (by name), then walk each body
    for fn_node in ast.walk(mod.tree):
        if not isinstance(fn_node,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _SHARDED_FN_RE.search(fn_node.name):
            continue
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            d = mod.dotted(node.func)
            if d in _TRANSFER_DOTTED:
                if not (node.args
                        and _mentions_sharded_state(node.args[0])):
                    continue
                yield _diag(
                    mod, node, "host-transfer-in-sharded-path",
                    f"{fn_node.name}: {_TRANSFER_DOTTED[d]} inside a "
                    f"sharded exec path — on a mesh fleet this "
                    f"gathers every device's shard through the host "
                    f"each round; keep the state on device (cursor "
                    f"readbacks are fine)",
                )
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item"
                  and _mentions_sharded_state(node.func.value)):
                yield _diag(
                    mod, node, "host-transfer-in-sharded-path",
                    f"{fn_node.name}: .item() on mesh-sharded state "
                    f"inside a sharded exec path — a cross-device "
                    f"readback per call; keep the value symbolic or "
                    f"read back cursors instead",
                )


# --------------------------------------------------------------------------
# aliased-pallas-planes
# --------------------------------------------------------------------------


def _is_blocked_spec(mod: ModuleInfo, node: ast.AST,
                     aliases: dict[str, ast.AST]) -> bool:
    """A `pl.BlockSpec(...)` whose first positional argument is a block
    shape (i.e. a BLOCKED, grid-pipelined plane). Specs built with only
    `memory_space=` (SMEM scalars, ANY/HBM refs moved by explicit
    in-kernel DMA) are un-blocked and exempt. Names resolve one level
    through the enclosing function's assignments; anything
    unresolvable counts as not-blocked (no false positives)."""
    if isinstance(node, ast.Name) and node.id in aliases:
        node = aliases[node.id]
    if not isinstance(node, ast.Call):
        return False
    callee = node.func
    name = (
        callee.attr if isinstance(callee, ast.Attribute)
        else callee.id if isinstance(callee, ast.Name) else None
    )
    if name != "BlockSpec":
        return False
    if not node.args:
        return False
    kw = {k.arg for k in node.keywords if k.arg}
    if "memory_space" in kw:
        # blocked VMEM planes never carry a memory_space kwarg in this
        # codebase; SMEM/ANY shaped specs (the shared-resp pattern) do
        return False
    return True


def _grid_is_single(node: ast.AST | None,
                    aliases: dict[str, ast.AST]) -> bool:
    """grid=(1,) / grid=1 / absent: a single grid step has no pipeline
    to race, which is exactly the plan kernels' sanctioned in-place
    aliasing regime (ops/pallas_vspace.py)."""
    if node is None:
        return True
    if isinstance(node, ast.Name) and node.id in aliases:
        node = aliases[node.id]
    if isinstance(node, ast.Constant):
        return node.value == 1
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(
            isinstance(e, ast.Constant) and e.value == 1
            for e in node.elts
        )
    return False


def _closure_aliases(mod: ModuleInfo,
                     node: ast.AST) -> dict[str, ast.AST]:
    """`_local_aliases` extended through the WHOLE lexical closure:
    name -> value expr from every enclosing function, innermost scope
    shadowing outermost. The shard_map-wrapped kernel builders need
    this — the `pl.pallas_call` lives in a nested shard-local function
    while its `grid`/`in_specs`/`input_output_aliases` are bound in
    the enclosing builder, so one-level (innermost-only) resolution
    sees nothing and the rule would stay silent on exactly the
    mesh-wrapped variant of the race. Within one function the LAST
    assignment in source order wins (`_local_aliases` parity — a
    rebound `grid = (1,)` → `grid = (R // tile,)` must resolve to the
    multi-step value or the ERROR rule goes silent on a real race),
    and nested function bodies are skipped when scanning an enclosing
    scope: a sibling inner def's bindings are its own, not the
    closure's."""
    def scope_assigns(fn) -> dict[str, ast.AST]:
        local: dict[str, ast.AST] = {}

        def visit(children) -> None:
            for n in children:
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # inner scopes bind their own names
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                ):
                    local[n.targets[0].id] = n.value
                visit(ast.iter_child_nodes(n))

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        visit(body)
        return local

    out: dict[str, ast.AST] = {}
    for fn in mod.enclosing_functions(node):
        for k, v in scope_assigns(fn).items():
            out.setdefault(k, v)  # innermost scope wins
    return out


@rule(
    "aliased-pallas-planes", ERROR,
    "input_output_aliases on a blocked state plane of a multi-step-grid "
    "pallas_call",
)
def aliased_pallas_planes(mod: ModuleInfo,
                          project: Project) -> Iterator[Diagnostic]:
    """The r5 silent-corruption pattern, machine-checked
    (`ops/pallas_chunk.py`): a `pl.pallas_call` whose BLOCKED state
    planes are aliased in->out corrupts state once the grid pipelines
    deep enough — Mosaic's block prefetch for a later grid step races
    the writeback of an earlier one, and the misread is silent (always
    at >= 64 grid steps on v5e, occasionally at 32, never in interpret
    mode, so no CPU test catches it). The sanctioned shapes stay
    clean: separate in/out planes with an in-kernel copy (the span
    kernels), aliasing under `grid=(1,)` (the plan kernels — one grid
    step, no pipeline), and aliasing of UN-BLOCKED refs
    (`memory_space=ANY/HBM` moved by explicit DMA — the fused round's
    ring planes, `ops/pallas_ring.py`). Covers the shard_map-wrapped
    variant too: names resolve through the whole lexical closure
    (`_closure_aliases`) and the alias map may itself be bound to a
    name, so a builder that constructs the call inside a nested
    shard-local function — the mesh-fused idiom — is checked exactly
    like a flat one. Scoped to ops/, where every kernel lives."""
    parts = re.split(r"[\\/]+", mod.path)
    if "ops" not in parts[:-1]:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        name = (
            callee.attr if isinstance(callee, ast.Attribute)
            else callee.id if isinstance(callee, ast.Name) else None
        )
        if name != "pallas_call":
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        aliases = _closure_aliases(mod, node)
        al = kw.get("input_output_aliases")
        if isinstance(al, ast.Name) and al.id in aliases:
            al = aliases[al.id]
        if not isinstance(al, ast.Dict):
            continue
        if _grid_is_single(kw.get("grid"), aliases):
            continue
        in_specs = kw.get("in_specs")
        if isinstance(in_specs, ast.Name) and in_specs.id in aliases:
            in_specs = aliases[in_specs.id]
        if not isinstance(in_specs, (ast.List, ast.Tuple)):
            continue  # unresolvable spec list: stay silent
        for key_node in al.keys:
            if not (isinstance(key_node, ast.Constant)
                    and isinstance(key_node.value, int)):
                continue
            idx = key_node.value
            if not 0 <= idx < len(in_specs.elts):
                continue
            if _is_blocked_spec(mod, in_specs.elts[idx], aliases):
                yield _diag(
                    mod, key_node, "aliased-pallas-planes",
                    f"pallas_call aliases BLOCKED input {idx} in-place "
                    f"on a multi-step grid — the r5 pipeline "
                    f"prefetch/writeback race silently corrupts state "
                    f"on hardware; use separate in/out planes with an "
                    f"in-kernel copy (ops/pallas_chunk.py), or an "
                    f"un-blocked ANY/HBM ref with explicit DMA "
                    f"(ops/pallas_ring.py)",
                )


# --------------------------------------------------------------------------
# raw-socket-in-worker
# --------------------------------------------------------------------------

#: socket calls that block forever without a configured timeout
_BLOCKING_SOCKET_METHODS = frozenset({
    "accept", "recv", "recv_into", "recvfrom", "recvmsg",
})


def _timeout_sanctioned_tails(mod: ModuleInfo) -> set[str]:
    """Receiver tails with a visible timeout configuration anywhere in
    the module: a `.settimeout(...)` call on that tail. Module-wide on
    purpose — sockets are typically configured once at their
    construction site (`__init__`, an accept loop) and blocked on in
    a different function, and a per-function scope would force
    re-asserting the timeout at every blocking site."""
    sanctioned: set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "settimeout":
            tail = _receiver_tail(f.value)
            if tail is not None:
                sanctioned.add(tail)
    return sanctioned


@rule(
    "raw-socket-in-worker", ERROR,
    "blocking socket accept/recv without a timeout in a repl/ worker "
    "thread",
)
def raw_socket_in_worker(mod: ModuleInfo,
                         project: Project) -> Iterator[Diagnostic]:
    """A `accept()`/`recv()` on a timeout-less socket inside a repl/
    thread target blocks FOREVER on a half-open connection: the worker
    can never observe its stop flag, `close()` hangs on the join, and
    a partitioned peer wedges the node instead of degrading it
    (`repl/transport.py`'s liveness discipline). Every socket a repl/
    worker loop blocks on must carry a `settimeout(...)` — visible on
    the same receiver name somewhere in the module (construction-site
    configuration counts) — or route its deadline through the
    injectable clock. Scoped to repl/ thread targets (the same
    transitive thread-target closure `swallowed-worker-exception`
    walks): request/response helpers on caller threads time out into
    the CALLER's error handling and are its business."""
    parts = re.split(r"[\\/]+", mod.path)
    if "repl" not in parts[:-1]:
        return
    sanctioned = _timeout_sanctioned_tails(mod)
    for name, fn in sorted(_thread_target_functions(mod,
                                                    project).items()):
        label = getattr(fn, "name", name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _BLOCKING_SOCKET_METHODS):
                continue
            tail = _receiver_tail(f.value)
            if tail is None:
                continue
            low = tail.lower()
            if not any(tok in low for tok in
                       ("sock", "conn", "listener", "client")):
                continue  # not socket-shaped (e.g. a queue's recv)
            if tail in sanctioned:
                continue
            yield _diag(
                mod, node, "raw-socket-in-worker",
                f"{label}: blocking .{f.attr}() on `{tail}` with no "
                f"settimeout anywhere in the module — a half-open "
                f"peer wedges this repl/ worker thread forever; "
                f"configure a socket timeout (or an injected-clock "
                f"deadline) so the loop can observe its stop flag",
            )


# --------------------------------------------------------------------------
# unbounded-metric-cardinality
# --------------------------------------------------------------------------

#: the registry's instrument factories (obs/metrics.py)
_METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})

#: receiver names that denote the metrics registry at a call site
#: (`reg.counter(...)`, `registry.gauge(...)`, `self._registry...`,
#: plus the direct `get_registry().counter(...)` chain)
_REGISTRY_TAILS = frozenset({"reg", "registry", "_reg", "_registry"})

#: identifier shapes that carry PER-RECORD data: log positions,
#: request/trace/sequence ids. Interpolating one into a metric NAME
#: mints a new instrument per record. Deliberately absent: `rid`
#: (replica id — fleet-bounded), `log_idx` (log count), `tid`
#: excluded? no — a thread-context tid is per-client-thread and
#: unbounded across a process lifetime, so it matches too.
_PER_RECORD_TOKENS = re.compile(
    r"(?:^|_)(?:pos0?|tid|seq(?:no)?|req(?:uest)?(?:_?id)?|"
    r"op_?id|record|trace_?id)(?:$|\d*$)",
    re.IGNORECASE,
)


def _is_registry_call(node: ast.Call) -> bool:
    f = node.func
    if not (isinstance(f, ast.Attribute)
            and f.attr in _METRIC_FACTORIES):
        return False
    recv = f.value
    if isinstance(recv, ast.Call):  # get_registry().counter(...)
        g = recv.func
        name = g.id if isinstance(g, ast.Name) else (
            g.attr if isinstance(g, ast.Attribute) else None
        )
        return name == "get_registry"
    tail = _receiver_tail(recv)
    return tail is not None and tail.lower() in _REGISTRY_TAILS


def _interp_exprs(name_arg: ast.AST) -> Iterator[ast.AST]:
    """Expressions interpolated into a metric-name argument: f-string
    holes, `.format(...)` arguments, `%` right-hand operands."""
    if isinstance(name_arg, ast.JoinedStr):
        for part in name_arg.values:
            if isinstance(part, ast.FormattedValue):
                yield part.value
    elif isinstance(name_arg, ast.Call) and isinstance(
            name_arg.func, ast.Attribute
    ) and name_arg.func.attr == "format":
        yield from name_arg.args
        for kw in name_arg.keywords:
            yield kw.value
    elif isinstance(name_arg, ast.BinOp) and isinstance(
            name_arg.op, ast.Mod):
        right = name_arg.right
        if isinstance(right, ast.Tuple):
            yield from right.elts
        else:
            yield right


def _per_record_ident(expr: ast.AST) -> str | None:
    """The per-record identifier an interpolated expression exposes,
    or None. Walks the whole expression so `rec.pos`, `self._seq`,
    and `int(pos0)` all surface their tell-tale name."""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and _PER_RECORD_TOKENS.search(name):
            return name
    return None


@rule(
    "unbounded-metric-cardinality", WARNING,
    "per-record value (pos / request id / seq) interpolated into a "
    "metric name",
)
def unbounded_metric_cardinality(
        mod: ModuleInfo, project: Project) -> Iterator[Diagnostic]:
    """The registry's obs discipline (`obs/metrics.py`): instruments
    are created once and cached; names are a FIXED vocabulary, with at
    most fleet-bounded dimensions baked in (`serve.queue_depth.r<rid>`
    — one per replica, retired with the replica). Interpolating
    per-record data — a log position, a request/trace id, a sequence
    number — into `counter(f"...{pos}...")` mints a new instrument
    per record: the registry (and every exporter scrape) grows without
    bound, which is a memory leak wearing a metrics costume. Emit the
    per-record value as a trace EVENT field instead (`obs/recorder`,
    sampled under NR_TPU_TRACE_SAMPLE); keep metric names closed over
    the code, not the data. Scoped outside obs/ — the registry's own
    implementation and fixtures legitimately build names from
    variables."""
    parts = re.split(r"[\\/]+", mod.path)
    if "obs" in parts[:-1]:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if not _is_registry_call(node):
            continue
        for expr in _interp_exprs(node.args[0]):
            ident = _per_record_ident(expr)
            if ident is None:
                continue
            kind = node.func.attr
            yield _diag(
                mod, node, "unbounded-metric-cardinality",
                f"`{ident}` interpolated into a {kind}() name mints "
                f"one instrument per record — the registry (and every "
                f"exporter scrape) grows without bound; emit it as a "
                f"trace event field instead and keep metric names a "
                f"fixed vocabulary",
            )
            break


# --------------------------------------------------------------------------
# device-sync-in-assembly
# --------------------------------------------------------------------------

#: host-sync calls that would re-serialize the serve pipeline if they
#: ran on the assembly stage (the whole point of the split is that the
#: assembly thread never waits on the device or on another round)
_ASSEMBLY_BLOCKING_DOTTED = {
    "jax.block_until_ready": "host sync re-serializes the pipeline",
    "jax.device_get": "device->host transfer re-serializes the "
                      "pipeline",
}
_ASSEMBLY_BLOCKING_METHODS = {
    "block_until_ready": "host sync re-serializes the pipeline",
    "item": "device->host scalar readback re-serializes the pipeline",
    "result": "waiting on a future blocks assembly behind the very "
              "round it should overlap",
}
#: the assembly-stage entry point (`ServeFrontend._assemble`); the
#: rule roots its transitive closure here
_ASSEMBLY_ENTRY = "_assemble"


def _assembly_functions(mod: ModuleInfo) -> dict[str, ast.AST]:
    """name -> function node for the assembly-stage call graph: the
    `_assemble` entry point closed transitively over same-module
    calls (plain `helper()` and `self._helper()` alike) — the
    `blocking-in-handler` closure machinery re-rooted at the serve
    pipeline's assembly stage."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    if _ASSEMBLY_ENTRY not in defs:
        return {}
    closure: dict[str, ast.AST] = {}
    queue: list[tuple[str, ast.AST]] = [
        (_ASSEMBLY_ENTRY, defs[_ASSEMBLY_ENTRY])
    ]
    while queue:
        name, fn = queue.pop()
        if name in closure:
            continue
        closure[name] = fn
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for n in ast.walk(stmt):
                if not isinstance(n, ast.Call):
                    continue
                callee = None
                if isinstance(n.func, ast.Name):
                    callee = n.func.id
                elif (
                    isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in ("self", "cls")
                ):
                    callee = n.func.attr
                if callee is not None and callee in defs:
                    queue.append((callee, defs[callee]))
    return closure


@rule(
    "device-sync-in-assembly", ERROR,
    "host-sync / future-wait on the serve pipeline's assembly stage",
)
def device_sync_in_assembly(mod: ModuleInfo,
                            project: Project) -> Iterator[Diagnostic]:
    """The pipelined serve worker (`ServeFrontend._assemble`,
    `ServeConfig.pipeline_depth`) exists to overlap round N+1's host
    work with round N's device work: the assembly stage drains the
    queue, sweeps deadlines, and `begin_mut_batch`es WITHOUT ever
    waiting on the device. A `block_until_ready`, `jax.device_get`,
    `.item()`, or `future.result()` anywhere in the assembly-stage
    call graph (the `_assemble` entry, closed transitively over
    same-module helpers like `blocking-in-handler`) silently
    re-serializes the pipeline — the overlap knob would still read 1
    while every round pays the full serial latency. Host syncs belong
    on the completion stage, which is the half DESIGNED to wait."""
    for name, fn in sorted(_assembly_functions(mod).items()):
        label = getattr(fn, "name", name)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = mod.dotted(node.func)
                if d in _ASSEMBLY_BLOCKING_DOTTED:
                    yield _diag(
                        mod, node, "device-sync-in-assembly",
                        f"{label}: {d}() on the assembly stage — "
                        f"{_ASSEMBLY_BLOCKING_DOTTED[d]}; move the "
                        f"sync to the completion stage",
                    )
                elif (
                    d is None
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ASSEMBLY_BLOCKING_METHODS
                ):
                    yield _diag(
                        mod, node, "device-sync-in-assembly",
                        f"{label}: .{node.func.attr}() on the "
                        f"assembly stage — "
                        f"{_ASSEMBLY_BLOCKING_METHODS[node.func.attr]}"
                        f"; move the sync to the completion stage",
                    )


# --------------------------------------------------------------------------
# unnamed-worker-thread
# --------------------------------------------------------------------------

_THREAD_NAMED_SUBSYSTEMS = frozenset(
    {"serve", "repl", "fault", "durable", "obs", "shard"}
)


@rule(
    "unnamed-worker-thread", WARNING,
    "threading.Thread(...) without name= in a subsystem module",
)
def unnamed_worker_thread(mod: ModuleInfo,
                          project: Project) -> Iterator[Diagnostic]:
    """The sampling profiler (`obs/profile.py`) attributes host CPU
    time by THREAD NAME: `serve-worker-r0` buckets under the
    serve-worker role, an anonymous `Thread-7` collapses into `other`
    and defeats the whole per-role budget (and `ServeFrontend.threads()`
    / stack dumps go equally blind). Every thread spawned inside the
    serve/, repl/, fault/, durable/, obs/ subsystems must carry a
    `name=` kwarg following the role-prefix contract
    (`obs/profile._ROLE_PREFIXES`). Scratch threads in tests, benches,
    and examples are out of scope — only subsystem code feeds the
    profiler's role table."""
    parts = re.split(r"[\\/]+", mod.path)
    if not _THREAD_NAMED_SUBSYSTEMS.intersection(parts[:-1]):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = mod.dotted(node.func)
        is_thread = d == "threading.Thread" or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "Thread"
        )
        if not is_thread:
            continue
        # name= kwarg, positional name (3rd arg: group/target/name),
        # or an opaque **kwargs splat all count as named
        if any(kw.arg == "name" or kw.arg is None
               for kw in node.keywords) or len(node.args) >= 3:
            continue
        yield _diag(
            mod, node, "unnamed-worker-thread",
            "threading.Thread without name= — anonymous threads "
            "collapse into the profiler's 'other' role bucket; name "
            "it with the subsystem's role prefix "
            "(obs/profile._ROLE_PREFIXES)",
        )

# --------------------------------------------------------------------------
# unrouted-key-in-shard-path
# --------------------------------------------------------------------------

#: submit surfaces of the serve frontend a shard/ function may only
#: reach AFTER a ShardMap lookup proved (or verified) the key's owner
_SHARD_SUBMIT_METHODS = frozenset({"submit", "execute_mut_batch"})

#: ShardMap lookups that constitute the routing step (`shard/ring.py`)
_SHARD_LOOKUP_CALLS = frozenset(
    {"shard_of", "shard_of_op", "split_batch"}
)


@rule(
    "unrouted-key-in-shard-path", ERROR,
    "frontend submit in shard/ with no ShardMap lookup in the same "
    "function",
)
def unrouted_key_in_shard_path(mod: ModuleInfo,
                               project: Project) -> Iterator[Diagnostic]:
    """The fleet-level LogMapper invariant, machine-checked like the
    in-process one: every write that reaches a `ServeFrontend` inside
    shard/ must have been routed — or re-verified — through the
    `ShardMap` congruence lookup (`shard_of` / `shard_of_op` /
    `split_batch`, `shard/ring.py`). A direct `.submit(...)` /
    `.execute_mut_batch(...)` in a shard/ function with NO lookup in
    that function is a path that can write a key into the wrong
    keyspace slice — silently, because the frontend itself has no idea
    shards exist; the mis-route would only surface as a cross-shard
    isolation violation later (the exact bug class `WrongShard` exists
    to make typed and immediate). Scoped per function: the lookup and
    the submit belong in the same routing step, not "somewhere in the
    module" — a verified sub-batch handed to a helper that submits
    blind is still one stale-map refactor away from a mis-route.
    Reads are exempt (any replica of any shard serves a read of ITS
    slice; a mis-routed read returns a typed miss, not corruption)."""
    parts = re.split(r"[\\/]+", mod.path)
    if "shard" not in parts[:-1]:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        submits = []
        routed = False
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr in _SHARD_SUBMIT_METHODS:
                submits.append(sub)
            elif f.attr in _SHARD_LOOKUP_CALLS:
                routed = True
        if routed:
            continue
        for sub in submits:
            yield _diag(
                mod, sub, "unrouted-key-in-shard-path",
                f"{node.name}: .{sub.func.attr}() on a frontend "
                f"inside shard/ with no ShardMap lookup "
                f"(shard_of/shard_of_op/split_batch) in the same "
                f"function — an unrouted key can land in the wrong "
                f"keyspace slice; route (or re-verify) through the "
                f"map before submitting",
            )


# --------------------------------------------------------------------------
# txn-ack-before-decision
# --------------------------------------------------------------------------

#: the prepare step of the 2PC protocol (`shard/txn.py`): an attribute
#: call `.prepare(...)` or a verb string handed to a dispatch helper
#: (`_verb_rehomed(s, "prepare", ...)`, `txn_verb("prepare", ...)`)
_TXN_PREPARE_ATTRS = frozenset({"prepare"})

#: sites that resolve the CALLER's view of the transaction — a future
#: resolution or an ok-frame reply. `set_exception` is exempt: failing
#: the caller never claims the transaction decided.
_TXN_ACK_ATTRS = frozenset({"set_result", "send_ok", "reply_ok"})

#: the durable decision point (`durable/txnlog.py DecisionLog.publish`
#: via `durable_publish`): the only thing allowed to dominate an ack
_TXN_DECISION_NAMES = frozenset(
    {"publish", "publish_decision", "durable_publish", "decide"}
)


@rule(
    "txn-ack-before-decision", ERROR,
    "txn path acks the caller with no durable decision publish "
    "dominating it in the same function",
)
def txn_ack_before_decision(mod: ModuleInfo,
                            project: Project) -> Iterator[Diagnostic]:
    """The 2PC commit point is the DURABLE DECISION RECORD, nothing
    else (`shard/txn.py`): once a coordinator tells its caller the
    transaction committed, a crash one instruction later must leave
    behind a decision document that recovery can re-drive — otherwise
    the prepared participants presumed-abort a transaction the caller
    was told succeeded, which is precisely the half-committed state
    the whole layer exists to rule out. Machine-checked shape: a
    shard/ function that drives a prepare verb AND resolves the
    caller's future (`.set_result`) or sends an ok frame must have a
    decision publish (`DecisionLog.publish` / `durable_publish`) at an
    earlier line of the same function. `set_exception` is exempt —
    reporting failure never claims a decision. Scoped per function
    for the same reason as `unrouted-key-in-shard-path`: the decision
    and the ack belong in the same protocol step, not "somewhere in
    the module"."""
    parts = re.split(r"[\\/]+", mod.path)
    if "shard" not in parts[:-1]:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        prepare_line = None
        decision_lines = []
        acks = []
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if attr is None:
                continue
            if attr in _TXN_PREPARE_ATTRS or any(
                isinstance(a, ast.Constant) and a.value == "prepare"
                for a in sub.args
            ):
                if prepare_line is None or sub.lineno < prepare_line:
                    prepare_line = sub.lineno
            elif attr in _TXN_ACK_ATTRS:
                acks.append(sub)
            elif attr in _TXN_DECISION_NAMES:
                decision_lines.append(sub.lineno)
        if prepare_line is None:
            continue
        for ack in acks:
            if any(dl < ack.lineno for dl in decision_lines):
                continue
            yield _diag(
                mod, ack, "txn-ack-before-decision",
                f"{node.name}: .{ack.func.attr if isinstance(ack.func, ast.Attribute) else ack.func.id}"
                f"() acks the transaction to the caller with no "
                f"durable decision publish (DecisionLog.publish / "
                f"durable_publish) at an earlier line of the same "
                f"function — a crash after this ack presumed-aborts "
                f"a transaction the caller was told committed",
            )
