"""Instrumented lock factory: the runtime half of nrcheck (ISSUE 17).

Subsystem locks are constructed through `make_lock` / `make_rlock` /
`make_condition` with a NAME that matches the static lock-order
graph's node naming (`<Class>.<attr>` for instance locks,
`<module_tail>.<var>` for module-level locks — see
`analysis/concurrency.py`, which machine-checks the name at each
construction site). In production the factory is a zero-cost
passthrough to the plain `threading` primitives; with
`NR_TPU_LOCKCHECK=1` every acquisition is checked against the
per-thread held-lock set:

- a *blocking* acquisition whose new ordering edge closes a cycle in
  the so-far-observed lock-order graph raises `LockOrderError` BEFORE
  blocking — the interleaving that would deadlock under an adversarial
  schedule fails fast and loud instead of hanging CI;
- a blocking re-acquisition of a held non-reentrant lock (guaranteed
  self-deadlock) raises the same way;
- every observed edge `held -> acquired` is recorded, and
  `NR_TPU_LOCKGRAPH=<path>` dumps the union as JSON at interpreter
  exit (merging with an existing file, so a multi-invocation CI job
  accumulates one graph). `analysis.lint --check-dynamic <path>`
  asserts the dump is a subgraph of the static lock-order graph — the
  static analysis and the runtime check validate each other.

This module must stay dependency-free (stdlib only): it is imported
by core/, serve/, repl/, and obs/ at module-import time.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading

__all__ = [
    "LockOrderError",
    "make_lock",
    "make_rlock",
    "make_condition",
    "lockcheck_enabled",
    "dump_lockgraph",
    "current_edges",
    "fresh_state",
]


class LockOrderError(RuntimeError):
    """A lock acquisition would deadlock under some schedule: either
    the new ordering edge closes a cycle in the observed lock-order
    graph, or a non-reentrant lock is being re-acquired by its own
    holder. Raised BEFORE the acquisition blocks."""


def lockcheck_enabled() -> bool:
    """True when `NR_TPU_LOCKCHECK=1` (checked at construction time,
    so a test may flip the env var before building its fixtures)."""
    return os.environ.get("NR_TPU_LOCKCHECK", "") == "1"


class LockCheckState:
    """Observed lock-order graph + per-thread held stacks.

    One process-global instance backs the factory; tests build private
    instances (`fresh_state`) so fixture edges never pollute the
    process graph that CI compares against the static one.
    """

    def __init__(self):
        # plain, uninstrumented lock: guards the edge graph (it is a
        # leaf by construction — nothing is acquired under it)
        self._meta = threading.Lock()
        #: observed edges: held-name -> {acquired-name, ...}
        self.edges: dict[str, set[str]] = {}
        self._tls = threading.local()

    # ------------------------------------------------------- held stack

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st  # list of [name, count] in acquisition order

    def held(self) -> list[str]:
        """Names this thread currently holds, outermost first."""
        return [name for name, _ in self._stack()]

    # ---------------------------------------------------------- checks

    def _reaches(self, src: str, dst: str) -> bool:
        """Path src ->* dst in the observed graph (caller holds _meta)."""
        seen = {src}
        frontier = [src]
        while frontier:
            nxt = []
            for n in frontier:
                for m in self.edges.get(n, ()):
                    if m == dst:
                        return True
                    if m not in seen:
                        seen.add(m)
                        nxt.append(m)
            frontier = nxt
        return False

    def before_acquire(self, name: str, blocking: bool,
                       reentrant: bool) -> None:
        stack = self._stack()
        for ent in stack:
            if ent[0] == name:
                if reentrant or not blocking:
                    # RLock re-entry, or a trylock probe that will
                    # simply return False: no new edges, no deadlock
                    return
                raise LockOrderError(
                    f"self-deadlock: thread "
                    f"{threading.current_thread().name!r} already "
                    f"holds non-reentrant lock {name!r}"
                )
        if not stack:
            return
        held = [ent[0] for ent in stack]
        with self._meta:
            # record FIRST, then check: a raised cycle stays visible
            # in the dumped graph for the post-mortem
            for h in held:
                if h != name:
                    self.edges.setdefault(h, set()).add(name)
            if blocking:
                for h in held:
                    if h != name and self._reaches(name, h):
                        raise LockOrderError(
                            f"lock-order cycle: acquiring {name!r} "
                            f"while holding {held!r} closes a cycle "
                            f"({name} ->* {h} -> {name}) in the "
                            f"observed lock-order graph — this "
                            f"interleaving can deadlock"
                        )

    def after_acquire(self, name: str) -> None:
        stack = self._stack()
        for ent in stack:
            if ent[0] == name:
                ent[1] += 1
                return
        stack.append([name, 1])

    def after_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                stack[i][1] -= 1
                if stack[i][1] <= 0:
                    del stack[i]
                return

    # ------------------------------------------------------------ dump

    def edge_list(self) -> list[list[str]]:
        with self._meta:
            return sorted(
                [a, b] for a, bs in self.edges.items() for b in bs
            )


_state = LockCheckState()


@contextlib.contextmanager
def fresh_state():
    """Swap in a private `LockCheckState` (test isolation: fixture
    locks must not contribute edges to the process graph)."""
    global _state
    prev = _state
    _state = LockCheckState()
    try:
        yield _state
    finally:
        _state = prev


def current_edges() -> list[list[str]]:
    """Observed `[held, acquired]` edges so far (checked mode only)."""
    return _state.edge_list()


class _CheckedLock:
    """Order-checking wrapper satisfying the `threading.Lock` protocol
    (acquire/release/locked/context manager), so `threading.Condition`
    can be built directly on top of one — `Condition.wait`'s
    release/re-acquire then flows through the held-stack bookkeeping."""

    _reentrant = False

    def __init__(self, name: str, state: LockCheckState | None = None):
        self.name = name
        self._state = state if state is not None else _state
        self._lock = self._make()

    def _make(self):
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._state.before_acquire(self.name, blocking, self._reentrant)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._state.after_acquire(self.name)
        return ok

    def release(self):
        self._lock.release()
        self._state.after_release(self.name)

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


class _CheckedRLock(_CheckedLock):
    _reentrant = True

    def _make(self):
        return threading.RLock()

    # threading.Condition uses these when present so a reentrantly
    # held lock is FULLY released around wait(); count bookkeeping
    # must follow the saved state through the round-trip
    def _release_save(self):
        stack = self._state._stack()
        count = 0
        for ent in stack:
            if ent[0] == self.name:
                count = ent[1]
                break
        saved = self._lock._release_save()
        for _ in range(max(count, 1)):
            self._state.after_release(self.name)
        return (saved, count)

    def _acquire_restore(self, state):
        saved, count = state
        self._state.before_acquire(self.name, True, True)
        self._lock._acquire_restore(saved)
        for _ in range(max(count, 1)):
            self._state.after_acquire(self.name)

    def _is_owned(self):
        return self._lock._is_owned()


def make_lock(name: str):
    """A `threading.Lock` (or its order-checking twin under
    `NR_TPU_LOCKCHECK=1`). `name` must match the static graph node:
    `<Class>.<attr>` / `<module_tail>.<var>`."""
    if lockcheck_enabled():
        return _CheckedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """Reentrant variant of `make_lock` (re-entry adds no edges)."""
    if lockcheck_enabled():
        return _CheckedRLock(name)
    return threading.RLock()


def make_condition(name: str, lock=None):
    """A `threading.Condition`. Pass `lock` to share an existing
    factory-made lock (the paired `_lock`/`_cond` idiom — the pair is
    then ONE node in the lock-order graph); otherwise the condition
    owns a private lock registered under `name`."""
    if lock is not None:
        return threading.Condition(lock)
    if lockcheck_enabled():
        return threading.Condition(_CheckedLock(name))
    return threading.Condition()


# ------------------------------------------------------------------ dump


def dump_lockgraph(path: str) -> None:
    """Write (merging with any existing dump at `path`) the observed
    edge set as `{"edges": [[held, acquired], ...]}`."""
    edges = {tuple(e) for e in _state.edge_list()}
    try:
        with open(path) as f:
            prev = json.load(f)
        edges |= {tuple(e) for e in prev.get("edges", [])}
    except (OSError, ValueError):
        pass
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"edges": sorted(list(e) for e in edges)}, f,
                  indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _atexit_dump() -> None:
    path = os.environ.get("NR_TPU_LOCKGRAPH", "")
    if path and lockcheck_enabled():
        dump_lockgraph(path)


atexit.register(_atexit_dump)
