"""nrlint engine + CLI.

    python -m node_replication_tpu.analysis.lint <paths> [options]

Parses every `.py` under the given paths, builds the project-wide
context (traced closure, Dispatch registrations — `astutil.py`), runs
every registered rule (`rules.py`), and prints
`file:line:col: rule-id severity: message` diagnostics.

Exit status: 0 when no unsuppressed diagnostic at or above
`--min-severity` (default `warning`) remains, 1 otherwise — the CI
gate. Suppressions (`# nrlint: disable=<rule>[,<rule>]` on the
diagnostic's line or the line directly above) keep the diagnostic
visible with `--show-suppressed` but never fail the run; a suppression
naming an unknown rule id is itself a `unknown-suppression` warning so
typos cannot disarm the gate.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Iterable

from node_replication_tpu.analysis.astutil import (
    Diagnostic,
    ModuleInfo,
    Project,
)
from node_replication_tpu.analysis.rules import (
    RULES,
    SEVERITY_ORDER,
    WARNING,
)
from node_replication_tpu.analysis import concurrency  # registers the
#   nrcheck-* and concurrency rules as an import side effect


def collect_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__"
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def _suppressed_by(mod: ModuleInfo, diag: Diagnostic) -> bool:
    for line in (diag.line, diag.line - 1):
        rules = mod.suppressions.get(line)
        if rules and diag.rule_id in rules:
            return True
    return False


def build_project(
    paths: Iterable[str],
) -> tuple[list[ModuleInfo], Project, list[str]]:
    """Parse every file under `paths` into one analyzable Project."""
    errors: list[str] = []
    modules: list[ModuleInfo] = []
    for path in collect_files(paths):
        try:
            modules.append(ModuleInfo(path))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{path}: {e}")
    return modules, Project(modules), errors


def run_lint(
    paths: Iterable[str],
    select: set[str] | None = None,
    project: Project | None = None,
) -> tuple[list[Diagnostic], list[str]]:
    """Run every (or the selected) rule over `paths`.

    Returns `(diagnostics, errors)`: diagnostics carry a `suppressed`
    flag already resolved against the source comments; `errors` are
    files that failed to parse (themselves a gate failure).
    """
    if project is None:
        modules, project, errors = build_project(paths)
    else:
        modules, errors = project.modules, []
    diags: list[Diagnostic] = []
    for mod in modules:
        for rule in RULES.values():
            if select and rule.id not in select:
                continue
            for d in rule.check(mod, project):
                d.suppressed = _suppressed_by(mod, d)
                diags.append(d)
        # meta-checks: a typo'd suppression must never silently disarm
        # the gate — unknown rule names and malformed suppression
        # comments are both diagnosed
        for line, names in sorted(mod.suppressions.items()):
            for name in sorted(names):
                if name not in RULES:
                    diags.append(Diagnostic(
                        path=mod.path, line=line, col=1,
                        rule_id="unknown-suppression",
                        severity=WARNING,
                        message=(
                            f"suppression names unknown rule "
                            f"{name!r} (known: "
                            f"{', '.join(sorted(RULES))})"
                        ),
                    ))
        for line in mod.malformed_suppressions:
            diags.append(Diagnostic(
                path=mod.path, line=line, col=1,
                rule_id="unknown-suppression",
                severity=WARNING,
                message=(
                    "malformed nrlint comment (suppresses nothing); "
                    "the only recognized form is "
                    "`# nrlint: disable=<rule>[,<rule>]`"
                ),
            ))
    diags.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return diags, errors


_SUPPRESS_LINE_RE = re.compile(
    r"#\s*nrlint:\s*disable\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)(.*)$"
)


def audit_suppressions(paths: Iterable[str]) -> int:
    """`--suppressions`: list every `# nrlint: disable=` with file:line,
    flag STALE entries (the named rule no longer fires on the covered
    lines) and UNJUSTIFIED entries (no trailing `— why` text and no
    explanatory comment on the line above). Exit 1 when either class
    is non-empty — a suppression must stay load-bearing and reviewed.
    """
    files = collect_files(paths)
    diags, errors = run_lint(files)
    for e in errors:
        print(f"parse error: {e}")
    # every diagnostic (suppressed or not) a rule produced, keyed so a
    # suppression at line L is "used" by a firing at L or L+1
    fired: set[tuple[str, str, int]] = set()
    for d in diags:
        fired.add((d.path, d.rule_id, d.line))
    n_stale = n_unjust = n_total = 0
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            continue
        for i, text in enumerate(lines, start=1):
            m = _SUPPRESS_LINE_RE.search(text)
            if not m:
                continue
            ids = [s.strip() for s in m.group(1).split(",")]
            trailing = m.group(2).strip(" -—:\t")
            above = lines[i - 2].strip() if i >= 2 else ""
            justified = bool(trailing) or (
                above.startswith("#")
                and not _SUPPRESS_LINE_RE.search(above)
            )
            for rid in ids:
                n_total += 1
                notes = []
                if rid in RULES and not (
                    (path, rid, i) in fired or (path, rid, i + 1) in fired
                ):
                    notes.append("STALE: rule no longer fires here")
                    n_stale += 1
                if not justified:
                    notes.append(
                        "UNJUSTIFIED: add `— why` or a comment above")
                    n_unjust += 1
                note = f"  [{'; '.join(notes)}]" if notes else ""
                print(f"{path}:{i}: disable={rid}{note}")
    print(
        f"nrlint --suppressions: {n_total} suppression(s), "
        f"{n_stale} stale, {n_unjust} unjustified"
    )
    return 1 if n_stale or n_unjust or errors else 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.analysis.lint",
        description=(
            "nrlint: project-native static analysis (trace hygiene, "
            "combiner lock discipline, ring-cursor safety)"
        ),
    )
    ap.add_argument("paths", nargs="*", default=["node_replication_tpu"],
                    help="files or directories (default: the package)")
    ap.add_argument("--min-severity", default=WARNING,
                    choices=sorted(SEVERITY_ORDER, key=SEVERITY_ORDER.get),
                    help="fail threshold (default: warning)")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed diagnostics")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--suppressions", action="store_true",
                    help="audit mode: list every suppression, flag "
                         "stale and unjustified ones")
    ap.add_argument("--lockgraph-out", default=None, metavar="PATH",
                    help="write the static lock-order graph "
                         "(nodes/edges/cycles) as JSON")
    ap.add_argument("--check-dynamic", default=None, metavar="PATH",
                    help="verify a runtime lockgraph dump "
                         "(NR_TPU_LOCKGRAPH) is a subgraph of the "
                         "static graph")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(r) for r in RULES)
        for rid, r in sorted(RULES.items()):
            print(f"{rid:<{width}}  {r.severity:<7}  {r.summary}")
        return 0

    if args.suppressions:
        return audit_suppressions(args.paths)

    select = (
        {s.strip() for s in args.select.split(",") if s.strip()}
        if args.select else None
    )
    if select:
        unknown = select - set(RULES)
        if unknown:
            print(f"nrlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    files = collect_files(args.paths)
    modules, project, errors = build_project(files)
    diags, _ = run_lint(files, select=select, project=project)
    for e in errors:
        print(f"parse error: {e}")

    graph_rc = 0
    if args.lockgraph_out or args.check_dynamic:
        analysis = concurrency.analyze(project)
        if args.lockgraph_out:
            with open(args.lockgraph_out, "w") as f:
                json.dump(analysis.graph_json(), f, indent=1,
                          sort_keys=True)
                f.write("\n")
            print(f"nrlint: static lock-order graph "
                  f"({len(analysis.edge_list())} edge(s)) -> "
                  f"{args.lockgraph_out}")
        if args.check_dynamic:
            try:
                with open(args.check_dynamic) as f:
                    dyn = json.load(f).get("edges", [])
            except (OSError, ValueError) as e:
                print(f"nrlint: cannot read dynamic lockgraph "
                      f"{args.check_dynamic}: {e}")
                return 2
            violations = analysis.check_dynamic(dyn)
            for v in violations:
                print(f"nrlint: {v}")
            print(
                f"nrlint --check-dynamic: {len(dyn)} dynamic edge(s), "
                f"{len(violations)} missing from the static graph, "
                f"{len(analysis.cycles)} static cycle(s)"
            )
            if violations or analysis.cycles:
                graph_rc = 1

    threshold = SEVERITY_ORDER[args.min_severity]
    failing = [
        d for d in diags
        if not d.suppressed and SEVERITY_ORDER[d.severity] >= threshold
    ]
    shown = failing if not args.show_suppressed else [
        d for d in diags if SEVERITY_ORDER[d.severity] >= threshold
    ]
    for d in shown:
        print(d.format())

    n_suppressed = sum(1 for d in diags if d.suppressed)
    print(
        f"nrlint: {len(failing)} failing diagnostic(s), "
        f"{n_suppressed} suppressed, {len(diags)} total "
        f"across {len(files)} file(s)"
    )
    return 1 if failing or errors or graph_rc else 0


if __name__ == "__main__":
    raise SystemExit(main())
