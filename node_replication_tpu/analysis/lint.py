"""nrlint engine + CLI.

    python -m node_replication_tpu.analysis.lint <paths> [options]

Parses every `.py` under the given paths, builds the project-wide
context (traced closure, Dispatch registrations — `astutil.py`), runs
every registered rule (`rules.py`), and prints
`file:line:col: rule-id severity: message` diagnostics.

Exit status: 0 when no unsuppressed diagnostic at or above
`--min-severity` (default `warning`) remains, 1 otherwise — the CI
gate. Suppressions (`# nrlint: disable=<rule>[,<rule>]` on the
diagnostic's line or the line directly above) keep the diagnostic
visible with `--show-suppressed` but never fail the run; a suppression
naming an unknown rule id is itself a `unknown-suppression` warning so
typos cannot disarm the gate.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Iterable

from node_replication_tpu.analysis.astutil import (
    Diagnostic,
    ModuleInfo,
    Project,
)
from node_replication_tpu.analysis.rules import (
    RULES,
    SEVERITY_ORDER,
    WARNING,
)


def collect_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__"
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def _suppressed_by(mod: ModuleInfo, diag: Diagnostic) -> bool:
    for line in (diag.line, diag.line - 1):
        rules = mod.suppressions.get(line)
        if rules and diag.rule_id in rules:
            return True
    return False


def run_lint(
    paths: Iterable[str],
    select: set[str] | None = None,
) -> tuple[list[Diagnostic], list[str]]:
    """Run every (or the selected) rule over `paths`.

    Returns `(diagnostics, errors)`: diagnostics carry a `suppressed`
    flag already resolved against the source comments; `errors` are
    files that failed to parse (themselves a gate failure).
    """
    errors: list[str] = []
    modules: list[ModuleInfo] = []
    for path in collect_files(paths):
        try:
            modules.append(ModuleInfo(path))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{path}: {e}")
    project = Project(modules)
    diags: list[Diagnostic] = []
    for mod in modules:
        for rule in RULES.values():
            if select and rule.id not in select:
                continue
            for d in rule.check(mod, project):
                d.suppressed = _suppressed_by(mod, d)
                diags.append(d)
        # meta-checks: a typo'd suppression must never silently disarm
        # the gate — unknown rule names and malformed suppression
        # comments are both diagnosed
        for line, names in sorted(mod.suppressions.items()):
            for name in sorted(names):
                if name not in RULES:
                    diags.append(Diagnostic(
                        path=mod.path, line=line, col=1,
                        rule_id="unknown-suppression",
                        severity=WARNING,
                        message=(
                            f"suppression names unknown rule "
                            f"{name!r} (known: "
                            f"{', '.join(sorted(RULES))})"
                        ),
                    ))
        for line in mod.malformed_suppressions:
            diags.append(Diagnostic(
                path=mod.path, line=line, col=1,
                rule_id="unknown-suppression",
                severity=WARNING,
                message=(
                    "malformed nrlint comment (suppresses nothing); "
                    "the only recognized form is "
                    "`# nrlint: disable=<rule>[,<rule>]`"
                ),
            ))
    diags.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return diags, errors


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m node_replication_tpu.analysis.lint",
        description=(
            "nrlint: project-native static analysis (trace hygiene, "
            "combiner lock discipline, ring-cursor safety)"
        ),
    )
    ap.add_argument("paths", nargs="*", default=["node_replication_tpu"],
                    help="files or directories (default: the package)")
    ap.add_argument("--min-severity", default=WARNING,
                    choices=sorted(SEVERITY_ORDER, key=SEVERITY_ORDER.get),
                    help="fail threshold (default: warning)")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed diagnostics")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(r) for r in RULES)
        for rid, r in sorted(RULES.items()):
            print(f"{rid:<{width}}  {r.severity:<7}  {r.summary}")
        return 0

    select = (
        {s.strip() for s in args.select.split(",") if s.strip()}
        if args.select else None
    )
    if select:
        unknown = select - set(RULES)
        if unknown:
            print(f"nrlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    files = collect_files(args.paths)
    diags, errors = run_lint(files, select=select)
    for e in errors:
        print(f"parse error: {e}")

    threshold = SEVERITY_ORDER[args.min_severity]
    failing = [
        d for d in diags
        if not d.suppressed and SEVERITY_ORDER[d.severity] >= threshold
    ]
    shown = failing if not args.show_suppressed else [
        d for d in diags if SEVERITY_ORDER[d.severity] >= threshold
    ]
    for d in shown:
        print(d.format())

    n_suppressed = sum(1 for d in diags if d.suppressed)
    print(
        f"nrlint: {len(failing)} failing diagnostic(s), "
        f"{n_suppressed} suppressed, {len(diags)} total "
        f"across {len(files)} file(s)"
    )
    return 1 if failing or errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
