"""nrlint: project-native static analysis for the TPU node-replication port.

The reference compiles its invariants into every build as `panic!`s
(`nr/src/log.rs:487-489`, `nr/src/context.rs:145-148`); compiled XLA code
cannot panic, so this port's equivalents are *conventions* — checkify
wrappers (`utils/checks.py`), "no host sync inside the hot path", "obs
calls never inside traced code", "ring indices are masked" — that nothing
used to enforce. This package is the machine-checked gate: an AST-based
lint over the project's own idioms, run as a required CI job.

    python -m node_replication_tpu.analysis.lint node_replication_tpu/

Layout:

- `astutil.py` — parsing, suppression comments, import/alias resolution,
  and the traced-closure inference (which functions execute under
  `jax.jit`/`vmap`/`lax.*`/`pallas_call` tracing, directly or through the
  project call graph / `Dispatch` registration).
- `rules.py` — the rule registry and every shipped rule.
- `lint.py` — the engine + CLI (`file:line:col: rule-id severity:
  message` diagnostics, `--min-severity`, `--list-rules`).

Suppress a diagnostic with a trailing (or immediately-preceding-line)
comment: `# nrlint: disable=<rule-id>[,<rule-id>...] — justification`.
That exact form is the ONLY one that suppresses: unknown rule ids and
malformed `# nrlint` comments are themselves diagnosed
(`unknown-suppression`) so typos cannot silently disarm the gate.
"""

__all__ = ["run_lint"]


def __getattr__(name):
    # lazy: `python -m node_replication_tpu.analysis.lint` would warn
    # about double-import if the package eagerly imported the submodule
    if name == "run_lint":
        from node_replication_tpu.analysis.lint import run_lint

        return run_lint
    raise AttributeError(name)
