"""nrcheck: whole-program lock-discipline analysis (ISSUE 17).

Three rules over the `astutil.Project` graph, plus two single-module
concurrency rules. The whole-program pass runs ONCE per project
(cached) and answers the two questions `nrlint`'s per-file rules
cannot:

- **guarded-by inference** (`nrcheck-guarded-by`): for every class the
  thread-role oracle marks as shared, infer which lock guards each
  `self._attr` — an attribute whose every store (outside `__init__`)
  happens under lock L is guarded by L — and flag reads outside L
  (and stores outside any lock for mixed-discipline attributes).
  Escape hatches, in declaration order of preference:
  `# guarded-by: <lock_attr>` on a `def` line (caller-holds-lock
  contract: the whole method body is an L region), `# guarded-by:`
  on an access line (this one access is known to run under L), and
  `# nrcheck: unshared` on an access line or on the attribute's
  `__init__` assignment (single-writer / racy-but-benign by design —
  the comment must say why).

- **lock-order graph** (`nrcheck-lock-order`): every nested
  acquisition — `with self._a:` inside `with self._b:`, directly or
  through calls resolved across modules — is an edge `b -> a`. A
  cycle is a potential deadlock. Graph nodes are named
  `<Class>.<attr>` / `<module_tail>.<var>`, the SAME names the
  runtime factory (`analysis/locks.py`) records, so the dynamic graph
  a `NR_TPU_LOCKCHECK=1` run dumps can be checked to be a subgraph of
  this one (`lint --check-dynamic`). `# nrcheck: lock-order A -> B`
  declares an edge the resolver cannot see (e.g. through a stored
  callback).

- **annotation hygiene** (`nrcheck-annotation`): malformed `# nrcheck:`
  / `# guarded-by:` comments, and factory construction sites whose
  name string does not match the static node name (name drift would
  silently disarm the dynamic-vs-static cross-check).

Single-module rules: `condition-wait-without-predicate-loop` (a bare
no-timeout `cond.wait()` outside a `while` misses spurious wakeups)
and `lock-held-across-blocking-call` (fsync / socket I/O /
`block_until_ready` / `.result()` under a held subsystem lock).

The pass is deliberately an under-approximating call resolver (typed
receivers via `__init__` assignments, parameter and return
annotations, module globals) glued to an over-approximating region
walker (a manually released lock still counts as held) — both err
toward the safe side of the dynamic-subgraph gate.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Iterator

from node_replication_tpu.analysis.astutil import (
    Diagnostic,
    ModuleInfo,
    Project,
)
from node_replication_tpu.analysis.rules import (
    ERROR,
    RULES,
    WARNING,
    _MUTATORS,
    _diag,
    _is_locked_method,
    _receiver_tail,
    _self_attr,
    rule,
)

# Thread-name prefix -> role. MUST mirror `obs.profile._ROLE_PREFIXES`
# (PR 16's thread-name contract); a unit test asserts the two tables
# agree so the oracle cannot drift. Kept as a copy because the
# analyzer must import without the runtime deps obs/ pulls in.
ROLE_PREFIXES: tuple[tuple[str, str], ...] = (
    ("serve-worker-", "serve-worker"),
    ("serve-asm-", "serve-assembly"),
    ("serve-cpl-", "serve-completion"),
    ("serve-client-", "serve-client"),
    ("repl-shipper", "repl-shipper"),
    ("repl-relay-", "repl-relay"),
    ("repl-apply-", "repl-apply"),
    ("repl-feed-", "repl-feed"),
    ("repl-promotion-watch", "repl-promote"),
    ("fault-medic-", "fault-medic"),
    ("obs-export-", "obs-export"),
    ("obs-device-trace-", "obs-export"),
    ("obs-fleet-collector", "obs-collect"),
    ("obs-profiler", "obs-profiler"),
    ("MainThread", "main"),
)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(.*)$")
_NRCHECK_RE = re.compile(r"#\s*nrcheck:\s*(.*)$")
_LOCK_ORDER_RE = re.compile(
    r"^lock-order\s+([\w.]+)\s*->\s*([\w.]+)\s*(?:—.*|--.*)?$"
)
_ATTR_RE = re.compile(r"^[A-Za-z_]\w*$")

_LOCK_FACTORIES = {"make_lock", "make_rlock", "make_condition"}
_THREADING_LOCKS = {"Lock", "RLock", "Condition"}


# --------------------------------------------------------------------------
# annotations
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _Annotations:
    guarded_by: dict[int, str]          # line -> lock attr name
    unshared: set[int]                  # lines carrying `nrcheck: unshared`
    lock_order: list[tuple[int, str, str]]  # declared edges
    malformed: list[tuple[int, str]]    # line, offending text


def _comment_tokens(source: str) -> list[tuple[int, str]]:
    """(line, text) for every real COMMENT token — a directive-shaped
    string inside a docstring must not count as an annotation."""
    out: list[tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError):
        pass
    return out


def _parse_annotations(mod: ModuleInfo) -> _Annotations:
    ann = _Annotations({}, set(), [], [])
    for i, line in _comment_tokens(mod.source):
        m = _GUARDED_RE.search(line)
        if m:
            arg = m.group(1).split("—")[0].split("--")[0].strip()
            if _ATTR_RE.match(arg):
                ann.guarded_by[i] = arg
            else:
                ann.malformed.append(
                    (i, f"guarded-by wants one lock attribute name, "
                        f"got {m.group(1).strip()!r}"))
        m = _NRCHECK_RE.search(line)
        if not m:
            continue
        body = m.group(1).strip()
        if body == "unshared" or body.startswith(("unshared —",
                                                  "unshared --")):
            ann.unshared.add(i)
            continue
        lo = _LOCK_ORDER_RE.match(body)
        if lo:
            ann.lock_order.append((i, lo.group(1), lo.group(2)))
            continue
        ann.malformed.append(
            (i, f"unknown nrcheck directive {body!r} (forms: "
                f"`unshared — why`, `lock-order A -> B — why`)"))
    return ann


def _annotated(ann: _Annotations, line: int, *, unshared=False,
               guarded: str | None = None) -> bool:
    """Annotation applies on the access line or the line above (the
    same two-line scope nrlint suppressions use)."""
    for ln in (line, line - 1):
        if unshared and ln in ann.unshared:
            return True
        if guarded is not None and ann.guarded_by.get(ln) == guarded:
            return True
    return False


# --------------------------------------------------------------------------
# per-class model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _ClassInfo:
    mod: ModuleInfo
    node: ast.ClassDef
    name: str
    bases: list[str]
    methods: dict[str, ast.FunctionDef]
    lock_attrs: dict[str, str]   # attr -> lock-graph node name
    attr_types: dict[str, str]   # attr -> class name (typed receivers)
    spawns_thread: bool = False


def _ann_tail(node: ast.AST | None) -> str | None:
    """Class name out of an annotation expression (`Counter`,
    `metrics.Counter`, `"Counter"`, `Counter | None`)."""
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1].strip()
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _ann_tail(node.left) or _ann_tail(node.right)
    if isinstance(node, ast.Subscript):  # Optional[X]
        return _ann_tail(node.slice)
    return None


def _call_name(call: ast.Call, mod: ModuleInfo) -> str | None:
    """Dotted (via imports) or tail name of a call's callee."""
    dotted = mod.dotted(call.func)
    if dotted:
        return dotted
    return _receiver_tail(call.func)


class _Analysis:
    """The cached whole-program pass (one per `Project`)."""

    def __init__(self, project: Project):
        self.project = project
        self.mods: list[ModuleInfo] = list(project.modules)
        # thread-spawn sites: (resolved target key, role-or-None)
        self._spawn_sites: list[tuple[tuple, str | None]] = []
        self.ann: dict[str, _Annotations] = {
            m.path: _parse_annotations(m) for m in self.mods
        }
        self.classes: dict[str, _ClassInfo] = {}
        self.dup_classes: set[str] = set()
        self.subclasses: dict[str, set[str]] = {}
        # module-level lock vars: (module_name, var) -> node name
        self.module_locks: dict[tuple[str, str], str] = {}
        self.module_global_types: dict[tuple[str, str], str] = {}
        # factory-name mismatches: (mod, line, msg)
        self.name_mismatches: list[tuple[ModuleInfo, int, str]] = []
        # fn key -> list of events; key forms:
        #   ("M", class_name, method)   ("F", dotted_module_fn)
        self.events: dict[tuple, list[tuple]] = {}
        self.fn_mod: dict[tuple, ModuleInfo] = {}
        self.fn_def: dict[tuple, ast.AST] = {}
        self.direct_acquires: dict[tuple, set[str]] = {}
        self.callees: dict[tuple, set[tuple]] = {}
        self.trans_acquires: dict[tuple, set[str]] = {}
        self.dom_held: dict[tuple, frozenset[str]] = {}
        # lock-order graph
        self.edges: dict[str, set[str]] = {}
        self.edge_sites: dict[tuple[str, str], tuple[str, int]] = {}
        self.declared_edges: set[tuple[str, str]] = set()
        self.cycles: list[list[str]] = []
        # thread roles
        self.class_roles: dict[str, set[str]] = {}
        # findings / diags, grouped by module path
        self.findings: dict[str, list[Diagnostic]] = {}
        self.annot_diags: dict[str, list[Diagnostic]] = {}
        self.cycle_diags: dict[str, list[Diagnostic]] = {}

        self._collect_classes()
        self._collect_module_globals()
        self._walk_all_functions()
        self._infer_dominated_methods()
        self._summarize_acquires()
        self._build_edges()
        self._find_cycles()
        self._infer_roles()
        self._guarded_by_findings()
        self._annotation_diags()

    # ------------------------------------------------------- class table

    def _collect_classes(self):
        for mod in self.mods:
            for node in mod.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                if node.name in self.classes:
                    self.dup_classes.add(node.name)
                    continue
                methods = {
                    n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                }
                bases = [b for b in
                         (_receiver_tail(x) for x in node.bases) if b]
                self.classes[node.name] = _ClassInfo(
                    mod, node, node.name, bases, methods, {}, {})
        for ci in self.classes.values():
            for b in ci.bases:
                self.subclasses.setdefault(b, set()).add(ci.name)
        for ci in self.classes.values():
            self._collect_class_attrs(ci)

    def _all_subclasses(self, name: str) -> set[str]:
        out, frontier = set(), [name]
        while frontier:
            n = frontier.pop()
            for s in self.subclasses.get(n, ()):
                if s not in out:
                    out.add(s)
                    frontier.append(s)
        return out

    def _lock_ctor_kind(self, call: ast.Call,
                        mod: ModuleInfo) -> str | None:
        name = _call_name(call, mod)
        if not name:
            return None
        tail = name.split(".")[-1]
        if tail in _THREADING_LOCKS and (
            "." not in name or name.startswith("threading.")
        ):
            return tail
        if tail in _LOCK_FACTORIES:
            return tail
        return None

    def _collect_class_attrs(self, ci: _ClassInfo):
        # two passes: Condition(self._lock) aliases may reference an
        # attr assigned on a later line
        targets: list[tuple[str, ast.Call, int]] = []
        # one walk of the class node covers class-level assignments
        # AND method bodies (walking methods separately would collect
        # every method-body assignment twice)
        for scope in [ci.node.body]:
            for stmt in ast.walk(ast.Module(body=scope,
                                            type_ignores=[])):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                tgts = (stmt.targets if isinstance(stmt, ast.Assign)
                        else [stmt.target])
                val = stmt.value
                for t in tgts:
                    attr = _self_attr(t)
                    if attr is None and isinstance(t, ast.Name):
                        attr = t.id  # class-level assignment
                    if attr is None or val is None:
                        continue
                    if isinstance(val, ast.Call):
                        kind = self._lock_ctor_kind(val, ci.mod)
                        if kind:
                            targets.append((attr, val, stmt.lineno))
                            continue
                        tname = self._call_type(ci.mod, ci, val, {})
                        if tname:
                            ci.attr_types.setdefault(attr, tname)
        for attr, call, lineno in targets:
            ci.lock_attrs.setdefault(
                attr, f"{ci.name}.{attr}")
        # alias + factory-name checks need lock_attrs complete
        for attr, call, lineno in targets:
            kind = self._lock_ctor_kind(call, ci.mod)
            alias_of = None
            if kind in ("Condition", "make_condition"):
                lock_arg = None
                if kind == "Condition" and call.args:
                    lock_arg = call.args[0]
                for kw in call.keywords:
                    if kw.arg == "lock":
                        lock_arg = kw.value
                la = _self_attr(lock_arg) if lock_arg is not None \
                    else None
                if la and la in ci.lock_attrs:
                    alias_of = ci.lock_attrs[la]
            if alias_of:
                ci.lock_attrs[attr] = alias_of
            if kind in _LOCK_FACTORIES:
                want = ci.lock_attrs[attr]
                got = (call.args[0].value
                       if call.args
                       and isinstance(call.args[0], ast.Constant)
                       else None)
                if got != want:
                    self.name_mismatches.append((
                        ci.mod, lineno,
                        f"{kind}({got!r}) assigned to "
                        f"{ci.name}.{attr}: the lock name must be "
                        f"{want!r} to match the static lock-order "
                        f"graph node",
                    ))

    def _collect_module_globals(self):
        for mod in self.mods:
            tail = mod.module_name.split(".")[-1]
            for stmt in mod.tree.body:
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                tgts = (stmt.targets if isinstance(stmt, ast.Assign)
                        else [stmt.target])
                val = stmt.value
                if val is None or not isinstance(val, ast.Call):
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                            stmt.target, ast.Name):
                        t = _ann_tail(stmt.annotation)
                        if t in self.classes:
                            self.module_global_types[
                                (mod.module_name, stmt.target.id)] = t
                    continue
                for t in tgts:
                    if not isinstance(t, ast.Name):
                        continue
                    kind = self._lock_ctor_kind(val, mod)
                    if kind:
                        node = f"{tail}.{t.id}"
                        self.module_locks[
                            (mod.module_name, t.id)] = node
                        if kind in _LOCK_FACTORIES:
                            got = (val.args[0].value
                                   if val.args and isinstance(
                                       val.args[0], ast.Constant)
                                   else None)
                            if got != node:
                                self.name_mismatches.append((
                                    mod, stmt.lineno,
                                    f"{kind}({got!r}) assigned to "
                                    f"module var {t.id}: name must "
                                    f"be {node!r}"))
                    else:
                        tname = self._call_type(mod, None, val, {})
                        if tname:
                            self.module_global_types.setdefault(
                                (mod.module_name, t.id), tname)

    # ---------------------------------------------------- type inference

    def _resolve_def(self, mod: ModuleInfo,
                     call: ast.Call) -> ast.AST | None:
        """A call's target def when it is a plain module function
        (local or imported project symbol)."""
        f = call.func
        if isinstance(f, ast.Name) and f.id in mod.top_defs:
            return mod.top_defs[f.id]
        dotted = mod.dotted(f)
        if dotted and dotted in self.project.symbols:
            _, node = self.project.symbols[dotted]
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                return node
        return None

    def _call_type(self, mod: ModuleInfo, ci: _ClassInfo | None,
                   call: ast.Call, local_types: dict) -> str | None:
        """Class name a call evaluates to: a constructor, or a
        function/method with a class-valued return annotation."""
        f = call.func
        tail = _receiver_tail(f)
        if tail in self.classes and tail not in self.dup_classes:
            if isinstance(f, ast.Name) or (
                isinstance(f, ast.Attribute)
                and not isinstance(f.value, ast.Call)
            ):
                return tail
        d = self._resolve_def(mod, call)
        if d is not None:
            t = _ann_tail(d.returns)
            if t in self.classes:
                return t
        # method call with annotated return: type the receiver first
        if isinstance(f, ast.Attribute):
            rtype = self._type_of(mod, ci, f.value, local_types)
            if rtype:
                for key in self._method_keys(rtype, f.attr):
                    mdef = self.classes[key[1]].methods[key[2]]
                    t = _ann_tail(mdef.returns)
                    if t in self.classes:
                        return t
        return None

    def _type_of(self, mod: ModuleInfo, ci: _ClassInfo | None,
                 expr: ast.AST, local_types: dict) -> str | None:
        if isinstance(expr, ast.Name):
            if expr.id in local_types:
                return local_types[expr.id]
            if expr.id == "self" and ci is not None:
                return ci.name
            return self.module_global_types.get(
                (mod.module_name, expr.id))
        attr = _self_attr(expr)
        if attr and ci is not None:
            return ci.attr_types.get(attr)
        if isinstance(expr, ast.Call):
            return self._call_type(mod, ci, expr, local_types)
        return None

    def _local_types(self, mod: ModuleInfo, ci: _ClassInfo | None,
                     fn: ast.AST) -> dict[str, str]:
        out: dict[str, str] = {}
        args = getattr(fn, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                t = _ann_tail(a.annotation)
                if t in self.classes:
                    out[a.arg] = t
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._type_of(mod, ci, node.value, out)
                if t:
                    out[node.targets[0].id] = t
        return out

    # ------------------------------------------------------ call targets

    def _method_keys(self, cls_name: str,
                     mname: str) -> list[tuple]:
        """Resolved method keys for `obj.m()` where type(obj) is
        `cls_name`: the inherited definition plus every subclass
        override (virtual dispatch — the acquire summary must cover
        whichever implementation runs)."""
        out: list[tuple] = []
        seen: set[str] = set()
        c: str | None = cls_name
        while c in self.classes and c not in seen:
            seen.add(c)
            if mname in self.classes[c].methods:
                out.append(("M", c, mname))
                break
            bases = self.classes[c].bases
            c = bases[0] if bases else None
        for s in self._all_subclasses(cls_name):
            if s in self.classes and mname in self.classes[s].methods:
                key = ("M", s, mname)
                if key not in out:
                    out.append(key)
        return out

    def _resolve_call(self, mod: ModuleInfo, ci: _ClassInfo | None,
                      call: ast.Call,
                      local_types: dict) -> list[tuple]:
        f = call.func
        if isinstance(f, ast.Attribute):
            rtype = self._type_of(mod, ci, f.value, local_types)
            if rtype:
                return self._method_keys(rtype, f.attr)
            return []
        if isinstance(f, ast.Name):
            if f.id in mod.top_defs:
                return [("F", f"{mod.module_name}.{f.id}")]
            dotted = mod.dotted(f)
            if dotted and dotted in self.project.symbols:
                sm, node = self.project.symbols[dotted]
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    return [("F", dotted)]
        return []

    def _lock_node_of(self, mod: ModuleInfo, ci: _ClassInfo | None,
                      expr: ast.AST, local_types: dict) -> str | None:
        attr = _self_attr(expr)
        if attr and ci is not None and attr in ci.lock_attrs:
            return ci.lock_attrs[attr]
        if isinstance(expr, ast.Attribute):
            rtype = self._type_of(mod, ci, expr.value, local_types)
            if rtype in self.classes:
                return self.classes[rtype].lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            node = self.module_locks.get((mod.module_name, expr.id))
            if node:
                return node
            t = local_types.get(expr.id)
            # `lk = self._lock` style aliases are not tracked; a lock
            # attr typed as its own class never occurs
        return None

    # ----------------------------------------------------- event walker

    def _walk_all_functions(self):
        for mod in self.mods:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    key = ("F", f"{mod.module_name}.{node.name}")
                    self._walk_fn(mod, None, key, node)
                elif isinstance(node, ast.ClassDef) and \
                        node.name in self.classes and \
                        node.name not in self.dup_classes:
                    ci = self.classes[node.name]
                    for mname, mdef in ci.methods.items():
                        self._walk_fn(mod, ci, ("M", ci.name, mname),
                                      mdef)

    def _walk_fn(self, mod: ModuleInfo, ci: _ClassInfo | None,
                 key: tuple, fn: ast.AST):
        ann = self.ann[mod.path]
        local_types = self._local_types(mod, ci, fn)
        initial: list[str] = []
        if ci is not None and _is_locked_method(fn) and \
                "_lock" in ci.lock_attrs:
            initial.append(ci.lock_attrs["_lock"])
        g = ann.guarded_by.get(fn.lineno) or \
            ann.guarded_by.get(fn.lineno - 1)
        if g is None:
            for dec in getattr(fn, "decorator_list", []):
                g = ann.guarded_by.get(dec.lineno) or g
        if g and ci is not None and g in ci.lock_attrs:
            initial.append(ci.lock_attrs[g])
        events: list[tuple] = []

        def visit(node: ast.AST, held: tuple[str, ...]):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cur = held
                for item in node.items:
                    ln = self._lock_node_of(mod, ci, item.context_expr,
                                            local_types)
                    if ln is not None:
                        events.append(("acq", ln, cur,
                                       item.context_expr.lineno,
                                       item.context_expr.col_offset))
                        if ln not in cur:
                            cur = cur + (ln,)
                    else:
                        visit(item.context_expr, cur)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, cur)
                for stmt in node.body:
                    visit(stmt, cur)
                return
            if isinstance(node, ast.Call):
                keys = self._resolve_call(mod, ci, node, local_types)
                if keys:
                    events.append(("call", tuple(keys), held,
                                   node.lineno, node.col_offset))
                # detect thread spawns for the role oracle
                cname = _call_name(node, mod) or ""
                if cname.split(".")[-1] == "Thread":
                    self._note_spawn(mod, ci, node, local_types)
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None and ci is not None:
                    store = self._is_store(mod, node)
                    events.append(("access", attr, store, held,
                                   node.lineno, node.col_offset))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                # a nested def's body does not run at definition point;
                # analyzed separately only if it is a top-level symbol
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, tuple(initial))
        self.events[key] = events
        self.fn_mod[key] = mod
        self.fn_def[key] = fn

    def _is_store(self, mod: ModuleInfo, node: ast.Attribute) -> bool:
        if isinstance(node.ctx, ast.Store):
            return True
        parent = mod.parent(node)
        if (isinstance(parent, ast.Subscript) and parent.value is node
                and isinstance(parent.ctx, ast.Store)):
            return True
        if (isinstance(parent, ast.Attribute)
                and parent.value is node
                and isinstance(parent.ctx, ast.Store)):
            return True
        # mutator method call: self.x.append(...) mutates self.x
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and parent.attr in _MUTATORS):
            gp = mod.parent(parent)
            if isinstance(gp, ast.Call) and gp.func is parent:
                return True
        return False

    # ------------------------------------------------------ thread roles

    def _note_spawn(self, mod: ModuleInfo, ci: _ClassInfo | None,
                    call: ast.Call, local_types: dict):
        if ci is not None:
            ci.spawns_thread = True
        target_keys: list[tuple] = []
        tname: str | None = None
        for kw in call.keywords:
            if kw.arg == "target":
                v = kw.value
                attr = _self_attr(v)
                if attr and ci is not None and attr in ci.methods:
                    target_keys = [("M", ci.name, attr)]
                elif isinstance(v, ast.Attribute):
                    rtype = self._type_of(mod, ci, v.value, local_types)
                    if rtype:
                        target_keys = self._method_keys(rtype, v.attr)
                elif isinstance(v, ast.Name):
                    if v.id in mod.top_defs:
                        target_keys = [
                            ("F", f"{mod.module_name}.{v.id}")]
            elif kw.arg == "name":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, str):
                    tname = v.value
                elif isinstance(v, ast.JoinedStr) and v.values and \
                        isinstance(v.values[0], ast.Constant):
                    tname = str(v.values[0].value)
        role = None
        if tname:
            for prefix, r in ROLE_PREFIXES:
                if tname.startswith(prefix):
                    role = r
                    break
        for k in target_keys:
            self._spawn_sites.append((k, role))

    def _infer_roles(self):
        reached: dict[tuple, set[str]] = {}
        frontier = []
        for key, role in self._spawn_sites:
            r = role or "worker"
            if r not in reached.setdefault(key, set()):
                reached[key].add(r)
                frontier.append((key, r))
        while frontier:
            key, r = frontier.pop()
            for callee in self.callees.get(key, ()):
                if r not in reached.setdefault(callee, set()):
                    reached[callee].add(r)
                    frontier.append((callee, r))
        for key, roles in reached.items():
            if key[0] == "M":
                self.class_roles.setdefault(key[1], set()).update(roles)

    def _class_is_shared(self, ci: _ClassInfo) -> bool:
        """Spawns a thread itself, or a worker role reaches one of its
        methods (the main thread reaches everything, so one worker
        role means two roles can interleave on the instance)."""
        return ci.spawns_thread or bool(self.class_roles.get(ci.name))

    # ------------------------------------------------- dominance fixpoint

    def _non_call_refs(self) -> set[str]:
        """Method names referenced as bare attributes (callbacks,
        thread targets): their bodies can run from anywhere, so
        call-site lock dominance must not apply to them."""
        out: set[str] = set()
        for mod in self.mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                parent = mod.parent(node)
                if isinstance(parent, ast.Call) and \
                        parent.func is node:
                    continue
                out.add(node.attr)
        return out

    def _infer_dominated_methods(self):
        """A private method whose EVERY resolved call site runs with
        lock L held is itself an L region (`frontend._store_replica`
        idiom: helpers factored out of a critical section)."""
        escaped = self._non_call_refs()
        # call sites per method key: (caller_key, held)
        sites: dict[tuple, list[tuple[tuple, tuple]]] = {}
        for caller, events in self.events.items():
            for ev in events:
                if ev[0] != "call":
                    continue
                for k in ev[1]:
                    sites.setdefault(k, []).append((caller, ev[2]))
        eligible = [
            ("M", ci.name, m)
            for ci in self.classes.values()
            for m in ci.methods
            if m.startswith("_") and not m.startswith("__")
            and m not in escaped and not _is_locked_method(
                ci.methods[m])
        ]
        changed = True
        while changed:
            changed = False
            for key in eligible:
                ss = sites.get(key)
                if not ss:
                    continue
                inter: set[str] | None = None
                for caller, held in ss:
                    h = set(held) | set(self.dom_held.get(
                        caller, frozenset()))
                    inter = h if inter is None else (inter & h)
                new = frozenset(inter or ())
                if new != self.dom_held.get(key, frozenset()):
                    self.dom_held[key] = new
                    changed = True

    def _held_at(self, key: tuple, held: tuple[str, ...]) -> set[str]:
        return set(held) | set(self.dom_held.get(key, frozenset()))

    # ------------------------------------------------------- lock order

    def _summarize_acquires(self):
        for key, events in self.events.items():
            acq, callees = set(), set()
            for ev in events:
                if ev[0] == "acq":
                    acq.add(ev[1])
                elif ev[0] == "call":
                    callees.update(ev[1])
            self.direct_acquires[key] = acq
            self.callees[key] = callees
        self.trans_acquires = {
            k: set(v) for k, v in self.direct_acquires.items()
        }
        changed = True
        while changed:
            changed = False
            for key, cs in self.callees.items():
                mine = self.trans_acquires[key]
                before = len(mine)
                for c in cs:
                    mine |= self.trans_acquires.get(c, set())
                if len(mine) != before:
                    changed = True

    def _add_edge(self, a: str, b: str, path: str, line: int):
        if a == b:
            return
        if b not in self.edges.setdefault(a, set()):
            self.edges[a].add(b)
            self.edge_sites[(a, b)] = (path, line)

    def _build_edges(self):
        for key, events in self.events.items():
            mod = self.fn_mod[key]
            for ev in events:
                if ev[0] == "acq":
                    _, node, held, line, _col = ev
                    for h in self._held_at(key, held):
                        self._add_edge(h, node, mod.path, line)
                elif ev[0] == "call":
                    _, keys, held, line, _col = ev
                    hset = self._held_at(key, held)
                    if not hset:
                        continue
                    targets: set[str] = set()
                    for k in keys:
                        targets |= self.trans_acquires.get(k, set())
                    for h in hset:
                        for t in targets:
                            self._add_edge(h, t, mod.path, line)
        for mod in self.mods:
            for line, a, b in self.ann[mod.path].lock_order:
                self.declared_edges.add((a, b))
                self._add_edge(a, b, mod.path, line)

    def _find_cycles(self):
        # Tarjan SCC, iterative
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on: set[str] = set()
        stack: list[str] = []
        counter = [0]
        sccs: list[list[str]] = []
        nodes = set(self.edges) | {
            b for bs in self.edges.values() for b in bs}

        for root in sorted(nodes):
            if root in index:
                continue
            work = [(root, iter(sorted(self.edges.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append(
                            (w, iter(sorted(self.edges.get(w, ())))))
                        advanced = True
                        break
                    elif w in on:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))
        for comp in sccs:
            cyc = self._cycle_path(comp)
            self.cycles.append(cyc)
            # anchor the diagnostic at the first edge of the cycle
            # that has a known site
            site = None
            for a, b in zip(cyc, cyc[1:] + cyc[:1]):
                site = self.edge_sites.get((a, b))
                if site:
                    break
            path, line = site if site else (self.mods[0].path, 1)
            self.cycle_diags.setdefault(path, []).append(Diagnostic(
                path=path, line=line, col=1,
                rule_id="nrcheck-lock-order",
                severity=RULES["nrcheck-lock-order"].severity,
                message=(
                    "lock-order cycle (potential deadlock): "
                    + " -> ".join(cyc + [cyc[0]])
                    + " — break the cycle or restructure so one "
                      "order is global"
                ),
            ))

    def _cycle_path(self, comp: list[str]) -> list[str]:
        comp_set = set(comp)
        start = comp[0]
        # BFS back to start constrained to the SCC
        prev: dict[str, str] = {}
        frontier = [start]
        seen = {start}
        while frontier:
            nxt = []
            for n in frontier:
                for m in sorted(self.edges.get(n, ())):
                    if m == start:
                        path = [n]
                        while path[-1] != start:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    if m in comp_set and m not in seen:
                        seen.add(m)
                        prev[m] = n
                        nxt.append(m)
            frontier = nxt
        return comp

    # ------------------------------------------------ guarded-by findings

    def _guarded_by_findings(self):
        for ci in self.classes.values():
            if not ci.lock_attrs or not self._class_is_shared(ci):
                continue
            ann = self.ann[ci.mod.path]
            # per-attr access sites across the class's methods
            stores: dict[str, list[tuple]] = {}
            reads: dict[str, list[tuple]] = {}
            unshared_attrs: set[str] = set()
            for mname, mdef in ci.methods.items():
                key = ("M", ci.name, mname)
                for ev in self.events.get(key, ()):
                    if ev[0] != "access":
                        continue
                    _, attr, is_store, held, line, col = ev
                    if attr in ci.lock_attrs:
                        continue
                    if mname == "__init__":
                        if is_store and _annotated(ann, line,
                                                   unshared=True):
                            unshared_attrs.add(attr)
                        continue
                    h = self._held_at(key, held)
                    site = (mname, h, line, col)
                    (stores if is_store else reads).setdefault(
                        attr, []).append(site)
            roles = sorted(self.class_roles.get(ci.name, set()))
            role_note = (
                f" (reached by thread role(s): {', '.join(roles)})"
                if roles else ""
            )
            for attr, ss in sorted(stores.items()):
                if attr in unshared_attrs:
                    continue
                union_g: set[str] = set()
                inter_g: set[str] | None = None
                for _m, h, _l, _c in ss:
                    union_g |= h
                    inter_g = set(h) if inter_g is None else (
                        inter_g & h)
                if not union_g:
                    continue  # never written under any lock: unshared
                              # by inference (single-writer / config)
                own = {n for n in (inter_g or set())}
                if own:
                    lock = sorted(own)[0]
                    lock_attr = lock.split(".")[-1]
                    for _m, h, line, col in sorted(
                            reads.get(attr, []),
                            key=lambda s: (s[2], s[3])):
                        if h & own:
                            continue
                        if _annotated(ann, line, unshared=True) or \
                                _annotated(ann, line,
                                           guarded=lock_attr):
                            continue
                        self.findings.setdefault(
                            ci.mod.path, []).append(Diagnostic(
                                path=ci.mod.path, line=line, col=col+1,
                                rule_id="nrcheck-guarded-by",
                                severity=RULES[
                                    "nrcheck-guarded-by"].severity,
                                message=(
                                    f"{ci.name}.{attr} is guarded by "
                                    f"{lock} (every store holds it) "
                                    f"but this read runs outside the "
                                    f"lock{role_note} — take the "
                                    f"lock, or annotate `# nrcheck: "
                                    f"unshared — why` / `# guarded-"
                                    f"by: {lock_attr}`"
                                ),
                            ))
                else:
                    # mixed discipline: stores both inside and outside
                    for _m, h, line, col in sorted(
                            ss, key=lambda s: (s[2], s[3])):
                        if h:
                            continue
                        if _annotated(ann, line, unshared=True):
                            continue
                        locks = ", ".join(sorted(union_g))
                        self.findings.setdefault(
                            ci.mod.path, []).append(Diagnostic(
                                path=ci.mod.path, line=line, col=col+1,
                                rule_id="nrcheck-guarded-by",
                                severity=RULES[
                                    "nrcheck-guarded-by"].severity,
                                message=(
                                    f"{ci.name}.{attr} is written "
                                    f"under {locks} elsewhere but "
                                    f"written here with no lock held"
                                    f"{role_note} — inconsistent "
                                    f"guard discipline"
                                ),
                            ))

    # -------------------------------------------------- annotation diags

    def _annotation_diags(self):
        for mod in self.mods:
            ann = self.ann[mod.path]
            out = self.annot_diags.setdefault(mod.path, [])
            for line, msg in ann.malformed:
                out.append(Diagnostic(
                    path=mod.path, line=line, col=1,
                    rule_id="nrcheck-annotation",
                    severity=RULES["nrcheck-annotation"].severity,
                    message=msg))
        for mod, line, msg in self.name_mismatches:
            self.annot_diags.setdefault(mod.path, []).append(
                Diagnostic(
                    path=mod.path, line=line, col=1,
                    rule_id="nrcheck-annotation",
                    severity=RULES["nrcheck-annotation"].severity,
                    message=msg))

    # ---------------------------------------------------------- exports

    def edge_list(self) -> list[list[str]]:
        return sorted(
            [a, b] for a, bs in self.edges.items() for b in bs)

    def graph_json(self) -> dict:
        nodes = set(self.edges) | {
            b for bs in self.edges.values() for b in bs}
        for ci in self.classes.values():
            nodes.update(ci.lock_attrs.values())
        nodes.update(self.module_locks.values())
        return {
            "nodes": sorted(nodes),
            "edges": self.edge_list(),
            "cycles": self.cycles,
        }

    def check_dynamic(self, dynamic_edges) -> list[str]:
        """Violations in a runtime lockgraph dump: every observed edge
        must already be in the static graph."""
        static = {(a, b) for a, bs in self.edges.items() for b in bs}
        out = []
        for e in dynamic_edges:
            a, b = e[0], e[1]
            if (a, b) not in static:
                out.append(
                    f"dynamic lock-order edge {a} -> {b} is missing "
                    f"from the static graph (the analyzer cannot see "
                    f"this nesting — fix the resolver or declare "
                    f"`# nrcheck: lock-order {a} -> {b} — why`)")
        return out


def analyze(project: Project) -> _Analysis:
    """The cached whole-program pass for `project`."""
    cached = getattr(project, "_nrcheck_analysis", None)
    if cached is None:
        cached = _Analysis(project)
        project._nrcheck_analysis = cached
    return cached


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------


@rule(
    "nrcheck-guarded-by", ERROR,
    "shared attribute accessed outside its inferred guarding lock",
)
def nrcheck_guarded_by(mod: ModuleInfo,
                       project: Project) -> Iterator[Diagnostic]:
    """Whole-program guarded-by inference (module docstring): in every
    thread-shared class, an attribute whose stores all hold lock L is
    guarded by L; reads outside L (and stores outside any lock for
    mixed-discipline attributes) are flagged. `# guarded-by:` /
    `# nrcheck: unshared` annotations are the reviewed escape hatch."""
    yield from analyze(project).findings.get(mod.path, [])


@rule(
    "nrcheck-lock-order", ERROR,
    "cycle in the global lock-order graph (potential deadlock)",
)
def nrcheck_lock_order(mod: ModuleInfo,
                       project: Project) -> Iterator[Diagnostic]:
    """Nested acquisitions — direct `with` nesting and nestings
    reached through resolved calls — form the global lock-order
    graph; a cycle means two threads can deadlock under some
    schedule. The runtime twin (`analysis/locks.py`) fails fast on
    the same condition dynamically."""
    yield from analyze(project).cycle_diags.get(mod.path, [])


@rule(
    "nrcheck-annotation", WARNING,
    "malformed nrcheck annotation or lock-factory name drift",
)
def nrcheck_annotation(mod: ModuleInfo,
                       project: Project) -> Iterator[Diagnostic]:
    """A typo'd `# nrcheck:` / `# guarded-by:` comment silently
    disarms the analysis, and a `make_lock` name that drifts from the
    static node name silently disarms the dynamic-vs-static subgraph
    gate — both are diagnosed."""
    yield from analyze(project).annot_diags.get(mod.path, [])


@rule(
    "condition-wait-without-predicate-loop", WARNING,
    "bare no-timeout condition wait outside a while loop",
)
def condition_wait_without_predicate_loop(
        mod: ModuleInfo, project: Project) -> Iterator[Diagnostic]:
    """`Condition.wait()` can wake spuriously; without a timeout the
    ONLY correct shape is `while not predicate: cond.wait()` — a bare
    `if`-guarded or unguarded wait can hang or proceed on a stale
    predicate. Timed waits (`wait(t)` / `clock.wait(cond, t)`) are a
    pacing idiom and exempt (the caller re-checks on a schedule)."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "wait"):
            continue
        if node.keywords:
            continue
        recv_tail = _receiver_tail(f.value) or ""
        condish = "cond" in recv_tail.lower()
        if len(node.args) == 0 and condish:
            pass  # bare cond.wait()
        elif len(node.args) == 1 and not condish and \
                "cond" in (_receiver_tail(node.args[0]) or "").lower():
            pass  # clock.wait(cond) with no timeout
        else:
            continue
        cur = mod.parent(node)
        in_while = False
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                      ast.Lambda)):
            if isinstance(cur, ast.While):
                in_while = True
                break
            cur = mod.parent(cur)
        if not in_while:
            yield _diag(
                mod, node, "condition-wait-without-predicate-loop",
                "no-timeout condition wait outside a `while "
                "predicate` loop: a spurious wakeup (or a missed "
                "notify before the wait) hangs or proceeds on a "
                "stale predicate — wrap in `while not <predicate>:`",
            )


_BLOCKING_METHOD_TAILS = {
    "sendall": "socket send",
    "sendto": "socket send",
    "recv": "socket receive",
    "recvfrom": "socket receive",
    "recv_into": "socket receive",
    "accept": "socket accept",
    "block_until_ready": "device sync",
    "result": "future wait",
}
_BLOCKING_FUNC_DOTTED = {
    "os.fsync": "fsync",
    "jax.block_until_ready": "device sync",
}


@rule(
    "lock-held-across-blocking-call", WARNING,
    "blocking I/O or device sync under a held subsystem lock",
)
def lock_held_across_blocking_call(
        mod: ModuleInfo, project: Project) -> Iterator[Diagnostic]:
    """A socket round-trip, an fsync, a `block_until_ready`, or a
    future wait under a held lock stalls every thread queued on that
    lock for the full I/O latency (and a future wait can deadlock
    outright if resolving it needs the same lock). Hoist the blocking
    call out of the critical section; the WAL's group-commit fsync is
    the one sanctioned exception and carries a justified
    suppression."""
    lockish = re.compile(r"(_lock|_cond|_mu)$")

    def lock_regions(fn):
        regions = []
        if _is_locked_method(fn):
            regions.append(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr and lockish.search(attr):
                        regions.append(node)
        return regions

    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        regions = lock_regions(fn)
        if not regions:
            continue
        region_ids = {id(r) for r in regions}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            what = None
            f = node.func
            dotted = mod.dotted(f)
            if dotted in _BLOCKING_FUNC_DOTTED:
                what = _BLOCKING_FUNC_DOTTED[dotted]
            elif isinstance(f, ast.Attribute) and \
                    f.attr in _BLOCKING_METHOD_TAILS:
                # skip module-qualified calls (`sqlite3.connect`
                # style): only instance methods block a held lock
                if not (isinstance(f.value, ast.Name)
                        and f.value.id in mod.imports):
                    what = _BLOCKING_METHOD_TAILS[f.attr]
            if what is None:
                continue
            cur = mod.parent(node)
            inside = _is_locked_method(fn)
            while cur is not None and cur is not fn:
                if id(cur) in region_ids:
                    inside = True
                    break
                cur = mod.parent(cur)
            if inside:
                yield _diag(
                    mod, node, "lock-held-across-blocking-call",
                    f"{what} ({ast.unparse(f)}) inside a held-lock "
                    f"region: every thread queued on the lock stalls "
                    f"for the full call — hoist it out of the "
                    f"critical section",
                )
