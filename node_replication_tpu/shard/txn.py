"""Atomic cross-shard transactions: presumed-abort 2PC over the
sharded fleet.

`ShardRouter.execute_batch` keeps CNR's multi-log contract — per-shard
sub-batches commit independently, no cross-shard rollback. This module
adds the missing guarantee ON TOP, composing mechanisms the repo
already trusts into two-phase commit:

- **prepare** (`TxnParticipant.prepare`): the participant fences the
  caller's map version and every op's congruence class (the
  `LocalBackend` door checks), refuses keys locked by OTHER prepared
  transactions (`TxnConflict` — a prepared intent blocks conflicting
  KEYS, not the shard), then journals the sub-batch as a CRC-framed
  intent record (`durable/txnlog.py:TxnIntentLog`) and fsyncs it.
  Returning from the fsync IS the yes-vote — the `maybe_executed`
  honesty shape: once voted, the participant can crash and still
  re-derive exactly what it promised.
- **decide** (`TxnCoordinator`): all-yes ⇒ the coordinator durably
  publishes the commit decision (`DecisionLog.publish`, atomic tmp +
  fsync + rename) BEFORE any caller-visible result resolves — the 2PC
  twin of `durability="batch"`'s fsync-before-ack (nrlint rule
  `txn-ack-before-decision` machine-checks the dominance). Any no-vote
  ⇒ publish abort (an accelerator only: ABSENCE of a decision for a
  dead coordinator generation already means abort) and roll the
  prepared participants back.
- **commit/abort** (phase 2): version-fenced verbs on
  `LocalBackend`/`SocketShardClient`/`ShardServer`. Commit journals
  `commit-begin` with the shard WAL tail, applies the intent through
  the shard's own durable frontend (fsync-before-ack acks), then
  journals `resolved` and releases the locks. Both verbs are
  idempotent across re-drives and restarts (the intent log retains
  resolved outcomes).

**Recovery** is decision-lookup, not dialogue:

- A restarted participant reloads unresolved intents (locks rebuilt),
  and `resolve_in_doubt` consults the decision store: a commit
  decision re-applies the intent — deduplicated by scanning the shard
  WAL from the journaled `commit-begin` position, so a crash between
  apply and resolve can never double-apply; an abort decision (or NO
  decision from a coordinator generation older than the current
  epoch) drops it. An undecided intent from the LIVE generation stays
  in doubt, its keys stay locked.
- A restarted coordinator bumps the durable generation
  (`DecisionLog.bump_epoch` — the fence that makes presumed abort
  sound) and re-drives every published commit decision
  (`TxnCoordinator.recover`); participants it cannot reach re-home
  through the same published-map refresh the router uses.

Zero cost when unused (the `obs_port=None` discipline): a
single-shard "transaction" degrades to a plain routed batch, and the
non-txn submit path's only tax is one `has_locks()` flag read.
"""

from __future__ import annotations

import itertools
import os
import threading

from concurrent.futures import Future

from node_replication_tpu.analysis.locks import make_lock
from node_replication_tpu.durable.txnlog import DecisionLog, TxnIntentLog
from node_replication_tpu.fault.inject import fault_hook
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.serve.errors import (
    FrontendClosed,
    ServeError,
    ShardUnavailable,
    TxnAborted,
    TxnConflict,
    TxnInDoubt,
    WrongShard,
)
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer


def _op_matches(stored: tuple, wanted: tuple) -> bool:
    """Does a WAL-stored op (args zero-padded to the log's arg width)
    carry the same opcode + args as an intent op?"""
    if not stored or stored[0] != wanted[0]:
        return False
    w = tuple(wanted[1:])
    s = tuple(stored[1:])
    if len(s) < len(w):
        return False
    return s[:len(w)] == w and all(x == 0 for x in s[len(w):])


class TxnParticipant:
    """One shard's 2PC participant: intent journal + key locks.

    Rides the shard's OWN serving stack: prepared intents apply
    through the shard's `ServeFrontend` (durable, ship-before-ack
    acks — a committed sub-batch survives exactly like any other
    write), and the intent journal lives next to the shard's WAL.
    Restart-safe by construction: reopening the journal rebuilds the
    locks of every prepared-but-undecided transaction.
    """

    def __init__(self, shard: int, frontend, shard_map, directory: str,
                 decisions: DecisionLog | None = None, wal=None,
                 apply_timeout_s: float = 10.0):
        self.shard = int(shard)
        self._frontend = frontend
        self._map = shard_map
        self._wal = wal
        self._decisions = decisions
        self.apply_timeout_s = float(apply_timeout_s)
        self._lock = make_lock("TxnParticipant._lock")
        path = directory
        if not path.endswith(".log"):
            path = os.path.join(directory, "txn-intents.log")
        self.log = TxnIntentLog(path)
        #: key -> holding txn id (the conflict fence). Rebuilt from
        #: the journal's unresolved intents on every (re)open.
        self._locked: dict[int, str] = {}
        for txn, info in self.log.unresolved().items():
            for op in info["ops"]:
                self._locked[int(op[1])] = txn
        reg = get_registry()
        self._m_prepared = reg.counter(f"shard.s{self.shard}.txn_prepared")
        self._m_committed = reg.counter(
            f"shard.s{self.shard}.txn_committed"
        )
        self._m_aborted = reg.counter(f"shard.s{self.shard}.txn_aborted")
        self._m_conflicts = reg.counter(
            f"shard.s{self.shard}.txn_conflicts"
        )

    # ------------------------------------------------------ wiring

    def set_frontend(self, frontend, wal=None) -> None:
        """Re-home onto a promoted/recovered frontend (+ its WAL)."""
        with self._lock:
            self._frontend = frontend
            if wal is not None:
                self._wal = wal

    def set_map(self, m) -> None:
        with self._lock:
            self._map = m

    def update_version(self, m) -> None:
        self.set_map(m)

    # ------------------------------------------------- conflict fence

    def has_locks(self) -> bool:
        """One flag read — the non-txn path's ENTIRE cost when no
        transaction is in flight (`LocalBackend.submit_batch` gates
        the per-op conflict scan on it)."""
        return bool(self._locked)

    def check_conflicts(self, ops) -> None:
        """Refuse any op on a locked key with retryable `TxnConflict`
        (zero log effect; the lock clears when the txn resolves)."""
        with self._lock:
            for op in ops:
                if len(op) < 2:
                    continue
                holder = self._locked.get(int(op[1]))
                if holder is not None:
                    self._m_conflicts.inc()
                    raise TxnConflict(int(op[1]), holder)

    # ------------------------------------------------------- phase one

    def prepare(self, txn: str, gen: int, ops, peer_version: int) -> bool:
        """Vote on the sub-batch. A True return means the yes-vote is
        DURABLE (the intent record is fsynced) and the keys are
        locked; every refusal is typed and has zero log effect."""
        with self._lock:
            m = self._map
            if peer_version != m.version:
                raise WrongShard(-1, self.shard, self.shard, m.version,
                                 peer_version=peer_version)
            ops = [tuple(op) for op in ops]
            for op in ops:
                owner = m.shard_of_op(op)
                if owner != self.shard:
                    raise WrongShard(op[1], self.shard, owner,
                                     m.version,
                                     peer_version=peer_version)
            prior = self.log.outcome(txn)
            if prior is not None:
                # a re-driven prepare after this participant already
                # resolved: commit means the work is done; abort means
                # the coordinator generation died — refuse loudly
                if prior == "commit":
                    return True
                raise TxnAborted(txn)
            if self.log.intent(txn) is not None:
                return True  # duplicate prepare: already voted yes
            for op in ops:
                holder = self._locked.get(int(op[1]))
                if holder is not None and holder != txn:
                    self._m_conflicts.inc()
                    raise TxnConflict(int(op[1]), holder)
            self.log.journal_intent(txn, gen, ops)
            for op in ops:
                self._locked[int(op[1])] = txn
            self._m_prepared.inc()
        get_tracer().emit("txn-prepare", shard=self.shard, txn=txn,
                          ops=len(ops))
        # after the durable vote, before the reply: a kill here is the
        # prepared-but-unacked participant the in-doubt story covers
        fault_hook("txn-prepare", self.shard)
        return True

    # ------------------------------------------------------- phase two

    def commit(self, txn: str, peer_version: int | None = None) -> list:
        """Apply the prepared intent; returns per-op results in intent
        order. Idempotent: a re-driven commit of a resolved txn
        returns `[]` without touching the log."""
        with self._lock:
            if peer_version is not None:
                m = self._map
                if peer_version != m.version:
                    raise WrongShard(-1, self.shard, self.shard,
                                     m.version,
                                     peer_version=peer_version)
            prior = self.log.outcome(txn)
            if prior == "commit":
                return []
            if prior == "abort":
                raise ServeError(
                    f"txn {txn} already aborted on shard "
                    f"{self.shard}; commit refused"
                )
            info = self.log.intent(txn)
            if info is None:
                raise ServeError(
                    f"txn {txn} was never prepared on shard "
                    f"{self.shard}"
                )
            # a journaled commit-begin fence means an earlier apply
            # attempt started (it may have appended ops before dying):
            # a re-driven commit — the coordinator-restart path — must
            # dedup against the WAL exactly like recovery does. Fresh
            # commits carry no fence and skip the scan.
            return self._apply_locked(
                txn, info, dedup=info.get("commit_begin") is not None)

    def abort(self, txn: str, peer_version: int | None = None) -> None:
        """Drop the intent (zero log effect) and release its locks.
        Idempotent; unknown transactions are a no-op (presumed
        abort needs no record)."""
        with self._lock:
            info = self.log.intent(txn)
            if info is None:
                return
            self.log.journal_resolved(txn, "abort")
            self._release_locked(txn, info)
            self._m_aborted.inc()
        get_tracer().emit("txn-abort", shard=self.shard, txn=txn)

    def status(self, txn: str) -> str:
        with self._lock:
            if self.log.intent(txn) is not None:
                return "prepared"
            out = self.log.outcome(txn)
            if out == "commit":
                return "committed"
            if out == "abort":
                return "aborted"
            return "unknown"

    # -------------------------------------------------------- recovery

    def resolve_in_doubt(self, decisions: DecisionLog | None = None,
                         epoch: int | None = None) -> dict[str, str]:
        """Resolve every unresolved intent by decision lookup: a
        commit decision applies it (deduplicated against the shard
        WAL), an abort decision — or NO decision from a generation
        older than `epoch` — presumed-aborts it, and an undecided
        intent from the live generation stays `"in-doubt"` with its
        keys locked. Returns txn → outcome."""
        dec = decisions or self._decisions
        if dec is None:
            raise ValueError(
                "resolve_in_doubt needs a DecisionLog (constructor "
                "`decisions=` or the `decisions` argument)"
            )
        if epoch is None:
            epoch = dec.epoch()
        out: dict[str, str] = {}
        with self._lock:
            for txn, info in list(self.log.unresolved().items()):
                outcome = dec.outcome(txn)
                if outcome == "commit":
                    self._apply_locked(txn, info, dedup=True)
                    out[txn] = "commit"
                elif outcome == "abort" or info["gen"] < epoch:
                    # explicit abort, or presumed: the coordinator
                    # generation that owned this intent is dead and
                    # never published — it can never decide commit now
                    self.log.journal_resolved(txn, "abort")
                    self._release_locked(txn, info)
                    self._m_aborted.inc()
                    out[txn] = "abort"
                else:
                    out[txn] = "in-doubt"
        if out:
            get_tracer().emit("txn-resolve", shard=self.shard,
                              resolved=out)
        return out

    # -------------------------------------------------------- internals

    def _release_locked(self, txn: str, info: dict) -> None:
        for op in info["ops"]:
            if self._locked.get(int(op[1])) == txn:
                del self._locked[int(op[1])]

    def _apply_locked(self, txn: str, info: dict, dedup: bool) -> list:
        """Apply the intent through the shard's durable frontend
        (caller holds the lock — commits are rare and the hold keeps
        the conflict fence trivially correct). `dedup=True` (recovery)
        skips ops already present in the WAL at/after the journaled
        `commit-begin` position, so a crash between apply and resolve
        never double-applies."""
        ops = [tuple(op) for op in info["ops"]]
        for op in ops:
            # re-verify the congruence at the door (the fleet-level
            # LogMapper invariant): the intent was fenced at prepare,
            # but a commit re-driven after a reshard must not apply a
            # moved key through the wrong shard's frontend
            owner = self._map.shard_of_op(op)
            if owner != self.shard:
                raise WrongShard(int(op[1]), self.shard, owner,
                                 self._map.version)
        need = [True] * len(ops)
        if info.get("commit_begin") is None:
            t0 = self._wal.tail if self._wal is not None else 0
            self.log.journal_commit_begin(txn, t0)
        elif dedup:
            need = self._missing_mask(ops, int(info["commit_begin"]))
        results: list = [None] * len(ops)
        try:
            staged = [
                (i, self._frontend.submit(ops[i]))
                for i in range(len(ops)) if need[i]
            ]
            for i, fut in staged:
                results[i] = fut.result(self.apply_timeout_s)
        except FrontendClosed as e:
            # mid-promotion/teardown: the intent survives, the locks
            # hold, and recovery (or a re-driven commit against the
            # re-homed frontend) finishes the job
            raise ShardUnavailable(self.shard, cause=e,
                                   maybe_executed=True) from e
        # between the durable acks above and the resolved record
        # below: THE mid-commit crash window the dedup scan exists for
        fault_hook("txn-commit", self.shard)
        self.log.journal_resolved(txn, "commit")
        self._release_locked(txn, info)
        self._m_committed.inc()
        get_tracer().emit("txn-commit", shard=self.shard, txn=txn,
                          ops=len(ops))
        return results

    def _missing_mask(self, ops: list, t0: int) -> list:
        """Which intent ops are NOT already applied: scan the shard
        WAL from the `commit-begin` fence, consuming one stored match
        per intent op. Sound because the keys were locked the whole
        time — no other writer can have appended an identical op in
        the window."""
        if self._wal is None:
            return [True] * len(ops)
        need = [True] * len(ops)
        start = max(int(t0), self._wal.base)
        for rec in self._wal.records(start):
            for stored in rec.ops():
                for i in range(len(ops)):
                    if need[i] and _op_matches(stored, ops[i]):
                        need[i] = False
                        break
        return need

    def close(self) -> None:
        self.log.close()


class TxnCoordinator:
    """Presumed-abort 2PC driver riding a `ShardRouter`.

        coord = TxnCoordinator(router, decision_dir)
        coord.execute_txn([(HM_PUT, k0, a), (HM_PUT, k1, b)])

    Construction durably bumps the coordinator generation
    (`DecisionLog.bump_epoch`) — the fence that lets participants
    presume abort for every undecided intent of an older generation.
    `execute_txn` is the synchronous surface; `submit_txn` returns a
    future resolved by a background drive (the decision publish
    dominates the resolve — nrlint rule `txn-ack-before-decision`).
    A restarted coordinator calls `recover()` to re-drive published
    commit decisions (idempotent at the participants).
    """

    def __init__(self, router, decision_dir: str, name: str = "coord",
                 max_rehome_attempts: int = 8,
                 rehome_backoff_s: float = 0.01):
        self.router = router
        self.name = str(name)
        self.decisions = DecisionLog(decision_dir)
        self.gen = self.decisions.bump_epoch()
        self.max_rehome_attempts = int(max_rehome_attempts)
        self.rehome_backoff_s = float(rehome_backoff_s)
        self._seq = itertools.count(1)
        self._lock = make_lock("TxnCoordinator._lock")
        reg = get_registry()
        self._m_committed = reg.counter("txn.committed")
        self._m_aborted = reg.counter("txn.aborted")
        self._m_in_doubt = reg.counter("txn.in_doubt")
        self._m_single = reg.counter("txn.single_shard")
        self._h_commit_s = reg.histogram("txn.commit_s")

    def _txn_id(self) -> str:
        with self._lock:
            return f"{self.name}.g{self.gen}.{next(self._seq)}"

    # ----------------------------------------------------------- drive

    def execute_txn(self, ops, timeout: float | None = None) -> list:
        """Atomically apply `ops` across shards; returns per-op
        results in submission order. Raises `TxnAborted` (zero log
        effect anywhere — whole-txn retry is exactly-once safe) or
        `TxnInDoubt` (decision durable, some participant unreachable —
        recovery enforces it; do not blindly retry)."""
        clock = get_clock()
        t0 = clock.now()
        ops = [tuple(op) for op in ops]
        groups = self.router.map.split_batch(ops)
        if len(groups) <= 1:
            # single-shard: the shard's own batch is already atomic
            # (one combiner round, one WAL append) — no 2PC cost
            self._m_single.inc()
            return self.router.execute_batch(ops, timeout=timeout)
        txn = self._txn_id()
        shards = sorted(groups)
        sub = {s: [op for _i, op in groups[s]] for s in shards}
        prepared: list[int] = []
        try:
            for s in shards:
                self._verb_rehomed(s, "prepare", txn, ops=sub[s],
                                   timeout=timeout)
                prepared.append(s)
                # coordinator-side crash window: some participants
                # prepared, no decision — presumed abort must clean up
                fault_hook("txn-prepare", s)
        except ServeError as e:
            # publish the abort as an ACCELERATOR (absence already
            # means abort once this generation dies), then roll back
            # the prepared participants best-effort
            self.decisions.publish(txn, "abort", shards=shards)
            for s in prepared:
                try:
                    self._verb_rehomed(s, "abort", txn, timeout=timeout)
                except ServeError:
                    pass  # presumed abort resolves it later
            self._m_aborted.inc()
            raise TxnAborted(txn, cause=e) from e
        # THE commit point: durable decision BEFORE anything resolves
        self.decisions.publish(txn, "commit", shards=shards)
        fault_hook("txn-decide", -1)
        out = self._commit_all(txn, groups, sub, timeout)
        self._m_committed.inc()
        self._h_commit_s.observe(clock.now() - t0)
        return out

    def submit_txn(self, ops) -> Future:
        """Asynchronous surface: a `Future` resolved after the durable
        decision + phase 2 (failures surface as `TxnAborted` /
        `TxnInDoubt` on the future)."""
        fut: Future = Future()
        t = threading.Thread(target=self._run_txn, args=(list(ops), fut),
                             name=f"txn-coord-{self.name}", daemon=True)
        t.start()
        return fut

    def _run_txn(self, ops, fut: Future) -> None:
        # `execute_txn` publishes the durable decision before
        # returning, so the resolve below is decision-dominated
        try:
            result = self.execute_txn(ops)
        except BaseException as e:
            fut.set_exception(e)
            return
        fut.set_result(result)

    # -------------------------------------------------------- recovery

    def recover(self, timeout: float | None = None) -> dict:
        """Coordinator-restart re-drive: every published COMMIT
        decision is re-sent to its participants (idempotent — a
        participant that already resolved returns immediately).
        Construction already bumped the generation, so every
        undecided intent of the dead generations presumed-aborts at
        the participants' own `resolve_in_doubt`."""
        redriven = failed = 0
        for d in self.decisions.decisions():
            if d.get("outcome") != "commit":
                continue
            for s in d.get("shards", ()):
                try:
                    self._verb_rehomed(int(s), "commit", d["txn"],
                                       timeout=timeout)
                    redriven += 1
                except ServeError:
                    failed += 1
        report = {"gen": self.gen, "redriven": redriven,
                  "failed": failed}
        get_tracer().emit("txn-recover", **report)
        return report

    # -------------------------------------------------------- internals

    def _commit_all(self, txn: str, groups: dict, sub: dict,
                    timeout) -> list:
        total = sum(len(g) for g in groups.values())
        out: list = [None] * total
        for s in sorted(groups):
            try:
                vals = self._verb_rehomed(s, "commit", txn,
                                          timeout=timeout)
            except ServeError as e:
                self._m_in_doubt.inc()
                raise TxnInDoubt(txn, decision="commit",
                                 cause=e) from e
            if vals == [] and len(groups[s]) > 0:
                # idempotent replay of an already-resolved commit:
                # results were delivered (and lost) once; slots stay
                # None — the WRITES are guaranteed, the values gone
                continue
            for (idx, _op), v in zip(groups[s], vals):
                out[idx] = v
        return out

    def _verb_rehomed(self, shard: int, verb: str, txn: str,
                      ops=None, timeout=None):
        """One txn verb with the `call_with_retry` re-homing story:
        `WrongShard` refreshes the published map and retries (the
        participant fenced a stale version); a retryable
        `ShardUnavailable` backs off and retries for the IDEMPOTENT
        verbs (commit/abort), but fails prepare fast — an unreachable
        participant cannot vote, and presumed abort is the cheap
        outcome."""
        clock = get_clock()
        last: ServeError | None = None
        for attempt in range(self.max_rehome_attempts):
            try:
                return self.router.txn_call(shard, verb, txn, self.gen,
                                            ops=ops, timeout=timeout)
            except WrongShard as e:
                last = e
                self.router.refresh_map()
            except ShardUnavailable as e:
                last = e
                if verb == "prepare":
                    # an unreachable (or sent-but-unanswered)
                    # participant cannot be counted as a yes-vote;
                    # fail fast — execute_txn publishes the abort, so
                    # even a vote that WAS durably journaled before
                    # the failure resolves by decision lookup
                    raise
                self.router.refresh_map()
                clock.sleep(self.rehome_backoff_s * (attempt + 1))
        raise last
