"""`ShardPrimary` / `ShardGroup`: N primaries, each owning a keyspace
slice with its OWN replication stack.

The fleet-level lift of CNR's per-log partitioning: where
`MultiLogReplicated` gives each congruence class its own in-process
log, a `ShardPrimary` gives it a whole primary — its own
`NodeReplicated` wrapper, attached WAL, epoch, `ReplicationShipper`
feed, and (optionally) a follower + `PromotionManager`. NOTHING here
is new machinery: promotion, fencing, snapshot bootstrap, and
recovery are the existing per-primary planes, instantiated once per
shard — the subsystem's job is composition and the routing contract,
not a second replication implementation.

`ShardGroup` is the all-in-one composition (tests, examples, the
embeddable deployment): N `ShardPrimary`s under one directory, a
durably-published `ShardMap`, and a `ShardRouter` over
`LocalBackend`s. Its failure story is the per-shard one: killing one
shard's primary (`kill_primary`) fails exactly that keyspace slice —
the other shards' frontends never see it — and `promote` re-homes the
slice onto the shard's follower, bumps + re-publishes the map, and
repoints the router, after which stale-map peers are fenced
(`WrongShard`) and `call_with_retry` re-routes via `refresh_map`.
Multi-process deployments (`bench.py --sharded`, the CI smoke) keep
the same shapes but put each `ShardPrimary`'s stack in its own
process behind a `ShardServer`.
"""

from __future__ import annotations

import os

from node_replication_tpu.shard.ring import ShardMap
from node_replication_tpu.shard.router import LocalBackend, ShardRouter


def _default_nr_kwargs() -> dict:
    # the follower-fleet bench's per-primary sizing: one replica per
    # shard process keeps the scaling measurement about SHARDS
    return dict(n_replicas=1, log_entries=1 << 15, gc_slack=512,
                exec_window=256)


class ShardPrimary:
    """One shard's complete primary stack over its keyspace slice.

    Layout under `base_dir`: `primary/` (WAL + snapshots), `feed/`
    (the shipper's directory feed), `follower/` (the standby's WAL).
    The frontend acks ship-before-ack (`ack_barrier =
    shipper.barrier`), so the group's zero-lost-acks property holds
    per shard across a promotion — an acked op is fsynced AND in the
    feed the follower drains.
    """

    def __init__(self, shard: int, dispatch, base_dir: str,
                 shard_map: ShardMap, config=None,
                 nr_kwargs: dict | None = None,
                 with_follower: bool = True,
                 heartbeat_timeout_s: float = 0.5,
                 poll_s: float = 0.002,
                 auto_start_watch: bool = False,
                 recover: bool = False,
                 with_txn: bool = True,
                 decisions=None):
        from node_replication_tpu import NodeReplicated
        from node_replication_tpu.durable import WriteAheadLog
        from node_replication_tpu.repl import (
            DirectoryFeed,
            Follower,
            PromotionManager,
            ReplicationShipper,
        )
        from node_replication_tpu.serve import ServeConfig, ServeFrontend

        self.shard = int(shard)
        self.map = shard_map
        self.base_dir = base_dir
        self.primary_dir = os.path.join(base_dir, "primary")
        self.feed_dir = os.path.join(base_dir, "feed")
        self.follower_dir = os.path.join(base_dir, "follower")
        for p in (self.primary_dir, self.feed_dir, self.follower_dir):
            os.makedirs(p, exist_ok=True)
        cfg = config or ServeConfig(durability="batch")
        if cfg.durability != "batch":
            raise ValueError(
                "sharded primaries require durable acks "
                "(ServeConfig(durability='batch'))"
            )
        self.dispatch = dispatch
        self.recovery = None
        if recover:
            # restart-in-place: rebuild this slice from its own
            # snapshots + WAL; the shipper then resumes at the feed's
            # persisted tail (ship-before-ack means nothing acked is
            # missing from either artifact)
            from node_replication_tpu.durable.recovery import \
                recover_fleet
            self.nr, self.recovery = recover_fleet(
                self.primary_dir, dispatch,
                nr_kwargs=nr_kwargs or _default_nr_kwargs(),
            )
            self.wal = self.nr.wal
        else:
            self.nr = NodeReplicated(
                dispatch, **(nr_kwargs or _default_nr_kwargs())
            )
            self.wal = WriteAheadLog(
                os.path.join(self.primary_dir, "wal"), policy="batch"
            )
            self.nr.attach_wal(self.wal)
        self.feed = DirectoryFeed(
            self.feed_dir, arg_width=self.nr.spec.arg_width
        )
        self.shipper = ReplicationShipper(
            self.wal, self.feed, poll_s=poll_s,
            heartbeat_interval_s=0.02,
        )
        self.frontend = ServeFrontend(self.nr, cfg)
        self.frontend.ack_barrier = self.shipper.barrier
        self.follower = None
        self.manager = None
        if with_follower:
            self.follower = Follower(
                dispatch, self.feed, self.follower_dir,
                config=cfg, poll_s=poll_s,
                nr_kwargs=nr_kwargs or _default_nr_kwargs(),
            )
            self.manager = PromotionManager(
                self.feed, [self.follower],
                heartbeat_timeout_s=heartbeat_timeout_s,
                check_interval_s=0.03,
            )
            if auto_start_watch:
                self.manager.start()
        self.txn = None
        if with_txn:
            # 2PC participant over THIS shard's frontend + WAL. Costs
            # the non-txn path nothing: `submit_batch` consults it
            # through one `has_locks()` flag read and the intent log
            # is an empty fsynced file until the first prepare.
            from node_replication_tpu.shard.txn import TxnParticipant
            self.txn = TxnParticipant(
                self.shard, self.frontend, shard_map,
                os.path.join(base_dir, "txn"),
                decisions=decisions, wal=self.wal,
            )
        self._primary_dead = False

    @property
    def live_frontend(self):
        """The frontend currently serving this shard's writes — the
        primary's until `promote()`, the promoted follower's after."""
        if (self.follower is not None and self.follower.promoted):
            return self.follower.frontend
        return self.frontend

    def kill_primary(self) -> None:
        """Fail this shard's primary abruptly (in-process stand-in for
        SIGKILL): stop shipping — heartbeat silence is what the
        `PromotionManager` detects — and tear the frontend down
        non-draining so queued requests reject instead of hanging."""
        if self._primary_dead:
            return
        self._primary_dead = True
        self.shipper.stop(clear_pin=False)
        self.frontend.close(drain=False)

    def promote(self, detect_s: float = 0.0):
        """Promote this shard's follower (detection done by the
        caller's watch, or operator-initiated). Returns the
        `PromotionReport`; `live_frontend` then serves writes."""
        if self.manager is None:
            raise RuntimeError(f"shard {self.shard} has no follower")
        return self.manager.promote_now(detect_s=detect_s)

    def close(self) -> None:
        if self.txn is not None:
            self.txn.close()
        if not self._primary_dead:
            self.shipper.stop()
            self.frontend.close()
        if self.follower is not None:
            self.follower.close()
        wal = self.nr.detach_wal()
        if wal is not None:
            wal.close()


class ShardGroup:
    """N `ShardPrimary`s + a published `ShardMap` + a `ShardRouter`.

        group = ShardGroup(3, make_hashmap(1024), base_dir=d)
        router = group.router
        router.call((HM_SET, key, value))     # routed by key % 3
        ...
        group.kill_primary(1)                 # one slice fails
        group.promote(1)                      # its follower takes over
        router.call((HM_SET, key1, value))    # re-routed, still acked

    `promote` bumps and RE-PUBLISHES the map before repointing the
    router, so external routers watching the published file
    (`refresh_map`) converge on the new topology, and any peer still
    submitting under the old version gets `WrongShard` — the zombie
    fence at the routing tier.
    """

    def __init__(self, n_shards: int, dispatch, base_dir: str,
                 config=None, nr_kwargs: dict | None = None,
                 with_followers: bool = True,
                 heartbeat_timeout_s: float = 0.5,
                 concurrent_router: bool = True,
                 with_txn: bool = True,
                 recover: bool = False):
        from node_replication_tpu.durable import DecisionLog

        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self.decisions_dir = os.path.join(base_dir, "decisions")
        self.decisions = DecisionLog(self.decisions_dir) \
            if with_txn else None
        #: participants created by a reshard (`shard/reshard.py`) for
        #: the refined classes — owned here so `close()` reaps them
        self.extra_participants: list = []
        if recover:
            # restart-in-place: adopt the published map (version and
            # all) instead of stamping a fresh version-1 map over it
            self.map = ShardMap.load(base_dir)
            if self.map.n_shards != n_shards:
                raise ValueError(
                    f"published map has {self.map.n_shards} shards, "
                    f"caller expected {n_shards}"
                )
        else:
            self.map = ShardMap(n_shards)
            self.map.publish(base_dir)
        self.primaries = [
            ShardPrimary(
                s, dispatch,
                os.path.join(base_dir, f"s{s}"),
                self.map, config=config, nr_kwargs=nr_kwargs,
                with_follower=with_followers,
                heartbeat_timeout_s=heartbeat_timeout_s,
                recover=recover, with_txn=with_txn,
                decisions=self.decisions,
            )
            for s in range(n_shards)
        ]
        self.router = ShardRouter(
            self.map,
            {
                s: LocalBackend(
                    s, self.primaries[s].frontend, self.map,
                    participant=self.primaries[s].txn,
                )
                for s in range(n_shards)
            },
            map_path=base_dir,
            concurrent=concurrent_router,
        )

    def coordinator(self, name: str = "coord"):
        """A `TxnCoordinator` over this group's router, sharing the
        fleet's decision directory — the one participants consult in
        `resolve_in_doubt`. Each construction durably bumps the
        coordinator epoch (older generations' undecided intents
        become presumed-abortable)."""
        if self.decisions is None:
            raise RuntimeError("group built with with_txn=False")
        from node_replication_tpu.shard.txn import TxnCoordinator
        return TxnCoordinator(self.router, self.decisions_dir,
                              name=name)

    def resolve_in_doubt(self) -> dict:
        """Run every participant's in-doubt resolution against the
        shared decision log (the restart path after a coordinator or
        participant crash). Returns `{shard: {txn: outcome}}`."""
        epoch = self.decisions.epoch() if self.decisions else 0
        out = {}
        parts = [p.txn for p in self.primaries] + \
            list(self.extra_participants)
        for t in parts:
            if t is not None:
                out[t.shard] = t.resolve_in_doubt(
                    decisions=self.decisions, epoch=epoch
                )
        return out

    @property
    def n_shards(self) -> int:
        return self.map.n_shards

    def kill_primary(self, shard: int) -> None:
        self.primaries[int(shard)].kill_primary()

    def promote(self, shard: int, detect_s: float = 0.0):
        """Promote `shard`'s follower and re-home its writes: publish
        the bumped map FIRST (external routers must be able to prove
        the old version stale before the new home acks), then repoint
        this group's router onto the promoted frontend."""
        s = int(shard)
        p = self.primaries[s]
        report = p.promote(detect_s=detect_s)
        new_map = self.map.with_address(s, None)
        new_map.publish(self.base_dir)
        self.map = new_map
        for q in self.primaries:
            q.map = new_map
            if q.txn is not None:
                q.txn.set_map(new_map)
        if p.txn is not None:
            # re-home the participant too: prepared intents survive
            # (the intent log is the shard's, not the primary's) and
            # future commits apply through the promoted frontend
            p.txn.set_frontend(p.live_frontend,
                               wal=p.follower.nr.wal)
        self.router.repoint(
            s, LocalBackend(s, p.live_frontend, new_map,
                            participant=p.txn),
            new_map=new_map,
        )
        return report

    def close(self) -> None:
        self.router.close()
        for t in self.extra_participants:
            if t is not None:
                t.close()
        for p in self.primaries:
            p.close()
