"""The routing tier: split → fan out → reassemble, over CRC frames.

`ShardRouter` is the embeddable client-side router (and the core of
the thin proxy `bench.py --sharded` runs): it holds a versioned
`ShardMap`, splits every submitted batch into per-shard sub-batches,
fans them out concurrently, and reassembles the responses in
submission order. Backends come in two shapes behind one protocol
(`submit_batch(ops, peer_version, ...)`):

- `LocalBackend` — an in-process `ServeFrontend` (the shard primary
  lives in this process, or the router just re-homed a shard onto a
  promoted follower). It re-verifies EVERY op against the map — key
  congruence and version — before staging, so a mis-routed op is a
  typed `WrongShard` before any log effect, never a silent write into
  the wrong keyspace slice.
- `SocketShardClient` — a shard primary in another process, reached
  through `ShardServer` over `repl/transport.py`'s length+CRC framing
  (`send_frame`/`recv_frame`; payloads are JSON). The client replays
  a HELLO carrying its map version on EVERY (re)connect and the
  server checks it on every submit — a fenced zombie shard (stale
  map after a promotion re-published it) can never ack.

**The cross-shard BATCH contract is the CNR one — explicitly NOT
atomic.** Ops on different shards live in disjoint `key % N`
congruence classes (`shard/ring.py`), so their sub-batches execute
concurrently and independently: one shard's sub-batch can commit and
ack while another's fails (`ShardUnavailable`), exactly as CNR's
per-log batches commit independently (PAPER.md;
`models/partitioned.py` pins the same semantics in-process).
`execute_batch` therefore reports per-op outcomes; there is no
cross-shard rollback. Callers that need multi-shard atomicity use the
transaction layer ON TOP: `shard/txn.py:TxnCoordinator` drives
presumed-abort 2PC through these same backends (the `txn_verb`
surface routed by `txn_call`), and costs this path nothing when
unused — see README "Keyspace sharding" for the guarantee table.

Failure semantics mirror the serve plane: `ShardUnavailable` with
`maybe_executed=False` means the sub-batch provably never reached the
shard's log (resubmit is exactly-once safe; `call_with_retry` does),
`maybe_executed=True` means the connection died after the ops were
sent (they may commit and replay; only the caller can decide).
`call_with_retry` re-routes across a shard promotion by calling
`refresh_map()` — the router reloads the durably-published map,
adopts the bumped version, and pushes it to every backend.
"""

from __future__ import annotations

import itertools
import json
import socket
import threading

from node_replication_tpu.analysis.locks import make_lock
from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.repl.transport import (
    MAX_FRAME_BYTES,
    TransportError,
    recv_frame,
    send_frame,
)
from node_replication_tpu.serve.errors import (
    DeadlineExceeded,
    FrontendClosed,
    NotPrimary,
    Overloaded,
    ReplicaFailed,
    ServeError,
    ShardUnavailable,
    TxnAborted,
    TxnConflict,
    TxnInDoubt,
    WrongShard,
)
from node_replication_tpu.shard.ring import ShardMap, ShardMapCorruptError
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.utils.trace import get_tracer

# ==========================================================================
# error encoding (typed errors survive the wire)
# ==========================================================================


def _encode_error(e: BaseException) -> dict:
    """One JSON dict per typed serve error, so the client re-raises
    the SAME type `call_with_retry` routes on — a shard's `Overloaded`
    must back off, its `WrongShard` must refresh the map, and a
    generic string would collapse both into a blind retry."""
    if isinstance(e, WrongShard):
        return {"type": "WrongShard", "key": e.key, "shard": e.shard,
                "expected_shard": e.expected_shard,
                "map_version": e.map_version,
                "peer_version": e.peer_version}
    if isinstance(e, ShardUnavailable):
        return {"type": "ShardUnavailable", "shard": e.shard,
                "maybe_executed": e.maybe_executed,
                "detail": str(e.cause) if e.cause else ""}
    if isinstance(e, Overloaded):
        return {"type": "Overloaded", "rid": e.rid, "depth": e.depth}
    if isinstance(e, ReplicaFailed):
        return {"type": "ReplicaFailed", "rid": e.rid,
                "maybe_executed": e.maybe_executed,
                "detail": str(e.cause) if e.cause else ""}
    if isinstance(e, DeadlineExceeded):
        return {"type": "DeadlineExceeded", "rid": e.rid,
                "late_by_s": e.late_by_s}
    if isinstance(e, NotPrimary):
        return {"type": "NotPrimary", "rid": e.rid}
    if isinstance(e, FrontendClosed):
        return {"type": "FrontendClosed", "detail": str(e)}
    if isinstance(e, TxnConflict):
        return {"type": "TxnConflict", "key": e.key, "txn": e.txn}
    if isinstance(e, TxnAborted):
        return {"type": "TxnAborted", "txn": e.txn,
                "detail": str(e.cause) if e.cause else ""}
    if isinstance(e, TxnInDoubt):
        return {"type": "TxnInDoubt", "txn": e.txn,
                "decision": e.decision,
                "detail": str(e.cause) if e.cause else ""}
    return {"type": "ServeError",
            "detail": f"{type(e).__name__}: {e}"}


def _decode_error(d: dict, shard: int) -> ServeError:
    t = d.get("type")
    if t == "WrongShard":
        return WrongShard(d["key"], d["shard"], d["expected_shard"],
                          d["map_version"], d.get("peer_version"))
    if t == "ShardUnavailable":
        cause = RuntimeError(d["detail"]) if d.get("detail") else None
        return ShardUnavailable(d["shard"], cause=cause,
                                maybe_executed=d["maybe_executed"])
    if t == "Overloaded":
        return Overloaded(d["rid"], d["depth"])
    if t == "ReplicaFailed":
        cause = RuntimeError(d["detail"]) if d.get("detail") else None
        return ReplicaFailed(d["rid"], cause=cause,
                             maybe_executed=d["maybe_executed"])
    if t == "DeadlineExceeded":
        return DeadlineExceeded(d["rid"], d["late_by_s"])
    if t == "NotPrimary":
        return NotPrimary(d["rid"])
    if t == "FrontendClosed":
        return FrontendClosed(d.get("detail", "frontend closed"))
    if t == "TxnConflict":
        return TxnConflict(d["key"], d["txn"])
    if t == "TxnAborted":
        cause = RuntimeError(d["detail"]) if d.get("detail") else None
        return TxnAborted(d["txn"], cause=cause)
    if t == "TxnInDoubt":
        cause = RuntimeError(d["detail"]) if d.get("detail") else None
        return TxnInDoubt(d["txn"], decision=d.get("decision"),
                          cause=cause)
    return ServeError(
        f"shard {shard} remote error: {d.get('detail', d)}"
    )


def _encode_pairs(pairs: list) -> list:
    """`submit_batch` outcome pairs → JSON rows. Results must be
    JSON-representable (the replicated models return ints / None;
    tuples survive as lists)."""
    out = []
    for status, val in pairs:
        if status == "ok":
            out.append(["ok", val])
        else:
            out.append(["err", _encode_error(val)])
    return out


def _decode_pairs(rows: list, shard: int) -> list:
    return [
        ("ok", val) if status == "ok"
        else ("err", _decode_error(val, shard))
        for status, val in rows
    ]


# ==========================================================================
# backends
# ==========================================================================


class LocalBackend:
    """One shard's in-process submit path.

    Used three ways: inside `ShardServer` (the shard primary's
    process), inside an all-in-one `ShardGroup` (tests, sim), and as
    the re-home target after a promotion (`ShardRouter.repoint` onto
    the promoted follower's frontend). In every role it re-verifies
    the routing invariant — the caller's map version matches and each
    op's key lands in THIS shard's congruence class — before any op
    is staged, so the fleet-level LogMapper contract is enforced at
    the door, not assumed (nrlint rule `unrouted-key-in-shard-path`
    machine-checks that no shard/ submit path skips this lookup).
    """

    def __init__(self, shard: int, frontend, shard_map: ShardMap,
                 participant=None):
        self.shard = int(shard)
        self._frontend = frontend
        self._map = shard_map
        #: the shard's 2PC participant (`shard/txn.py`), when wired:
        #: routes txn verbs and fences non-txn ops off locked keys
        self._participant = participant
        self._lock = make_lock("LocalBackend._lock")
        # in-flight submit_batch tokens: `quiesce()` waits for the
        # calls that entered BEFORE a map fence to leave, closing the
        # check-then-stage window a reshard cutover must not race
        # (`shard/reshard.py`: an op that passed the old-version check
        # must finish acking — ship barrier armed — before the donor's
        # shipper stops, or an acked moved-key write could miss the
        # promote drain)
        self._active: set = set()
        self._active_seq = itertools.count()

    @property
    def map(self) -> ShardMap:
        with self._lock:
            return self._map

    def set_map(self, m: ShardMap) -> None:
        with self._lock:
            self._map = m

    def update_version(self, m: ShardMap) -> None:
        """Router pushed a newer map (uniform backend surface with
        `SocketShardClient.update_version`)."""
        self.set_map(m)

    def set_frontend(self, frontend) -> None:
        with self._lock:
            self._frontend = frontend

    def set_participant(self, participant) -> None:
        with self._lock:
            self._participant = participant

    @property
    def participant(self):
        with self._lock:
            return self._participant

    def txn_verb(self, verb: str, txn: str, gen: int,
                 peer_version: int, ops=None,
                 timeout: float | None = None):
        """Dispatch one 2PC verb to this shard's participant
        (`shard/txn.py`). The participant does its own version and
        congruence fencing; a shard with no participant refuses
        retryably — the coordinator re-homes via the published map
        exactly like a dead primary."""
        with self._lock:
            p = self._participant
        if p is None:
            raise ShardUnavailable(
                self.shard,
                cause=RuntimeError("shard has no txn participant"),
            )
        if verb == "prepare":
            return p.prepare(txn, gen, ops or [], peer_version)
        if verb == "commit":
            return p.commit(txn, peer_version)
        if verb == "abort":
            p.abort(txn, peer_version)
            return True
        if verb == "status":
            return p.status(txn)
        raise ServeError(f"unknown txn verb {verb!r}")

    def submit_batch(self, ops, peer_version: int,
                     deadline_s: float | None = None,
                     timeout: float | None = None,
                     priority: int | None = None,
                     rid: int = 0) -> list:
        """Verify-then-stage the sub-batch; returns one `("ok",
        result)` / `("err", exc)` pair per op, submission order.

        All ops are staged before any result is awaited (the frontend
        batches them into combiner rounds); per-op failures stay
        per-op — an `Overloaded` shed of op k never aborts op k+1,
        matching the non-atomic contract.
        """
        tok = next(self._active_seq)
        with self._lock:
            m = self._map
            fe = self._frontend
            p = self._participant
            self._active.add(tok)
        try:
            return self._submit_batch(m, fe, p, ops, peer_version,
                                      deadline_s, timeout, priority,
                                      rid)
        finally:
            with self._lock:
                self._active.discard(tok)

    def _submit_batch(self, m, fe, p, ops, peer_version,
                      deadline_s, timeout, priority, rid) -> list:
        if peer_version != m.version:
            raise WrongShard(-1, self.shard, self.shard, m.version,
                             peer_version=peer_version)
        for op in ops:
            owner = m.shard_of_op(op)
            if owner != self.shard:
                raise WrongShard(op[1], self.shard, owner, m.version,
                                 peer_version=peer_version)
        if p is not None and p.has_locks():
            # a prepared-but-undecided txn blocks CONFLICTING KEYS,
            # not the shard; the flag read above is the txn plane's
            # entire cost on the non-txn path when nothing is in
            # flight (the `obs_port=None` discipline)
            p.check_conflicts(ops)
        kwargs = {} if priority is None else {"priority": priority}

        def translate(e: ServeError) -> ServeError:
            # a closed/dead frontend is PERMANENT for its process but
            # TRANSIENT for the shard — the op never reached the log
            # and the slice is about to be re-homed onto the promoted
            # follower, so surface the retryable shard-plane error;
            # likewise a follower-mode frontend mid-cutover
            # (`shard/reshard.py`: the recipient backend is attached
            # BEFORE its promotion drains) refuses with zero effect —
            # retryably, so closed-loop clients ride the fence out
            if isinstance(e, (FrontendClosed, NotPrimary)):
                return ShardUnavailable(self.shard, cause=e)
            return e

        staged: list = []
        for op in ops:
            try:
                staged.append(
                    ("fut", fe.submit(tuple(op), rid=rid,
                                      deadline_s=deadline_s, **kwargs))
                )
            except ServeError as e:
                staged.append(("err", translate(e)))
        pairs: list = []
        for status, item in staged:
            if status == "err":
                pairs.append(("err", item))
                continue
            try:
                pairs.append(("ok", item.result(timeout)))
            except TimeoutError as e:
                pairs.append(("err", e))
            except ServeError as e:
                pairs.append(("err", translate(e)))
        return pairs

    def quiesce(self, timeout: float = 10.0) -> bool:
        """Wait until every `submit_batch` call that entered BEFORE
        this point has left (acked or failed). Calls entering after
        do not extend the wait — they already see the current map, so
        a cutover that fenced the map first only needs the OLD
        epoch's in-flight calls gone. True when drained in time."""
        with self._lock:
            snap = set(self._active)
        clock = get_clock()
        t_end = clock.now() + float(timeout)
        while snap:
            with self._lock:
                snap &= self._active
            if not snap:
                break
            if clock.now() >= t_end:
                return False
            clock.sleep(0.002)
        return True

    def close(self) -> None:
        pass


class SocketShardClient:
    """One shard's remote submit path, over the repl CRC framing.

    Connection discipline follows `repl/transport.py:SocketFeed`: one
    socket guarded by the client lock, and EVERY (re)connect replays
    the HELLO carrying this client's map version — the server refuses
    a mismatch with a typed `WrongShard`, which is what makes a fenced
    zombie shard (or a stale router) unable to exchange a single ack
    after a promotion bumps the published map.

    Retry discipline is STRICTER than the feed's, because submits are
    not idempotent: a failure BEFORE the request frame was fully sent
    reconnects and retries once (a torn frame fails the server's CRC
    check, so nothing executed); a failure AFTER the send raises
    `ShardUnavailable(maybe_executed=True)` — the sub-batch may commit
    and replay from the shard's WAL, so the client must not blindly
    resubmit (`call_with_retry` refuses exactly like a
    `maybe_executed` `ReplicaFailed`).
    """

    def __init__(self, shard: int, address, map_version: int,
                 connect_timeout_s: float = 5.0,
                 io_timeout_s: float = 10.0):
        self.shard = int(shard)
        self.address = (str(address[0]), int(address[1]))
        self._version = int(map_version)
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self._lock = make_lock("SocketShardClient._lock")
        self._sock: socket.socket | None = None

    # ------------------------------------------------- connection mgmt

    def _connect_locked(self) -> None:
        sock = socket.create_connection(
            self.address, timeout=self.connect_timeout_s
        )
        sock.settimeout(self.io_timeout_s)
        try:
            send_frame(sock, json.dumps(
                {"kind": "hello", "version": self._version}
            ).encode())
            rsp = json.loads(
                recv_frame(sock, MAX_FRAME_BYTES).decode()
            )
        except BaseException:
            sock.close()
            raise
        if rsp.get("kind") == "error":
            sock.close()
            raise _decode_error(rsp["err"], self.shard)
        if (rsp.get("kind") != "hello-ok"
                or rsp.get("shard") != self.shard):
            sock.close()
            raise TransportError(
                f"bad hello response from shard {self.shard}: {rsp}"
            )
        self._sock = sock

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def update_version(self, m: ShardMap) -> None:
        """Adopt a newer map (and address): drop the connection so the
        next request replays HELLO under the new version — the
        map-version check runs on every reconnect by construction."""
        with self._lock:
            self._version = m.version
            addr = m.addresses[self.shard]
            if addr is not None:
                self.address = (str(addr[0]), int(addr[1]))
            self._drop_locked()

    # ------------------------------------------------------- requests

    def _request(self, obj: dict) -> dict:
        with self._lock:
            last: BaseException | None = None
            for attempt in (0, 1):
                if self._sock is None:
                    try:
                        self._connect_locked()
                    except (TransportError, OSError) as e:
                        self._drop_locked()
                        last = e
                        continue
                sent = False
                try:
                    send_frame(self._sock, json.dumps(obj).encode())
                    sent = True
                    return json.loads(
                        recv_frame(self._sock,
                                   MAX_FRAME_BYTES).decode()
                    )
                except TransportError as e:
                    self._drop_locked()
                    if sent:
                        # the request frame left intact: the shard may
                        # execute it and lose only the response
                        raise ShardUnavailable(
                            self.shard, cause=e, maybe_executed=True
                        ) from e
                    last = e
            raise ShardUnavailable(self.shard, cause=last) from last

    def submit_batch(self, ops, peer_version: int,
                     deadline_s: float | None = None,
                     timeout: float | None = None,
                     priority: int | None = None,
                     rid: int = 0) -> list:
        rsp = self._request({
            "kind": "submit",
            "version": int(peer_version),
            "ops": [list(op) for op in ops],
            "deadline_s": deadline_s,
            "timeout": timeout,
            "priority": priority,
            "rid": int(rid),
        })
        if rsp.get("kind") == "error":
            raise _decode_error(rsp["err"], self.shard)
        if rsp.get("kind") != "ack":
            raise ShardUnavailable(
                self.shard,
                cause=RuntimeError(f"bad response kind: {rsp}"),
            )
        return _decode_pairs(rsp["pairs"], self.shard)

    def txn_verb(self, verb: str, txn: str, gen: int,
                 peer_version: int, ops=None,
                 timeout: float | None = None):
        """One 2PC verb over the wire (`ShardServer` routes it to the
        shard's participant). Same post-send honesty as `submit`: a
        connection death after the frame left raises
        `maybe_executed=True` — but unlike a submit, commit/abort are
        idempotent at the participant, so the coordinator MAY re-drive
        them (and does)."""
        rsp = self._request({
            "kind": "txn",
            "verb": str(verb),
            "txn": str(txn),
            "gen": int(gen),
            "version": int(peer_version),
            "ops": [list(op) for op in (ops or [])],
            "timeout": timeout,
        })
        if rsp.get("kind") == "error":
            raise _decode_error(rsp["err"], self.shard)
        if rsp.get("kind") != "txn-ok":
            raise ShardUnavailable(
                self.shard,
                cause=RuntimeError(f"bad response kind: {rsp}"),
            )
        return rsp.get("result")

    def close(self) -> None:
        with self._lock:
            self._drop_locked()


# ==========================================================================
# the shard-side server
# ==========================================================================


class ShardServer:
    """One shard primary's submit endpoint (thin proxy target).

    Lifecycle and socket discipline mirror
    `repl/transport.py:FeedServer`: a named accept thread polling a
    stop flag under an accept timeout, one named thread per
    connection with an io timeout, and every failure ANSWERED as a
    typed error frame (`_encode_error`), never swallowed — a client
    must be able to tell `WrongShard` (refresh and re-route) from
    `Overloaded` (back off) without string-matching.

    Version fencing: the server holds the shard's current `ShardMap`
    and checks the client's version at HELLO **and on every submit**
    (`LocalBackend` re-checks it), so bumping the map via `set_map`
    immediately fences every stale peer — the shard-level twin of the
    feed's epoch fence.
    """

    def __init__(self, shard: int, frontend, shard_map: ShardMap,
                 host: str = "127.0.0.1", port: int = 0,
                 name: str = "shard",
                 accept_timeout_s: float = 0.2,
                 io_timeout_s: float = 10.0):
        self.shard = int(shard)
        self.name = name
        self._backend = LocalBackend(shard, frontend, shard_map)
        self._accept_timeout_s = float(accept_timeout_s)
        self._io_timeout_s = float(io_timeout_s)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET,
                               socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(64)
        self._lsock.settimeout(self._accept_timeout_s)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._stop = threading.Event()
        self._conn_id = 0
        self._threads_lock = make_lock("ShardServer._threads_lock")
        self._conn_threads: list[threading.Thread] = []
        reg = get_registry()
        self._m_submitted = reg.counter(
            f"shard.s{self.shard}.server_submitted"
        )
        self._m_refused = reg.counter(
            f"shard.s{self.shard}.server_refused"
        )
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"shard-server-{name}-s{self.shard}",
            daemon=True,
        )
        self._accept_thread.start()

    @property
    def map(self) -> ShardMap:
        return self._backend.map

    def set_map(self, m: ShardMap) -> None:
        """Adopt a re-published map: every in-flight and future
        submit carrying the old version is refused (`WrongShard`)."""
        self._backend.set_map(m)

    def set_frontend(self, frontend) -> None:
        self._backend.set_frontend(frontend)

    def set_participant(self, participant) -> None:
        """Wire the shard's 2PC participant (`shard/txn.py`); txn
        frames are refused (retryably) until one is attached."""
        self._backend.set_participant(participant)

    # --------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._lsock.accept()
            except (TimeoutError, socket.timeout):
                continue
            except OSError:
                if self._stop.is_set():
                    return
                continue
            conn.settimeout(self._io_timeout_s)
            with self._threads_lock:
                self._conn_id += 1
                t = threading.Thread(
                    target=self._serve_conn,
                    args=(conn,),
                    name=(f"shard-conn-{self.name}-s{self.shard}"
                          f"-{self._conn_id}"),
                    daemon=True,
                )
                self._conn_threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    req = json.loads(
                        recv_frame(conn, MAX_FRAME_BYTES).decode()
                    )
                except (TransportError, ValueError):
                    return  # client gone / torn stream: done
                try:
                    rsp = self._handle(req)
                except Exception as e:  # answered, never swallowed
                    self._record_failure(e)
                    rsp = {"kind": "error", "err": _encode_error(e)}
                try:
                    send_frame(conn, json.dumps(rsp).encode())
                except TransportError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _record_failure(self, e: BaseException) -> None:
        """Count + trace a refused request (the FeedServer report
        discipline): every failure is ANSWERED as a typed error frame
        by the caller, and this makes it visible to obs too."""
        self._m_refused.inc()
        tracer = get_tracer()
        if tracer.enabled:
            tracer.emit("shard-refused", shard=self.shard,
                        error=type(e).__name__, detail=str(e))

    def _handle(self, req: dict) -> dict:
        kind = req.get("kind")
        if kind == "hello":
            m = self._backend.map
            peer = int(req.get("version", -1))
            if peer != m.version:
                raise WrongShard(-1, self.shard, self.shard,
                                 m.version, peer_version=peer)
            return {"kind": "hello-ok", "shard": self.shard,
                    "version": m.version}
        if kind == "submit":
            ops = [tuple(op) for op in req["ops"]]
            self._m_submitted.inc(len(ops))
            pairs = self._backend.submit_batch(
                ops,
                int(req["version"]),
                deadline_s=req.get("deadline_s"),
                timeout=req.get("timeout"),
                priority=req.get("priority"),
                rid=int(req.get("rid", 0)),
            )
            return {"kind": "ack", "pairs": _encode_pairs(pairs)}
        if kind == "txn":
            result = self._backend.txn_verb(
                req["verb"],
                req["txn"],
                int(req.get("gen", 0)),
                int(req["version"]),
                ops=[tuple(op) for op in req.get("ops", [])],
                timeout=req.get("timeout"),
            )
            if isinstance(result, list):
                # commit results: the models return JSON-safe scalars
                result = [
                    v if v is None else int(v) for v in result
                ]
            return {"kind": "txn-ok", "result": result}
        raise ServeError(f"unknown request kind {kind!r}")

    def close(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=5.0)
        with self._threads_lock:
            threads = list(self._conn_threads)
        for t in threads:
            t.join(timeout=5.0)


# ==========================================================================
# the router
# ==========================================================================


class ShardRouter:
    """Split → fan out → reassemble over a fleet of shard backends.

    Frontend-shaped on purpose: `call(op, ...)` is drop-in for
    `serve/client.py:call_with_retry`, which then handles the shard
    plane's transients for free — `ShardUnavailable` backs off and
    retries (the op provably never reached a log), `WrongShard`
    triggers `refresh_map()` so a promotion's re-published map is
    adopted mid-retry-loop and the resubmission routes to the shard's
    new home. The router deliberately does NOT expose
    `healthy_rids()`: keys are pinned to shards by the congruence
    map, so re-routing an op to a different shard is never correct —
    re-homing happens by map adoption, not replica choice.
    """

    def __init__(self, shard_map: ShardMap, backends: dict,
                 map_path: str | None = None,
                 concurrent: bool = True):
        self._lock = make_lock("ShardRouter._lock")
        self._map = shard_map
        self._backends = dict(backends)
        self._map_path = map_path
        #: sequential shard-ordered fan-out when False — the sim's
        #: determinism knob (thread interleaving is schedule noise)
        self.concurrent = bool(concurrent)
        reg = get_registry()
        self._m_fanout = reg.histogram("shard.router.fanout_s")
        self._m_version = reg.gauge("shard.map_version")
        self._m_version.set(shard_map.version)
        self._m_corrupt = reg.counter("shard.map_corrupt")
        # per-shard counters are created LAZILY: a reshard can grow
        # `n_shards` mid-life (`shard/reshard.py`), and metric
        # creation on first touch keeps the registry in step without
        # a resize hook
        self._m_sub: dict[int, object] = {}
        self._m_ack: dict[int, object] = {}
        self._m_reroute: dict[int, object] = {}
        for s in range(shard_map.n_shards):
            self._shard_counters(s)

    def _shard_counters(self, s: int) -> tuple:
        sub = self._m_sub.get(s)
        if sub is None:
            reg = get_registry()
            sub = self._m_sub[s] = reg.counter(f"shard.s{s}.submitted")
            self._m_ack[s] = reg.counter(f"shard.s{s}.acked")
            self._m_reroute[s] = reg.counter(f"shard.s{s}.rerouted")
        return sub, self._m_ack[s], self._m_reroute[s]

    @property
    def map(self) -> ShardMap:
        with self._lock:
            return self._map

    # ------------------------------------------------------ map churn

    def adopt(self, new_map: ShardMap, backends: dict | None = None,
              reason: str = "map-update") -> None:
        """Adopt a newer map (and optionally replacement backends for
        re-homed shards), pushing the version to every backend so
        socket clients replay HELLO under it on their next request."""
        with self._lock:
            old = self._map
            if new_map.version < old.version:
                return
            self._map = new_map
            if backends:
                for s, b in backends.items():
                    prev = self._backends.get(s)
                    if prev is not None and prev is not b:
                        prev.close()
                    self._backends[int(s)] = b
            live = list(self._backends.items())
        self._m_version.set(new_map.version)
        # growth-safe move detection: a refined map (`ShardMap.refine`)
        # has MORE classes than the old one — a brand-new class index
        # counts as moved only when a backend was re-homed onto it
        moved = [
            s for s in range(new_map.n_shards)
            if (backends and s in backends)
            or (s < old.n_shards
                and new_map.addresses[s] != old.addresses[s])
        ]
        for s in moved:
            self._shard_counters(s)[2].inc()
        tracer = get_tracer()
        if tracer.enabled and (moved or new_map.version != old.version):
            tracer.emit("serve-reroute", reason=reason,
                        map_version=new_map.version,
                        from_version=old.version, shards=moved)
        for _s, b in live:
            b.update_version(new_map)

    def backend(self, shard: int):
        """The backend currently attached for `shard` (None when
        absent) — the reshard plan's handle for quiescing the donor
        at its cutover fence."""
        with self._lock:
            return self._backends.get(int(shard))

    def attach_backend(self, shard: int, backend) -> None:
        """Register a backend WITHOUT adopting a new map — the reshard
        cutover's staging step (`shard/reshard.py`): backends for the
        refined classes are attached first (inert; no key routes to a
        class beyond the current map), so the instant the doubled map
        is adopted every class already has a home and moved-key
        unavailability is the fence window, not a backend scramble."""
        with self._lock:
            prev = self._backends.get(int(shard))
            if prev is not None and prev is not backend:
                prev.close()
            self._backends[int(shard)] = backend

    def txn_call(self, shard: int, verb: str, txn: str, gen: int,
                 ops=None, timeout: float | None = None):
        """Route one 2PC verb (`shard/txn.py:TxnCoordinator`) to a
        shard's backend under the CURRENT map version — the
        participant fences it exactly like a submit."""
        with self._lock:
            m = self._map
            backend = self._backends.get(int(shard))
        if backend is None:
            raise ShardUnavailable(
                int(shard),
                cause=RuntimeError("no backend attached"),
            )
        return backend.txn_verb(verb, txn, gen, m.version, ops=ops,
                                timeout=timeout)

    def repoint(self, shard: int, backend,
                new_map: ShardMap | None = None) -> ShardMap:
        """Re-home one shard onto `backend` (a promotion: the shard's
        follower took over). Bumps the map version unless a
        re-published map is given, then adopts it fleet-wide."""
        with self._lock:
            m = self._map
        if new_map is None:
            addr = getattr(backend, "address", None)
            new_map = m.with_address(shard, addr)
        self.adopt(new_map, {int(shard): backend},
                   reason=f"repoint-s{shard}")
        return new_map

    def refresh_map(self) -> bool:
        """Reload the durably-published map; adopt if newer. This is
        `call_with_retry`'s re-route hook (`WrongShard` /
        `ShardUnavailable` both trigger it). Returns True when a newer
        version was adopted.

        Survives a CORRUPT published map: `ShardMap.load` raises
        typed `ShardMapCorruptError` for a document that parses or
        validates wrong (a hand edit, bit rot — never a torn publish,
        `durable_publish` excludes those), and the router keeps its
        old map and counts `shard.map_corrupt` — routing on the last
        good topology beats adopting garbage or crashing the retry
        loop."""
        if self._map_path is None:
            return False
        try:
            m = ShardMap.load(self._map_path)
        except ShardMapCorruptError:
            self._m_corrupt.inc()
            return False
        except (OSError, ValueError, KeyError):
            return False
        with self._lock:
            newer = m.version > self._map.version
        if newer:
            self.adopt(m, reason="refresh")
        return newer

    # ------------------------------------------------------ submit path

    def _fan_out(self, m: ShardMap, backends: dict, groups: dict,
                 deadline_s, timeout, priority, rid) -> dict:
        """One `submit_batch` per shard; concurrently when configured.
        Returns shard → pairs-or-exception (a whole-sub-batch failure
        is recorded per shard and mapped onto its ops by the caller)."""
        def run_one(shard: int, entries: list) -> list:
            backend = backends.get(shard)
            if backend is None:
                raise ShardUnavailable(
                    shard, cause=RuntimeError("no backend attached")
                )
            return backend.submit_batch(
                [op for _i, op in entries], m.version,
                deadline_s=deadline_s, timeout=timeout,
                priority=priority, rid=rid,
            )

        out: dict = {}
        shards = sorted(groups)
        if not self.concurrent or len(shards) == 1:
            for s in shards:
                try:
                    out[s] = run_one(s, groups[s])
                except Exception as e:
                    out[s] = e
            return out

        sinks: dict[int, list] = {s: [] for s in shards}

        def worker(s: int) -> None:
            try:
                sinks[s].append(("done", run_one(s, groups[s])))
            except Exception as e:
                # recorded to the per-shard sink; surfaced as this
                # sub-batch's per-op errors by the caller
                sinks[s].append(("error", e))

        threads = [
            threading.Thread(target=worker, args=(s,),
                             name=f"shard-router-fan-s{s}")
            for s in shards
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for s in shards:
            status, payload = sinks[s][0] if sinks[s] else (
                "error",
                ShardUnavailable(s, cause=RuntimeError(
                    "fan-out worker died"
                )),
            )
            out[s] = payload
        return out

    def execute_batch(self, ops, deadline_s: float | None = None,
                      timeout: float | None = None,
                      priority: int | None = None, rid: int = 0,
                      return_exceptions: bool = False) -> list:
        """Route a batch: split by congruence class, fan out, and
        reassemble responses in SUBMISSION order.

        Per-op outcomes are independent across shards (the CNR
        non-atomic contract): with `return_exceptions=True` each slot
        is either the op's result or its typed exception; with the
        default False the first failing op's exception is raised —
        AFTER every sub-batch completed, so ops on other shards have
        already committed (there is no rollback; the docstring above
        is the contract).
        """
        clock = get_clock()
        with self._lock:
            m = self._map
            backends = dict(self._backends)
        groups = m.split_batch(ops)
        for s, entries in groups.items():
            self._shard_counters(s)[0].inc(len(entries))
        t0 = clock.now()
        by_shard = self._fan_out(m, backends, groups,
                                 deadline_s, timeout, priority, rid)
        self._m_fanout.observe(clock.now() - t0)
        out: list = [None] * len(ops)
        first_err: tuple | None = None  # (submission idx, exception)
        for s, entries in groups.items():
            result = by_shard[s]
            if isinstance(result, BaseException):
                pairs = [("err", result)] * len(entries)
            else:
                pairs = result
            acked = 0
            for (idx, _op), (status, val) in zip(entries, pairs):
                out[idx] = val
                if status == "ok":
                    acked += 1
                elif first_err is None or idx < first_err[0]:
                    first_err = (idx, val)
            if acked:
                self._shard_counters(s)[1].inc(acked)
        if first_err is not None and not return_exceptions:
            raise first_err[1]
        return out

    def call(self, op: tuple, rid: int = 0,
             deadline_s: float | None = None,
             timeout: float | None = None,
             priority: int | None = None):
        """Single-op closed loop (the `call_with_retry` surface):
        route, submit, return the result or raise its typed error."""
        return self.execute_batch(
            [op], deadline_s=deadline_s, timeout=timeout,
            priority=priority, rid=rid,
        )[0]

    def close(self) -> None:
        with self._lock:
            backends = list(self._backends.values())
            self._backends.clear()
        for b in backends:
            b.close()
