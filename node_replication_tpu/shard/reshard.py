"""`ReshardPlan`: live keyspace splits (and quiesced merges) on a
sharded fleet.

The congruence refinement that makes an ONLINE split possible at all:
shard `s` of `N` owns class `s (mod N)`, and that class partitions
EXACTLY into classes `{s, s + N}` under `mod 2N` — so doubling the
map (`ShardMap.refine`) moves only the keys whose new class is
re-homed, and every other key keeps its shard without copying a byte.
The recipient for the moved half is the donor's OWN standby: the
follower already holds a full, continuously-caught-up copy of the
donor's state (seeded through the replication feed), so "seed the
recipient" is the replication plane's steady state, not a bulk copy.

Split cutover, in order:

1. **catch-up** — wait until the follower's applied cursor is at the
   donor's durable tail (bounds the drain below);
2. **stage** — build backends (and 2PC participants) for every
   refined class and `router.attach_backend` them: inert, because no
   key routes to a class beyond the current map;
3. **fence** — publish the refined map and `router.adopt` it. From
   this instant moved-key submits land on the recipient's backend,
   which refuses retryably (`NotPrimary` → `ShardUnavailable`) until
   its promotion completes — the moved keys' unavailability clock
   starts here, and ship-before-ack guarantees every PREVIOUSLY
   acked moved-key write is already in the feed;
4. **consume the standby** — stop the donor's shipper and drop its
   ack barrier (the follower it shipped to is being promoted away;
   the donor keeps serving its half WAL-durable and un-replicated
   until the operator attaches a new standby);
5. **promote** — the follower fences the feed epoch, drains the
   remaining records (bounded: the shipper is stopped), fsyncs, and
   enables writes. Moved keys are available again the moment this
   returns: the unavailability window is the FENCE WINDOW
   (catch-up lag + drain), never proportional to state size.

The recipient retains fenced copies of the donor's unmoved keys
(and vice versa) — unreachable by construction, since every submit
path re-checks the congruence at the door (`LocalBackend`,
nrlint rule `unrouted-key-in-shard-path`).

`merge` is the inverse, but QUIESCED, not live: the moved class's
history is replayed through the survivor's frontend, so the merge
window is proportional to the folded class's HISTORY SIZE — the
documented asymmetry (splits are cheap and online; merges are an
operator maintenance action). Order matters here too: the folded
shard's frontend is closed FIRST (acks drained), the history
replayed SECOND, and the coarsened map adopted LAST — adopting
before the replay would route moved keys to the survivor's stale
copy and let a fresh ack be overwritten by replayed history.
"""

from __future__ import annotations

import dataclasses
import os

from node_replication_tpu.obs import get_registry, get_tracer
from node_replication_tpu.utils.clock import get_clock
from node_replication_tpu.shard.router import LocalBackend


class ReshardError(RuntimeError):
    """A split/merge precondition failed — nothing was changed."""


@dataclasses.dataclass
class ReshardReport:
    """What one split/merge did (JSON-safe)."""

    kind: str                 # "split" | "merge"
    donor: int                # class that split (or absorbed)
    moved: int                # the re-homed class (donor + N)
    old_version: int
    new_version: int
    catchup_s: float          # split: follower catch-up wait
    fence_s: float            # moved-key unavailability window
    drained_records: int      # split: promote drain / merge: replayed
    duration_s: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class ReshardPlan:
    """One split (and its optional inverse merge) on a `ShardGroup`.

        plan = ReshardPlan(group, donor=0)
        report = plan.split()          # class 0 of N → {0, N} of 2N
        ...
        report = plan.merge()          # fold class N back into 0

    The plan object owns the split's bookkeeping (which follower
    became which shard), so the merge knows exactly what to fold
    back. One plan = one split; run another plan to split again.
    """

    def __init__(self, group, donor: int):
        self.group = group
        self.donor = int(donor)
        self.split_report: ReshardReport | None = None
        self._recipient = None        # the promoted Follower
        self._recipient_txn = None
        self._alias_txns: list = []
        if not (0 <= self.donor < len(group.primaries)):
            raise ReshardError(f"donor {donor} out of range")

    # ------------------------------------------------------------ split

    def split(self, catchup_timeout_s: float = 10.0,
              drain_timeout_s: float = 10.0) -> ReshardReport:
        """Refine the map in place: class `donor` of `N` splits into
        `{donor, donor + N}` of `2N`, the moved half re-homed onto
        the donor's promoted follower. Live except for the moved
        keys' fence window (measured, returned)."""
        g = self.group
        p = g.primaries[self.donor]
        clock = get_clock()
        t0 = clock.now()
        if self.split_report is not None:
            raise ReshardError("plan already split; build a new plan")
        if p._primary_dead:
            raise ReshardError(f"donor {self.donor} primary is dead")
        if p.follower is None or p.follower.promoted:
            raise ReshardError(
                f"donor {self.donor} has no promotable follower to "
                f"receive the moved class"
            )
        if p.txn is not None and p.txn.has_locks():
            # a prepared-but-undecided txn's locked keys may be in the
            # MOVED half; committing it after the cutover would apply
            # through the donor's frontend onto a fenced copy. The
            # operator quiesces the coordinator first.
            raise ReshardError(
                f"donor {self.donor} has prepared transactions in "
                f"flight; resolve them before splitting"
            )
        old_map = g.map
        n = old_map.n_shards
        moved = self.donor + n

        # 1. catch-up: bound the promote drain by waiting until the
        # follower has applied (and journaled) the donor's current
        # durable tail. New writes keep landing — that remainder is
        # exactly what the drain folds inside the fence window.
        target = p.wal.tail
        p.follower.wait_applied(target, timeout=catchup_timeout_s)
        t_caught = clock.now()

        # 2. stage: a backend (+ participant) for every refined class,
        # attached without a map change — inert until adoption.
        new_map = old_map.refine()
        from node_replication_tpu.shard.txn import TxnParticipant

        def _participant(shard, frontend, wal):
            if g.decisions is None:
                return None
            t = TxnParticipant(
                shard, frontend, new_map,
                os.path.join(g.base_dir, f"r{shard}", "txn"),
                decisions=g.decisions, wal=wal,
            )
            g.extra_participants.append(t)
            return t

        for d in range(n):
            if d == self.donor:
                continue
            q = g.primaries[d]
            alias_txn = _participant(d + n, q.live_frontend, q.wal)
            self._alias_txns.append(alias_txn)
            g.router.attach_backend(
                d + n,
                LocalBackend(d + n, q.live_frontend, new_map,
                             participant=alias_txn),
            )
        self._recipient = p.follower
        self._recipient_txn = _participant(
            moved, p.follower.frontend, p.follower.nr.wal
        )
        g.router.attach_backend(
            moved,
            LocalBackend(moved, p.follower.frontend, new_map,
                         participant=self._recipient_txn),
        )

        # 3. fence: publish + adopt. Moved-key submits now land on
        # the recipient backend and refuse retryably until the
        # promotion below completes — the unavailability clock.
        t_fence = clock.now()
        donor_backend = g.router.backend(self.donor)
        new_map.publish(g.base_dir)
        g.router.adopt(new_map, reason=f"split-s{self.donor}")

        # 3b. quiesce the OLD epoch: a submit that passed the donor's
        # old-version check just before the adopt may still be in its
        # check-then-stage window — wait for those calls to finish
        # acking (ship barrier still armed) so no acked moved-key
        # write can miss the drain below.
        if donor_backend is not None and not donor_backend.quiesce(
                timeout=drain_timeout_s):
            raise ReshardError(
                f"donor {self.donor} submit pipeline failed to "
                f"quiesce within {drain_timeout_s}s"
            )

        # 4. the split consumes the donor's standby: stop shipping
        # (the promote's epoch fence would reject it anyway) and drop
        # the ack barrier — the donor serves on WAL durability alone
        # until a new standby is attached.
        p.shipper.stop(clear_pin=False)
        p.frontend.ack_barrier = None

        # 5. promote: feed epoch fence + bounded drain + fsync +
        # enable_writes. Every moved-key ack issued before the fence
        # was shipped before it was acked, so the drain carries ALL
        # of them into the recipient.
        promo = p.follower.promote(drain_timeout_s=drain_timeout_s)
        t_open = clock.now()

        # bookkeeping: the follower now IS shard `moved`, not the
        # donor's standby — detach it so `live_frontend` (and any
        # later promotion of the donor) stays the donor's own stack.
        p.follower = None
        p.manager = None
        g.map = new_map
        for q in g.primaries:
            q.map = new_map
            if q.txn is not None:
                q.txn.set_map(new_map)

        rep = ReshardReport(
            kind="split", donor=self.donor, moved=moved,
            old_version=old_map.version, new_version=new_map.version,
            catchup_s=t_caught - t0, fence_s=t_open - t_fence,
            drained_records=int(promo.get("drained_records", 0)),
            duration_s=clock.now() - t0,
        )
        self.split_report = rep
        get_registry().counter("shard.splits").inc()
        get_tracer().emit(
            "shard-split", donor=self.donor, moved=moved,
            map_version=new_map.version, fence_s=rep.fence_s,
        )
        return rep

    # ------------------------------------------------------------ merge

    def merge(self, apply_timeout_s: float = 10.0) -> ReshardReport:
        """Fold class `donor + N` back into class `donor`: quiesce
        the moved class, replay its FULL history through the donor's
        frontend, then adopt the coarsened map. The window is
        history-sized — a maintenance action, not a live cutover."""
        g = self.group
        if self.split_report is None:
            raise ReshardError("nothing to merge: plan never split")
        if self.split_report.kind == "merge":
            raise ReshardError("plan already merged")
        clock = get_clock()
        t0 = clock.now()
        p = g.primaries[self.donor]
        old_map = g.map
        n2 = old_map.n_shards
        moved = self.donor + n2 // 2
        recip = self._recipient
        wal = recip.nr.wal
        if wal.base > 0:
            raise ReshardError(
                f"shard {moved}'s WAL history starts at {wal.base}, "
                f"not 0 (reclaimed): the folded class cannot be "
                f"reconstructed by replay"
            )
        for t in ([p.txn, self._recipient_txn] + self._alias_txns):
            if t is not None and t.has_locks():
                raise ReshardError(
                    "prepared transactions in flight; resolve them "
                    "before merging"
                )

        # 1. quiesce the moved class: close its frontend (drains
        # in-flight acks first). Moved-key submits now refuse
        # retryably — the merge window opens.
        t_fence = clock.now()
        recip.frontend.close(drain=True)

        # 2. replay the moved class's history, in order, through the
        # donor. The recipient's WAL holds the donor's FULL pre-split
        # history plus the post-split writes; filtering to the moved
        # congruence class replays exactly the keys being folded
        # back, and a deterministic state machine replayed from
        # position 0 reproduces the recipient's final values.
        replayed = 0
        futs = []
        for rec in wal.records(0):
            for op in rec.ops():
                if old_map.shard_of_op(op) != moved:
                    continue
                futs.append(p.frontend.submit(tuple(op)))
                replayed += 1
        for f in futs:
            f.result(apply_timeout_s)

        # 3. coarsen + publish + adopt LAST: only now do moved keys
        # route to the donor, whose state is caught up. The merge
        # window closes.
        new_map = old_map.coarsen()
        new_map.publish(g.base_dir)
        g.router.adopt(new_map, reason=f"merge-s{moved}")
        t_open = clock.now()

        g.map = new_map
        for q in g.primaries:
            q.map = new_map
            if q.txn is not None:
                q.txn.set_map(new_map)
        recip.close()

        rep = ReshardReport(
            kind="merge", donor=self.donor, moved=moved,
            old_version=old_map.version, new_version=new_map.version,
            catchup_s=0.0, fence_s=t_open - t_fence,
            drained_records=replayed,
            duration_s=clock.now() - t0,
        )
        self.split_report = rep
        get_registry().counter("shard.merges").inc()
        get_tracer().emit(
            "shard-merge", donor=self.donor, moved=moved,
            map_version=new_map.version, fence_s=rep.fence_s,
            replayed=replayed,
        )
        return rep
