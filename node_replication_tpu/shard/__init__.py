"""Fleet-level keyspace sharding: the paper's CNR partitioning lifted
from logs to primaries.

- `ring`: `ShardMap` — the deterministic `key % N` congruence map,
  versioned + durably published.
- `router`: `ShardRouter` (split → fan out → reassemble) over
  `LocalBackend` / `SocketShardClient` backends, and `ShardServer`,
  the shard primary's CRC-framed submit endpoint.
- `primary`: `ShardPrimary` / `ShardGroup` — N primaries, each with
  its own WAL, epoch, shipper, and follower tree.

Cross-shard batches are explicitly NOT atomic (the CNR contract);
see `shard/router.py` and README "Keyspace sharding".
"""

from node_replication_tpu.shard.primary import ShardGroup, ShardPrimary
from node_replication_tpu.shard.ring import MAP_FILENAME, ShardMap
from node_replication_tpu.shard.router import (
    LocalBackend,
    ShardRouter,
    ShardServer,
    SocketShardClient,
)

__all__ = [
    "MAP_FILENAME",
    "LocalBackend",
    "ShardGroup",
    "ShardMap",
    "ShardPrimary",
    "ShardRouter",
    "ShardServer",
    "SocketShardClient",
]
