"""Fleet-level keyspace sharding: the paper's CNR partitioning lifted
from logs to primaries.

- `ring`: `ShardMap` — the deterministic `key % N` congruence map,
  versioned + durably published, with `refine`/`coarsen` for live
  splits and merges.
- `router`: `ShardRouter` (split → fan out → reassemble) over
  `LocalBackend` / `SocketShardClient` backends, and `ShardServer`,
  the shard primary's CRC-framed submit endpoint.
- `primary`: `ShardPrimary` / `ShardGroup` — N primaries, each with
  its own WAL, epoch, shipper, and follower tree.
- `txn`: `TxnCoordinator` / `TxnParticipant` — presumed-abort 2PC
  for atomic cross-shard transactions (durable intent journal,
  durable decision publish BEFORE any ack).
- `reshard`: `ReshardPlan` — online split of a congruence class
  (`s` of `N` → `{s, s+N}` of `2N`) and its quiesced merge inverse.

Cross-shard BATCHES remain explicitly NOT atomic (the CNR contract);
atomic cross-shard writes go through the transaction layer. See
`shard/router.py`, `shard/txn.py`, and README "Keyspace sharding".
"""

from node_replication_tpu.shard.primary import ShardGroup, ShardPrimary
from node_replication_tpu.shard.reshard import (
    ReshardError,
    ReshardPlan,
    ReshardReport,
)
from node_replication_tpu.shard.ring import (
    MAP_FILENAME,
    ShardMap,
    ShardMapCorruptError,
)
from node_replication_tpu.shard.router import (
    LocalBackend,
    ShardRouter,
    ShardServer,
    SocketShardClient,
)
from node_replication_tpu.shard.txn import TxnCoordinator, TxnParticipant

__all__ = [
    "MAP_FILENAME",
    "LocalBackend",
    "ReshardError",
    "ReshardPlan",
    "ReshardReport",
    "ShardGroup",
    "ShardMap",
    "ShardMapCorruptError",
    "ShardPrimary",
    "ShardRouter",
    "ShardServer",
    "SocketShardClient",
    "TxnCoordinator",
    "TxnParticipant",
]
