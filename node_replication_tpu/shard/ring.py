"""`ShardMap`: the deterministic keyspace → shard congruence map.

The fleet-level twin of the in-process `LogMapper` (PAPER.md's CNR
layer; `models/partitioned.py` pins the same contract for device
state): shard `s` of `N` owns every key `k` with `k % N == s`, where
an op's key is `args[0]` — exactly the commutativity hash the benches
and `MultiLogReplicated` use (`hash = args[0] % nlogs`). Two
consequences the router relies on:

- **determinism**: any two parties holding the same `(n_shards,
  version)` route every op identically, with no coordination;
- **commutativity across shards**: ops on different congruence
  classes touch disjoint keys, so per-shard sub-batches may execute
  concurrently and acks interleave freely — which is also why a
  cross-shard batch is explicitly NOT atomic (see `shard/router.py`).

The map is **versioned and durably published**: `publish()` writes
the JSON document through `durable_publish` (atomic tmp + fsync +
rename), so a concurrent reader observes either the previous complete
map or the new complete map, never a torn one — the same discipline
every other control file in the repo follows. Routers and shards
compare versions on every (re)connect; a mismatch is a typed
`WrongShard`, never a silent mis-route. Promotions bump the version
(`with_address`) so a router that re-homed a shard's writes can prove
any stale peer wrong.
"""

from __future__ import annotations

import dataclasses
import json
import os

from node_replication_tpu.durable.wal import durable_publish

#: default published filename inside a fleet's shared directory
MAP_FILENAME = "shard_map.json"


class ShardMapCorruptError(RuntimeError):
    """A published `shard_map.json` failed validation on load.

    The `WalCorruptError`/`SnapshotCorruptError` discipline applied to
    the routing control file: `durable_publish` guarantees a reader
    never sees a TORN document, so a file that fails to parse — or
    parses into an inconsistent map (address count != `n_shards`,
    non-positive version) — is bit rot or a hand edit, and must be a
    TYPED refusal the router's `refresh_map()` can survive (keep the
    old map, count `shard.map_corrupt`) rather than a raw
    `JSONDecodeError`/`KeyError` escaping into the retry path."""

    def __init__(self, path: str | None, detail: str):
        where = f" at {path}" if path else ""
        super().__init__(f"corrupt shard map{where}: {detail}")
        self.path = path
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class ShardMap:
    """Immutable, versioned keyspace map.

    `addresses[s]` is shard `s`'s submit endpoint — `[host, port]`
    for a socket backend, `None` for a local/in-process one. Equality
    of `(n_shards, version)` is the routing agreement the fleet
    checks; addresses are advisory (how to reach the shard), the
    congruence is the contract (which keys it owns).
    """

    n_shards: int
    version: int = 1
    addresses: tuple = ()

    def __post_init__(self):
        if self.n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if self.version < 1:
            raise ValueError("version must be >= 1")
        addrs = tuple(
            tuple(a) if a is not None else None for a in self.addresses
        ) or (None,) * self.n_shards
        if len(addrs) != self.n_shards:
            raise ValueError(
                f"{len(addrs)} addresses for {self.n_shards} shards"
            )
        object.__setattr__(self, "addresses", addrs)

    # ---------------------------------------------------------- routing

    def shard_of(self, key: int) -> int:
        """Owning shard of `key`: the `key % N` congruence class."""
        return int(key) % self.n_shards

    def shard_of_op(self, op) -> int:
        """Owning shard of one op `(opcode, *args)` — the key is
        `args[0]`, matching the benches' LogMapper and the
        partitioned model's congruence contract."""
        if len(op) < 2:
            raise ValueError(f"op {op!r} has no key argument")
        return self.shard_of(op[1])

    def split_batch(self, ops) -> dict[int, list[tuple[int, tuple]]]:
        """Partition a batch into per-shard sub-batches, keeping each
        op's submission index so responses reassemble in submission
        order. Within one shard the sub-batch preserves submission
        order; ACROSS shards sub-batches are independent (disjoint
        congruence classes — the CNR commutativity argument)."""
        groups: dict[int, list[tuple[int, tuple]]] = {}
        for i, op in enumerate(ops):
            groups.setdefault(self.shard_of_op(op), []).append(
                (i, tuple(op))
            )
        return groups

    # ------------------------------------------------------- publication

    def with_address(self, shard: int, address) -> "ShardMap":
        """A NEW map with `shard` re-pointed (a promotion re-homing
        its writes) and the version bumped — publish it so every
        router and shard can prove stale peers wrong."""
        if not (0 <= int(shard) < self.n_shards):
            raise ValueError(f"shard {shard} out of range")
        addrs = list(self.addresses)
        addrs[int(shard)] = tuple(address) if address is not None \
            else None
        return ShardMap(self.n_shards, self.version + 1, tuple(addrs))

    def refine(self, overrides: dict | None = None) -> "ShardMap":
        """The reshard doubling (`shard/reshard.py`): every class `s`
        of `N` refines into `{s, s + N}` under `mod 2N` — a key in
        class `s (mod N)` is in class `s` or `s + N (mod 2N)`, never
        anywhere else, so the refinement moves ONLY the keys whose new
        class is re-addressed. By default class `s + N` keeps class
        `s`'s address (the same primary serves both halves until a
        split re-homes one); `overrides` maps new-shard → address for
        the re-homed slices. Version bumps once."""
        addrs = list(self.addresses) * 2
        for s, addr in (overrides or {}).items():
            if not (0 <= int(s) < 2 * self.n_shards):
                raise ValueError(f"shard {s} out of range for refine")
            addrs[int(s)] = tuple(addr) if addr is not None else None
        return ShardMap(2 * self.n_shards, self.version + 1,
                        tuple(addrs))

    def coarsen(self) -> "ShardMap":
        """The merge inverse of `refine`: classes `{s, s + N}` under
        `mod 2N` collapse back into class `s` under `mod N`, each
        merged class served at the LOWER half's address. Requires an
        even shard count (only a refined map coarsens)."""
        if self.n_shards % 2:
            raise ValueError(
                f"cannot coarsen an odd shard count ({self.n_shards})"
            )
        half = self.n_shards // 2
        return ShardMap(half, self.version + 1,
                        tuple(self.addresses[:half]))

    def as_dict(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "version": self.version,
            "addresses": [list(a) if a is not None else None
                          for a in self.addresses],
        }

    @classmethod
    def from_dict(cls, d: dict, path: str | None = None) -> "ShardMap":
        """Validate + build. EVERY defect in the document — missing
        keys, non-numeric fields, an address list whose length
        disagrees with `n_shards` — is a typed `ShardMapCorruptError`
        so the router's refresh path can keep its old map instead of
        crashing on a raw `KeyError`."""
        try:
            n_shards = int(d["n_shards"])
            version = int(d["version"])
            addresses = tuple(
                tuple(a) if a is not None else None
                for a in d.get("addresses", [])
            )
        except (KeyError, TypeError, ValueError) as e:
            raise ShardMapCorruptError(
                path, f"{type(e).__name__}: {e}"
            ) from e
        if addresses and len(addresses) != n_shards:
            raise ShardMapCorruptError(
                path,
                f"{len(addresses)} addresses for {n_shards} shards",
            )
        try:
            return cls(n_shards=n_shards, version=version,
                       addresses=addresses)
        except ValueError as e:
            raise ShardMapCorruptError(path, str(e)) from e

    def publish(self, path: str) -> None:
        """Durably publish this map (atomic tmp + fsync + rename via
        `durable_publish`) so routers and shards agree across
        restarts. `path` may be a directory (the fleet's shared dir;
        the map lands at `<path>/shard_map.json`) or a file path."""
        if os.path.isdir(path):
            path = os.path.join(path, MAP_FILENAME)
        durable_publish(
            path,
            json.dumps(self.as_dict(), sort_keys=True).encode(),
        )

    @classmethod
    def load(cls, path: str) -> "ShardMap":
        """Load a published map. Always observes a COMPLETE document
        (the `durable_publish` rename guarantee) — so a document that
        does not parse/validate is corruption or a hand edit, raised
        as typed `ShardMapCorruptError` (missing file stays a plain
        `FileNotFoundError`: absent and corrupt are different
        failures)."""
        if os.path.isdir(path):
            path = os.path.join(path, MAP_FILENAME)
        with open(path, "rb") as f:
            raw = f.read()
        try:
            doc = json.loads(raw.decode())
        except ValueError as e:
            raise ShardMapCorruptError(path, f"bad JSON: {e}") from e
        if not isinstance(doc, dict):
            raise ShardMapCorruptError(
                path, f"expected an object, got {type(doc).__name__}"
            )
        return cls.from_dict(doc, path=path)
