"""`ShardMap`: the deterministic keyspace → shard congruence map.

The fleet-level twin of the in-process `LogMapper` (PAPER.md's CNR
layer; `models/partitioned.py` pins the same contract for device
state): shard `s` of `N` owns every key `k` with `k % N == s`, where
an op's key is `args[0]` — exactly the commutativity hash the benches
and `MultiLogReplicated` use (`hash = args[0] % nlogs`). Two
consequences the router relies on:

- **determinism**: any two parties holding the same `(n_shards,
  version)` route every op identically, with no coordination;
- **commutativity across shards**: ops on different congruence
  classes touch disjoint keys, so per-shard sub-batches may execute
  concurrently and acks interleave freely — which is also why a
  cross-shard batch is explicitly NOT atomic (see `shard/router.py`).

The map is **versioned and durably published**: `publish()` writes
the JSON document through `durable_publish` (atomic tmp + fsync +
rename), so a concurrent reader observes either the previous complete
map or the new complete map, never a torn one — the same discipline
every other control file in the repo follows. Routers and shards
compare versions on every (re)connect; a mismatch is a typed
`WrongShard`, never a silent mis-route. Promotions bump the version
(`with_address`) so a router that re-homed a shard's writes can prove
any stale peer wrong.
"""

from __future__ import annotations

import dataclasses
import json
import os

from node_replication_tpu.durable.wal import durable_publish

#: default published filename inside a fleet's shared directory
MAP_FILENAME = "shard_map.json"


@dataclasses.dataclass(frozen=True)
class ShardMap:
    """Immutable, versioned keyspace map.

    `addresses[s]` is shard `s`'s submit endpoint — `[host, port]`
    for a socket backend, `None` for a local/in-process one. Equality
    of `(n_shards, version)` is the routing agreement the fleet
    checks; addresses are advisory (how to reach the shard), the
    congruence is the contract (which keys it owns).
    """

    n_shards: int
    version: int = 1
    addresses: tuple = ()

    def __post_init__(self):
        if self.n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if self.version < 1:
            raise ValueError("version must be >= 1")
        addrs = tuple(
            tuple(a) if a is not None else None for a in self.addresses
        ) or (None,) * self.n_shards
        if len(addrs) != self.n_shards:
            raise ValueError(
                f"{len(addrs)} addresses for {self.n_shards} shards"
            )
        object.__setattr__(self, "addresses", addrs)

    # ---------------------------------------------------------- routing

    def shard_of(self, key: int) -> int:
        """Owning shard of `key`: the `key % N` congruence class."""
        return int(key) % self.n_shards

    def shard_of_op(self, op) -> int:
        """Owning shard of one op `(opcode, *args)` — the key is
        `args[0]`, matching the benches' LogMapper and the
        partitioned model's congruence contract."""
        if len(op) < 2:
            raise ValueError(f"op {op!r} has no key argument")
        return self.shard_of(op[1])

    def split_batch(self, ops) -> dict[int, list[tuple[int, tuple]]]:
        """Partition a batch into per-shard sub-batches, keeping each
        op's submission index so responses reassemble in submission
        order. Within one shard the sub-batch preserves submission
        order; ACROSS shards sub-batches are independent (disjoint
        congruence classes — the CNR commutativity argument)."""
        groups: dict[int, list[tuple[int, tuple]]] = {}
        for i, op in enumerate(ops):
            groups.setdefault(self.shard_of_op(op), []).append(
                (i, tuple(op))
            )
        return groups

    # ------------------------------------------------------- publication

    def with_address(self, shard: int, address) -> "ShardMap":
        """A NEW map with `shard` re-pointed (a promotion re-homing
        its writes) and the version bumped — publish it so every
        router and shard can prove stale peers wrong."""
        if not (0 <= int(shard) < self.n_shards):
            raise ValueError(f"shard {shard} out of range")
        addrs = list(self.addresses)
        addrs[int(shard)] = tuple(address) if address is not None \
            else None
        return ShardMap(self.n_shards, self.version + 1, tuple(addrs))

    def as_dict(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "version": self.version,
            "addresses": [list(a) if a is not None else None
                          for a in self.addresses],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ShardMap":
        return cls(
            n_shards=int(d["n_shards"]),
            version=int(d["version"]),
            addresses=tuple(
                tuple(a) if a is not None else None
                for a in d.get("addresses", [])
            ),
        )

    def publish(self, path: str) -> None:
        """Durably publish this map (atomic tmp + fsync + rename via
        `durable_publish`) so routers and shards agree across
        restarts. `path` may be a directory (the fleet's shared dir;
        the map lands at `<path>/shard_map.json`) or a file path."""
        if os.path.isdir(path):
            path = os.path.join(path, MAP_FILENAME)
        durable_publish(
            path,
            json.dumps(self.as_dict(), sort_keys=True).encode(),
        )

    @classmethod
    def load(cls, path: str) -> "ShardMap":
        """Load a published map. Always observes a COMPLETE document
        (the `durable_publish` rename guarantee)."""
        if os.path.isdir(path):
            path = os.path.join(path, MAP_FILENAME)
        with open(path, "rb") as f:
            return cls.from_dict(json.loads(f.read().decode()))
