"""Backward-compatible alias for the flight recorder.

The structured tracer grew into the observability layer and lives in
`node_replication_tpu/obs/recorder.py` (ring-buffered in-memory mode,
monotonic timestamps, fence-accurate spans under NR_TPU_TRACE_FENCE=1);
`obs/metrics.py` holds the process-wide metrics registry and
`obs/report.py` the trace-report CLI. This module keeps the original
import surface (`from node_replication_tpu.utils.trace import
get_tracer, span`) working.
"""

from node_replication_tpu.obs.recorder import (  # noqa: F401
    Tracer,
    get_tracer,
    pos_sampled,
    span,
)

__all__ = ["Tracer", "get_tracer", "pos_sampled", "span"]
