"""Structured tracing: timestamped JSONL events + timing spans.

The reference's observability story is the `log` crate facade plus
spin-loop diagnostics every WARN_THRESHOLD iterations
(`nr/src/lib.rs:80-81`, `nr/src/log.rs:351-358`) and the harness's
per-second throughput counters (`benches/mkbench.rs:755-761`). This module
is the TPU build's equivalent: a process-wide `Tracer` that appends JSONL
events (`{"ts", "event", ...fields}`) to a file or collects them in
memory, plus a `span` context manager for timing named sections.

Disabled by default (no overhead beyond one branch); enable with
`NR_TPU_TRACE=<path>` or `get_tracer().enable(...)`.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._buffer: list[dict] | None = None
        self.enabled = False

    def enable(self, path: str | None = None) -> None:
        """Write events to `path`, or buffer in memory when path is None."""
        with self._lock:
            if path:
                self._fh = open(path, "a", buffering=1)
                self._buffer = None
            else:
                self._fh = None
                self._buffer = []
            self.enabled = True

    def disable(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
            self._fh = None
            self._buffer = None
            self.enabled = False

    def emit(self, event: str, **fields: Any) -> None:
        if not self.enabled:
            return
        rec = {"ts": time.time(), "event": event, **fields}
        with self._lock:
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
            elif self._buffer is not None:
                self._buffer.append(rec)

    def events(self) -> list[dict]:
        """Buffered events (memory mode only)."""
        with self._lock:
            return list(self._buffer or [])


_tracer = Tracer()
if os.environ.get("NR_TPU_TRACE"):
    _tracer.enable(os.environ["NR_TPU_TRACE"])


def get_tracer() -> Tracer:
    return _tracer


@contextlib.contextmanager
def span(event: str, **fields: Any):
    """Time a section; emits `<event>` with `duration_s` on exit."""
    t = _tracer
    if not t.enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t.emit(event, duration_s=time.perf_counter() - t0, **fields)
