"""JAX version-compat shims (0.4.x <-> 0.5+ API moves).

The seed targeted a newer jax surface; the pinned container runs jax
0.4.x. Two APIs moved between those lines and broke 21 tier-1 tests at
the seed (every `tests/test_pallas*` and `tests/test_collectives.py`
failure — see BENCH_NOTES.md triage):

- `jax.enable_x64(False)` (0.5+ parametrized context manager) vs
  `jax.experimental.disable_x64()` (0.4.x): used by the pallas kernels
  to trace pure-int32 programs under the package's global x64 mode.
- `jax.shard_map` (0.5+) vs `jax.experimental.shard_map.shard_map`
  (0.4.x): the explicit-collective multi-chip step.

Import from here; never touch the moved names directly.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.5
    shard_map = jax.shard_map
except AttributeError:  # jax 0.4.x: experimental home + check_rep kwarg
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map_04

    @functools.wraps(_shard_map_04)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:  # 0.5+ renamed check_rep -> check_vma
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_04(*args, **kwargs)


def x64_disabled():
    """Context manager: trace with x64 disabled (pallas kernels build
    pure-int32 programs while the package globally enables x64)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    from jax.experimental import disable_x64

    return disable_x64()
