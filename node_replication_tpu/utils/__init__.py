"""Utilities: tracing/observability helpers."""

from node_replication_tpu.utils.trace import Tracer, get_tracer, span

__all__ = ["Tracer", "get_tracer", "span"]
