"""Utilities: tracing/observability helpers + the injectable clock."""

from node_replication_tpu.utils.clock import (
    Clock,
    RealClock,
    SimClock,
    get_clock,
    set_clock,
)
from node_replication_tpu.utils.trace import Tracer, get_tracer, span

__all__ = [
    "Clock",
    "RealClock",
    "SimClock",
    "Tracer",
    "get_clock",
    "get_tracer",
    "set_clock",
    "span",
]
