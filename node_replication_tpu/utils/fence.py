"""A real device barrier for timed regions.

Round-3 discovery: on the tunneled `axon` TPU platform,
`jax.block_until_ready` returns as soon as the remote enqueue is
acknowledged — NOT when the computation finishes. Measured: 1000 chained
4096^3 matmuls (>1 s of genuine device work) "block" in ~1 ms, after which
a scalar readback waits 3.4 s for the backlog; 163 queued replay steps
"blocked" in 4 ms and the following readback took 95.2 s (exactly 163 x
the true 0.58 s/step). Every throughput number measured by fencing with
`block_until_ready` on this platform (rounds 1-2) was therefore a
dispatch-rate measurement, not a device-throughput measurement.

The only true barrier is a data-dependent device→host readback. `fence`
folds one element of every array leaf into a single scalar on device and
fetches it — one tiny D2H transfer total, which cannot complete until
every computation feeding those leaves has actually executed.

On platforms where `block_until_ready` is sound (CPU tests, untunneled
TPU) the readback is equivalent and costs one transfer.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def fence(*trees) -> None:
    """Block until every computation producing the given pytrees has
    really finished on device (see module docstring for why
    `jax.block_until_ready` is not enough)."""
    leaves = [
        leaf
        for tree in trees
        for leaf in jax.tree_util.tree_leaves(tree)
        # size-0 leaves carry no pending data (and cannot be indexed)
        if isinstance(leaf, jax.Array) and leaf.size
    ]
    if not leaves:
        return
    acc = None
    for leaf in leaves:
        v = leaf[(0,) * leaf.ndim] if leaf.ndim else leaf
        v = v.astype(jnp.float32)
        acc = v if acc is None else acc + v
    np.asarray(acc)  # the one data-dependent D2H: the true barrier
