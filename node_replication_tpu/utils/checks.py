"""Device-side defensive invariants (checkify debug mode).

The reference guards its cursor invariants with `panic!`s compiled into
every build — exec with a local tail ahead of the global tail
(`nr/src/log.rs:487-489`) and context batch-index bounds
(`nr/src/context.rs:145-148`, `186-190`). Compiled XLA code cannot panic,
so the device path historically clamped/dropped silently. This module is
the opt-in equivalent: `jax.experimental.checkify` checks inserted at the
same invariant points.

Two flags with different blast radii:

- `NR_TPU_DEBUG=1` (env) flips the DEFAULT of `NodeReplicated(debug=...)`
  to True — the end-to-end debug mode. It deliberately does NOT make
  `check()` fire globally: a live `checkify.check` inside a jit that was
  never `checked()`-wrapped is a trace-time error, so arming checks
  process-wide would crash every unwrapped jit in the library.
- `debug_checks(True)` (context manager) arms `check()` for code traced
  inside it — use it only around calls whose functions are `checked()`-
  functionalized (as `NodeReplicated` does internally). With the flag
  off, `check()` is a no-op at trace time and the compiled program is
  bit-identical to the unchecked one (zero cost off).

Usage of this module (instead of raw `checkify.check`, which bypasses
the arming contract above) is machine-enforced by the nrlint rule
`raw-checkify-check` (`node_replication_tpu/analysis/`, run as
`python -m node_replication_tpu.analysis.lint node_replication_tpu/`).
"""

from __future__ import annotations

import contextlib
import contextvars
import os

from jax.experimental import checkify

# Context-local arming flag: `debug_checks()` must only arm `check()`
# for code traced in THIS thread/task. A module-global here would let
# one thread's debug context manager arm checks inside another
# thread's concurrently-tracing un-functionalized jit — a trace-time
# crash injected across threads. A ContextVar is inherited by the
# arming thread's own nested traces (tracing runs synchronously in the
# calling thread) and by nothing else.
_ctx_enabled: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "nr_tpu_debug_checks", default=False
)


def debug_default() -> bool:
    """Default for `NodeReplicated(debug=...)` (the NR_TPU_DEBUG env)."""
    return os.environ.get("NR_TPU_DEBUG", "") == "1"


def debug_checks_enabled() -> bool:
    return _ctx_enabled.get()


@contextlib.contextmanager
def debug_checks(on: bool = True):
    """Arm `check()` for functions traced within (tracing happens at the
    first CALL of a jitted function, not at `jax.jit`). Only wrap calls
    to `checked()`-functionalized functions. Thread-local: arming here
    never affects traces running concurrently in other threads."""
    token = _ctx_enabled.set(on)
    try:
        yield
    finally:
        _ctx_enabled.reset(token)


def check(pred, msg: str, **fmt) -> None:
    """Emit a checkify invariant when armed at trace time; no-op (and no
    cost in the compiled program) otherwise."""
    if _ctx_enabled.get():
        checkify.check(pred, msg, **fmt)


def checked(fn):
    """Functionalize a fn containing `check()` calls:
    `checked(fn)(*a) -> (err, out)`; surface with `err.throw()`."""
    return checkify.checkify(fn, errors=checkify.user_checks)
