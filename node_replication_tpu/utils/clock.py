"""Injectable time source for every timed wait in the runtime planes.

Every subsystem that waits on time — the serve frontend's linger and
deadline sweeps, the retry client's backoff, the health machine's
timeline stamps, the shipper/follower poll loops, the promotion
watcher's heartbeat silence, the WAL's fsync spans — routes through
ONE process-global `Clock` (`get_clock()`), so the simulation plane
(`sim/`) can substitute virtual time and turn every timing-dependent
robustness gate into a fast, reproducible unit test (the FoundationDB
simulation-testing idiom). The nrlint rule `raw-clock-in-subsystem`
machine-checks the routing: a direct `time.monotonic()` /
`time.sleep()` / `Condition.wait()` inside serve/, fault/, repl/, or
durable/ is a diagnostic — this module (and obs/, whose wall/mono
stamps are correlation fields for external logs) is where the raw
clock is allowed to live.

Contract:

- `now()` — monotonic seconds (ordering + durations; never steps).
- `sleep(s)` — block the calling thread for `s` seconds.
- `wait(cond, timeout)` — wait on an ALREADY-HELD
  `threading.Condition` with an optional deadline; returns False iff
  the timeout elapsed without a notification (the `Condition.wait`
  contract). Routing condition waits through the clock is what lets
  `SimClock` wake timed waiters when *virtual* time passes their
  deadline.

The default is `RealClock` — a zero-behavior-change veneer over
`time.monotonic`/`time.sleep`/`Condition.wait`. `SimClock` is the
virtual twin: time advances only via `advance()` (or instantly inside
`sleep()` when `auto_advance=True`, the single-driver simulation
mode), so a seeded schedule fully determines which timeouts fire and
in what order.
"""

from __future__ import annotations

import contextlib
import threading

from node_replication_tpu.analysis.locks import make_condition
import time


class Clock:
    """Injectable time source (see module docstring for the contract)."""

    def now(self) -> float:
        """Monotonic seconds."""
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        """Block the calling thread for `seconds`."""
        raise NotImplementedError

    def wait(self, cond: threading.Condition,
             timeout: float | None = None) -> bool:
        """Wait on a HELD condition; False iff the timeout elapsed."""
        raise NotImplementedError


class RealClock(Clock):
    """The default: thin veneer over the OS monotonic clock."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)

    def wait(self, cond: threading.Condition,
             timeout: float | None = None) -> bool:
        return cond.wait(timeout)


class SimClock(Clock):
    """Virtual monotonic clock for deterministic simulation.

    Time moves only when someone moves it: `advance(dt)` /
    `advance_to(t)` from a driver thread, or — with `auto_advance=True`
    (the default, the single-threaded harness mode) — instantly inside
    `sleep()`, so a backoff or an injected stall costs zero wall time
    while still being visible in virtual timelines.

    Timed condition waits (`wait(cond, t)`) register a virtual
    deadline and then block on the condition with NO real timeout: the
    waiter wakes on a real `notify` or when `advance()` crosses its
    deadline (the clock notifies the registered condition). A timed
    wait therefore never spins and never races real time — under
    simulation, "the linger expired" is an explicit schedule event.

    Components driven by real OS threads under a SimClock must either
    be configured without timed waits (e.g. `batch_linger_s=0`) or be
    paired with a driver that advances the clock; `waiters()` exposes
    the registered deadlines so a driver can advance exactly to the
    next one.
    """

    def __init__(self, start: float = 0.0, auto_advance: bool = True):
        self._cond = make_condition("SimClock._cond")
        self._now = float(start)
        self.auto_advance = bool(auto_advance)
        # timed condition waiters: list of [deadline, cond] entries
        # (list, not dict: the same cond may carry several deadlines)
        self._waiters: list[list] = []

    # ------------------------------------------------------------ Clock API

    def now(self) -> float:
        with self._cond:
            return self._now

    def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._cond:
            deadline = self._now + seconds
            if self.auto_advance:
                self._advance_locked(deadline)
                return
            while self._now < deadline:
                self._cond.wait()

    def wait(self, cond: threading.Condition,
             timeout: float | None = None) -> bool:
        if timeout is None:
            # the predicate loop lives at the CALLER (the Clock.wait
            # contract mirrors Condition.wait); spurious wakeups are
            # re-checked there
            # nrlint: disable=condition-wait-without-predicate-loop
            cond.wait()
            return True
        with self._cond:
            if timeout <= 0:
                return False
            entry = [self._now + timeout, cond]
            self._waiters.append(entry)
        try:
            # block with no real timeout: a real notify or the clock
            # crossing `deadline` (advance notifies `cond`) wakes us;
            # the caller's predicate loop absorbs spurious wakeups
            # nrlint: disable=condition-wait-without-predicate-loop
            cond.wait()
        finally:
            with self._cond:
                if entry in self._waiters:
                    self._waiters.remove(entry)
                expired = self._now >= entry[0]
        return not expired

    # ----------------------------------------------------------- driver API

    def advance(self, dt: float) -> float:
        """Move virtual time forward by `dt`; wakes every sleeper and
        timed waiter whose deadline the step crosses. Returns the new
        time."""
        with self._cond:
            return self._advance_locked(self._now + float(dt))

    def advance_to(self, t: float) -> float:
        """Move virtual time to absolute `t` (no-op when in the past)."""
        with self._cond:
            return self._advance_locked(float(t))

    def _advance_locked(self, t: float) -> float:
        if t > self._now:
            self._now = t
        expired = [c for (d, c) in self._waiters if d <= self._now]
        self._cond.notify_all()  # wake blocking sleepers
        now = self._now
        # notify outside our lock: a waiter woken by cond.notify will
        # immediately try to take OUR lock to unregister (lock order
        # cond -> clock there; taking cond under the clock lock here
        # would be the reverse order — a deadlock)
        if expired:
            self._cond.release()
            try:
                for c in {id(c): c for c in expired}.values():
                    with c:
                        c.notify_all()
            finally:
                self._cond.acquire()
        return now

    def waiters(self) -> list[float]:
        """Registered timed-wait deadlines, sorted (driver
        introspection: `advance_to(waiters()[0])` fires exactly the
        next timeout)."""
        with self._cond:
            return sorted(d for d, _ in self._waiters)


_clock: Clock = RealClock()


def get_clock() -> Clock:
    """The process-global clock (default: `RealClock`)."""
    return _clock


def set_clock(clock: Clock) -> Clock:
    """Install `clock` globally; returns the previous one."""
    global _clock
    prev = _clock
    _clock = clock
    return prev


@contextlib.contextmanager
def installed(clock: Clock):
    """Context manager: install `clock`, restore the previous one on
    exit (the test/simulation entry point)."""
    prev = set_clock(clock)
    try:
        yield clock
    finally:
        set_clock(prev)
