"""tpu-node-replication: a TPU-native node-replication framework.

A brand-new framework with the capabilities of the reference
`node-replication` Rust library (black-box replication of data structures
through a shared operation log — see /root/reference, cited per-file in
docstrings below), re-designed TPU-first:

- Replica state is a JAX pytree of fixed-shape arrays; `Dispatch` is a set of
  pure transition functions selected with `lax.switch` (replaces the Rust
  `Dispatch` trait, `nr/src/lib.rs:103-125`).
- The shared log is a device-resident struct-of-arrays ring buffer; `append`
  is a batched reserve-then-write (replacing the CAS tail loop,
  `nr/src/log.rs:391-418`) and `exec` is a vmapped `lax.scan` replay
  (replacing the per-entry `alivef` spin loop, `nr/src/log.rs:473-524`).
- Thousands of replicas replay the log in lock-step on one chip via `vmap`;
  across chips, replicas shard over a `jax.sharding.Mesh` axis with the log
  replicated (appends ride ICI as replicated computation; see
  `node_replication_tpu.parallel`).
- CNR (multi-log, commutativity-partitioned) becomes a stacked log axis that
  can shard over a second mesh axis (`core/multilog.py`).

Data arrays are int32 (TPU-native lane width); log cursors are int64 so
logical positions never wrap (the reference relies on 64-bit `tail` never
overflowing, `nr/src/log.rs:88-131`). We therefore enable jax x64 at import
(opt out with NR_TPU_NO_X64=1; cursor math then wraps at 2^31).
"""

import os as _os

import jax as _jax

if not _os.environ.get("NR_TPU_NO_X64"):
    _jax.config.update("jax_enable_x64", True)

from node_replication_tpu.ops.encoding import (  # noqa: E402
    Dispatch,
    NOOP,
    apply_read,
    apply_write,
    encode_ops,
)
from node_replication_tpu.core.log import (  # noqa: E402
    DEFAULT_LOG_ENTRIES,
    GC_FROM_HEAD,
    LogSpec,
    LogState,
    log_append,
    log_exec_all,
    log_init,
    log_reset,
    log_space,
    is_replica_synced_for_reads,
)
from node_replication_tpu.core.replica import (  # noqa: E402
    MAX_PENDING_OPS,
    MAX_THREADS_PER_REPLICA,
    NodeReplicated,
    ReplicaToken,
)
from node_replication_tpu.core.step import make_step  # noqa: E402
from node_replication_tpu.durable import (  # noqa: E402
    WriteAheadLog,
    recover_fleet,
    save_durable_snapshot,
)
from node_replication_tpu.fault import (  # noqa: E402
    FaultPlan,
    FaultSpec,
    HealthTracker,
    ReplicaLifecycleManager,
)
from node_replication_tpu.serve import (  # noqa: E402
    DeadlineExceeded,
    FrontendClosed,
    Overloaded,
    ReplicaFailed,
    ServeConfig,
    ServeFrontend,
)

__all__ = [
    "Dispatch",
    "NOOP",
    "apply_read",
    "apply_write",
    "encode_ops",
    "DEFAULT_LOG_ENTRIES",
    "GC_FROM_HEAD",
    "LogSpec",
    "LogState",
    "log_append",
    "log_exec_all",
    "log_init",
    "log_reset",
    "log_space",
    "is_replica_synced_for_reads",
    "MAX_PENDING_OPS",
    "MAX_THREADS_PER_REPLICA",
    "NodeReplicated",
    "ReplicaToken",
    "make_step",
    "DeadlineExceeded",
    "FaultPlan",
    "FaultSpec",
    "FrontendClosed",
    "HealthTracker",
    "Overloaded",
    "ReplicaFailed",
    "ReplicaLifecycleManager",
    "ServeConfig",
    "ServeFrontend",
    "WriteAheadLog",
    "recover_fleet",
    "save_durable_snapshot",
]

__version__ = "0.1.0"
