"""Device topology discovery: the hwloc walk, TPU edition.

The reference discovers PU → core → L1/L2/L3 → socket → NUMA node with
hwloc2 and allocates threads/replicas over that hierarchy
(`benches/utils/topology.rs:89-156`, `allocate` at `174-219`). The TPU
hierarchy is device → host (process) → slice: intra-slice links are ICI,
cross-slice is DCN. This module walks `jax.devices()` into the same kind of
queryable topology object, and `allocate()` maps a replica/thread-placement
strategy onto an ordered device list the mesh builder consumes.

Thread pinning / DVFS (the remaining items of `benches/utils/mod.rs`:
`pin_thread` at 26-31, `disable_dvfs` at 38-50) have no TPU analog by
design, not by omission: "pinning" is device placement — the ordered
device lists produced here ARE the pinning decision, consumed by
`make_mesh`/`ShardedRunner` — and TPU cores have no OS-adjustable
frequency governor to disable; clock management is firmware-controlled
and uniform across a slice, so there is no DVFS knob whose variance a
benchmark must suppress. The reference needs both only because its
replicas are OS threads on frequency-scaled CPU cores.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import defaultdict

import jax


class ThreadMapping(enum.Enum):
    """Placement order over devices (`benches/utils/topology.rs:19-50`).

    NONE — jax default order; SEQUENTIAL — fill one host's devices before
    the next (the "fill socket first" analog, keeps a replica group on one
    host's ICI domain); INTERLEAVE — round-robin across hosts (the
    cross-socket analog, spreads load across DCN).
    """

    NONE = "none"
    SEQUENTIAL = "sequential"
    INTERLEAVE = "interleave"


@dataclasses.dataclass(frozen=True)
class DeviceInfo:
    device: object
    index: int
    process: int  # host index — the "NUMA node" analog
    slice_index: int  # TPU slice — the "socket" analog


class MachineTopology:
    """Queryable accelerator topology (`MachineTopology`,
    `benches/utils/topology.rs:89-156`)."""

    def __init__(self, devices=None):
        devices = list(devices if devices is not None else jax.devices())
        self.infos = [
            DeviceInfo(
                device=d,
                index=i,
                process=getattr(d, "process_index", 0),
                slice_index=getattr(d, "slice_index", None) or 0,
            )
            for i, d in enumerate(devices)
        ]

    def devices(self):
        return [i.device for i in self.infos]

    def n_devices(self) -> int:
        return len(self.infos)

    def n_hosts(self) -> int:
        return len({i.process for i in self.infos})

    def devices_on_host(self, process: int):
        return [i.device for i in self.infos if i.process == process]

    def allocate(self, mapping: ThreadMapping, n: int):
        """Pick `n` devices in placement order
        (`MachineTopology::allocate`, `benches/utils/topology.rs:174-219`)."""
        if n > len(self.infos):
            raise ValueError(f"want {n} devices, have {len(self.infos)}")
        if mapping in (ThreadMapping.NONE, ThreadMapping.SEQUENTIAL):
            order = sorted(self.infos, key=lambda i: (i.process, i.index))
        else:  # INTERLEAVE: round-robin hosts
            by_host = defaultdict(list)
            for i in sorted(self.infos, key=lambda i: i.index):
                by_host[i.process].append(i)
            order = []
            hosts = sorted(by_host)
            k = 0
            while len(order) < len(self.infos):
                h = hosts[k % len(hosts)]
                if by_host[h]:
                    order.append(by_host[h].pop(0))
                k += 1
        return [i.device for i in order[:n]]
