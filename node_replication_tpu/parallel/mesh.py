"""Mesh sharding: replicas and logs across TPU chips.

The reference scales by placing replicas over the NUMA topology
(`ReplicaStrategy`, `benches/mkbench.rs:321-362`) and partitioning the op
stream over logs (`LogStrategy`, `benches/mkbench.rs:364-383`), with the
shared-memory ring as the communication backend (SURVEY.md §2.6). The TPU
equivalent (SURVEY.md §2.6 "TPU-native equivalent"):

- mesh axis 'replica' — the fleet of replica states shards across chips
  (data parallelism of *state*); each chip replays only its shard.
- mesh axis 'log' — CNR's stacked log axis shards across chips
  (tensor/expert parallelism of the *op stream*); each chip appends and
  scans only its logs.
- the log (single-log case) is *replicated* over the mesh: the append batch
  is identical on every chip, so XLA keeps one copy per chip updated with
  zero communication, and replicas gather entries locally — the all-gather
  of appended spans rides ICI only when the batch itself originates sharded.

No hand-written collectives: shardings are declared with
`jax.sharding.NamedSharding` on a jitted pure step and GSPMD inserts the
all-gathers (scaling-book recipe: pick a mesh, annotate, let XLA place
collectives).
"""

from __future__ import annotations

import enum

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from node_replication_tpu.core.log import LogState
from node_replication_tpu.core.multilog import MultiLogState


class ReplicaStrategy(enum.Enum):
    """How many replicas and where (`benches/mkbench.rs:321-362`). ONE —
    one replica on one chip; PER_DEVICE — one replica shard per chip (the
    'Socket'/NUMA-node analog); PER_CORE — replicas sharded over every core
    of every chip (the 'L1'/PerThread analog, i.e. the full mesh)."""

    ONE = "one"
    PER_DEVICE = "per_device"
    PER_CORE = "per_core"


def make_mesh(
    n_replica_shards: int | None = None,
    n_log_shards: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('replica', 'log') mesh. Defaults to all devices on the
    replica axis."""
    devices = list(devices if devices is not None else jax.devices())
    total = len(devices)
    if n_replica_shards is None:
        n_replica_shards = total // n_log_shards
    if n_replica_shards * n_log_shards != total:
        raise ValueError(
            f"{n_replica_shards}x{n_log_shards} mesh needs "
            f"{n_replica_shards * n_log_shards} devices, got {total}"
        )
    arr = np.asarray(devices).reshape(n_replica_shards, n_log_shards)
    return Mesh(arr, ("replica", "log"))


def _log_spec_tree(log, mesh: Mesh):
    """Sharding pytree for a log state. Single-log: fully replicated
    (identical append on every chip). Multi-log: ring + cursors shard over
    the 'log' mesh axis on their leading log dimension."""
    if isinstance(log, MultiLogState):
        return MultiLogState(
            opcodes=NamedSharding(mesh, P("log")),
            args=NamedSharding(mesh, P("log")),
            head=NamedSharding(mesh, P("log")),
            tail=NamedSharding(mesh, P("log")),
            ctail=NamedSharding(mesh, P("log")),
            ltails=NamedSharding(mesh, P("log", "replica")),
        )
    assert isinstance(log, LogState)
    return LogState(
        opcodes=NamedSharding(mesh, P()),
        args=NamedSharding(mesh, P()),
        head=NamedSharding(mesh, P()),
        tail=NamedSharding(mesh, P()),
        ctail=NamedSharding(mesh, P()),
        ltails=NamedSharding(mesh, P("replica")),
    )


def _states_spec_tree(states, mesh: Mesh):
    """Replica states shard on the leading (replica) axis."""
    return jax.tree.map(lambda _: NamedSharding(mesh, P("replica")), states)


def place(log, states, mesh: Mesh):
    """device_put log + states with their canonical shardings."""
    log = jax.device_put(log, _log_spec_tree(log, mesh))
    states = jax.device_put(states, _states_spec_tree(states, mesh))
    return log, states


def shard_step(step_fn, mesh: Mesh, log_template, states_template,
               batch_spec: P | None = None, donate: bool = True):
    """Jit an (unjitted) `make_step`-style step with mesh shardings.

    Write/read batches are [R, B]-shaped: sharded over 'replica' like the
    states so each chip generates/answers only its shard's ops; the append
    concatenation all-gathers them (ICI) into the replicated log.
    """
    if batch_spec is None:
        batch_spec = P("replica")
    log_s = _log_spec_tree(log_template, mesh)
    states_s = _states_spec_tree(states_template, mesh)
    bs = NamedSharding(mesh, batch_spec)
    return jax.jit(
        step_fn,
        in_shardings=(log_s, states_s, bs, bs, bs, bs),
        out_shardings=(log_s, states_s, bs, bs),
        donate_argnums=(0, 1) if donate else (),
    )
