"""Mesh sharding: replicas and logs across TPU chips.

The reference scales by placing replicas over the NUMA topology
(`ReplicaStrategy`, `benches/mkbench.rs:321-362`) and partitioning the op
stream over logs (`LogStrategy`, `benches/mkbench.rs:364-383`), with the
shared-memory ring as the communication backend (SURVEY.md §2.6). The TPU
equivalent (SURVEY.md §2.6 "TPU-native equivalent"):

- mesh axis 'replica' — the fleet of replica states shards across chips
  (data parallelism of *state*); each chip replays only its shard.
- mesh axis 'log' — CNR's stacked log axis shards across chips
  (tensor/expert parallelism of the *op stream*); each chip appends and
  scans only its logs.
- the log (single-log case) is *replicated* over the mesh: the append batch
  is identical on every chip, so XLA keeps one copy per chip updated with
  zero communication, and replicas gather entries locally — the all-gather
  of appended spans rides ICI only when the batch itself originates sharded.

No hand-written collectives: shardings are declared with
`jax.sharding.NamedSharding` on a jitted pure step and GSPMD inserts the
all-gathers (scaling-book recipe: pick a mesh, annotate, let XLA place
collectives).

PRODUCTION STATUS: this module is the placement layer of the stateful
wrappers, not a demo — `NodeReplicated(mesh=...)` and
`MultiLogReplicated(mesh=...)` call `place()` at construction (and
after every fleet-shape change) so their replica axis lives across the
mesh, and `replica_mesh()` is the one-liner most callers want. The
explicit-collective twin lives in `parallel/collectives.py`.
"""

from __future__ import annotations

import enum

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from node_replication_tpu.core.log import LogState
from node_replication_tpu.core.multilog import MultiLogState


class ReplicaStrategy(enum.Enum):
    """Replica-shard placement granularity (`ReplicaStrategy`,
    `benches/mkbench.rs:321-362`): the reference's One/Socket/L1.../
    PerThread ladder mapped onto the TPU hierarchy (device → host →
    slice, `parallel/topology.py`).

    ONE — the whole fleet on a single device, un-sharded (the reference's
    `One`: one replica, every thread shares it).
    PER_HOST — one replica shard per host, placed on each host's first
    device (the `Socket`/NUMA-node analog: shards communicate over DCN).
    PER_DEVICE — one replica shard on every device (the
    `L1`/`PerThread` analog: the full mesh, shards communicate over ICI).

    Consumed by `strategy_devices()` → `ShardedRunner` /
    `ScaleBenchBuilder.replica_strategies()`.
    """

    ONE = "one"
    PER_HOST = "per_host"
    PER_DEVICE = "per_device"


def strategy_devices(strategy: ReplicaStrategy, topo=None, mapping=None):
    """Ordered device list realizing a ReplicaStrategy (the
    `replica_core_allocation` analog, `benches/mkbench.rs:838-945`):
    topology walk + ThreadMapping placement pick which devices host
    replica shards."""
    from node_replication_tpu.parallel.topology import (
        MachineTopology,
        ThreadMapping,
    )

    topo = topo or MachineTopology()
    mapping = mapping or ThreadMapping.SEQUENTIAL
    if strategy == ReplicaStrategy.ONE:
        return topo.allocate(mapping, 1)
    if strategy == ReplicaStrategy.PER_HOST:
        hosts = sorted({i.process for i in topo.infos})
        return [topo.devices_on_host(p)[0] for p in hosts]
    return topo.allocate(mapping, topo.n_devices())


def make_mesh(
    n_replica_shards: int | None = None,
    n_log_shards: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('replica', 'log') mesh. Defaults to all devices on the
    replica axis."""
    devices = list(devices if devices is not None else jax.devices())
    total = len(devices)
    if n_replica_shards is None:
        n_replica_shards = total // n_log_shards
    if n_replica_shards * n_log_shards != total:
        raise ValueError(
            f"{n_replica_shards}x{n_log_shards} mesh needs "
            f"{n_replica_shards * n_log_shards} devices, got {total}"
        )
    arr = np.asarray(devices).reshape(n_replica_shards, n_log_shards)
    return Mesh(arr, ("replica", "log"))


def replica_mesh(n_shards: int | None = None, devices=None,
                 strategy: "ReplicaStrategy | None" = None,
                 mapping=None) -> Mesh:
    """One-axis ('replica', 'log'=1) mesh for a replica-sharded fleet —
    the `NodeReplicated(mesh=...)` convenience. `n_shards=None` takes
    every device; a `ReplicaStrategy` picks the device set through the
    topology walk (`strategy_devices`)."""
    if strategy is not None:
        devices = strategy_devices(strategy, mapping=mapping)
        if n_shards is not None:
            devices = devices[:n_shards]
    elif devices is None:
        devices = jax.devices()
        if n_shards is not None:
            devices = list(devices)[:n_shards]
    return make_mesh(len(list(devices)), 1, devices=devices)


def log_spec_tree(log, mesh: Mesh):
    """Sharding pytree for a log state. Single-log: fully replicated
    (identical append on every chip). Multi-log: ring + cursors shard over
    the 'log' mesh axis on their leading log dimension."""
    if isinstance(log, MultiLogState):
        return MultiLogState(
            opcodes=NamedSharding(mesh, P("log")),
            args=NamedSharding(mesh, P("log")),
            head=NamedSharding(mesh, P("log")),
            tail=NamedSharding(mesh, P("log")),
            ctail=NamedSharding(mesh, P("log")),
            ltails=NamedSharding(mesh, P("log", "replica")),
        )
    assert isinstance(log, LogState)
    return LogState(
        opcodes=NamedSharding(mesh, P()),
        args=NamedSharding(mesh, P()),
        head=NamedSharding(mesh, P()),
        tail=NamedSharding(mesh, P()),
        ctail=NamedSharding(mesh, P()),
        ltails=NamedSharding(mesh, P("replica")),
    )


def states_spec_tree(states, mesh: Mesh):
    """Replica states shard on the leading (replica) axis."""
    return jax.tree.map(lambda _: NamedSharding(mesh, P("replica")), states)


# compat aliases (pre-production private names)
_log_spec_tree = log_spec_tree
_states_spec_tree = states_spec_tree


def place(log, states, mesh: Mesh):
    """device_put log + states with their canonical shardings."""
    log = jax.device_put(log, log_spec_tree(log, mesh))
    states = jax.device_put(states, states_spec_tree(states, mesh))
    return log, states


def announce_placement(mesh: Mesh, n_replicas: int, wrapper: str,
                       tier: str) -> None:
    """Record a wrapper's mesh placement in obs: `mesh.*` gauges
    (per-device replica count, device count) and one `mesh-place`
    trace event — the report CLI's Mesh section feeds on these."""
    from node_replication_tpu.obs.metrics import get_registry
    from node_replication_tpu.utils.trace import get_tracer

    n_shards = int(np.prod(mesh.devices.shape))
    per_device = n_replicas // max(1, mesh.shape.get("replica", 1))
    reg = get_registry()
    reg.gauge("mesh.devices").set(n_shards)
    reg.gauge("mesh.replicas_per_device").set(per_device)
    get_tracer().emit(
        "mesh-place", wrapper=wrapper, devices=n_shards,
        replicas=n_replicas, per_device=per_device, tier=tier,
        shape=dict(mesh.shape),
    )


def shard_step(step_fn, mesh: Mesh, log_template, states_template,
               batch_spec: P | None = None, donate: bool = True):
    """Jit an (unjitted) `make_step`-style step with mesh shardings.

    Write/read batches are [R, B]-shaped: sharded over 'replica' like the
    states so each chip generates/answers only its shard's ops; the append
    concatenation all-gathers them (ICI) into the replicated log.
    """
    if batch_spec is None:
        batch_spec = P("replica")
    log_s = log_spec_tree(log_template, mesh)
    states_s = states_spec_tree(states_template, mesh)
    bs = NamedSharding(mesh, batch_spec)
    return jax.jit(
        step_fn,
        in_shardings=(log_s, states_s, bs, bs, bs, bs),
        out_shardings=(log_s, states_s, bs, bs),
        donate_argnums=(0, 1) if donate else (),
    )
