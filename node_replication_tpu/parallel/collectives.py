"""Explicit-collective multi-chip replay: shard_map + ICI primitives.

PRODUCTION STATUS: this module is no longer a dryrun demo. Since the
mesh-sharded-fleet work, `make_shmap_exec` IS `NodeReplicated`'s exec
round on a mesh (`NodeReplicated(mesh=..., collectives="shmap")` — the
default tier for scan-engine models), and `make_ring_exec` backs the
ring catch-up tier `NodeReplicated.sync()` takes for large uniform
backlogs. `make_shmap_step` remains the fused lock-step batch path
(`ShardedRunner`'s explicit twin and `__graft_entry__.dryrun_multichip`'s
convergence probe), and `MeshFusedEngine` is the MESH-FUSED exec tier:
the PR 10 one-launch fused append+replay round embedded in a shard_map
program so a lock-step combiner round on an N-device fleet stays one
launch per device with the cursor lattice joined over ICI. Per-tier
selection counters live next to the other engine tiers
(`log.engine.shmap`, `log.engine.mesh_fused`, `nr.exec.engine.ring`,
`nr.exec.mesh.*` — core/log.py, core/replica.py).

`parallel/mesh.py` scales by annotation (GSPMD inserts the collectives);
this module is the hand-scheduled path for the places where owning the
communication pattern matters (SURVEY.md §2.6 "TPU-native equivalent"):

1. `make_shmap_step` — the fused append→replay→read step as a `shard_map`
   program: each chip generates its replica shard's write batch, the full
   append span is assembled with an explicit `all_gather` over the ICI
   ring (the moral equivalent of the reference's cross-replica entry
   publication, `nr/src/log.rs:391-418`), every chip appends the identical
   span to its local log copy, and replays only its shard. `ctail`/`head`
   bookkeeping uses `pmax`/`pmin` over the mesh axis — `fetch_max` /
   `min(ltails)` (`nr/src/log.rs:520-523`, `536-580`) as lattice
   reductions over ICI.

2. `make_ring_exec` — sequence parallelism for the op stream: a LONG
   replay window (W entries) is sharded over P chips; chunks rotate around
   the ICI ring (`ppermute`, ring-attention style) while replica-state
   shards stay resident. Unlike attention, log replay does NOT commute
   across chunks, so each chip masks its activity window to consume chunks
   in order: chip d sees chunk `(d + t) % P` at round t and is active for
   `t ∈ [P-d, 2P-d-1]` — a software pipeline whose fill/drain bubbles are
   masked NOOP replays (padded slots replay as identity, so masking is
   free of control flow). After `2P-1` rounds every replica shard has
   applied all W entries in log order.

   This is the structural analog of CNR's "scale the stream" story
   (SURVEY.md §5 long-context): one logical op stream, sharded transport,
   per-shard compute, order restored by schedule rather than by lock.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from node_replication_tpu.core.log import (
    LogSpec,
    LogState,
    _FAR,
    _exec_one,
    _m_engine_shmap,
)
from node_replication_tpu.ops.pallas_ring import FusedEngineHost
from node_replication_tpu.utils.compat import shard_map
from node_replication_tpu.ops.encoding import (
    Dispatch,
    NOOP,
    apply_write,
    dispatch_reads,
)


def make_shmap_step(
    dispatch: Dispatch,
    spec: LogSpec,
    mesh: Mesh,
    writes_per_replica: int,
    reads_per_replica: int,
    axis: str = "replica",
):
    """Explicit-collective twin of `core/step.make_step`.

    Shapes are the global ones (`[R, Bw]` etc.); states and batches shard
    over `axis`, the log replicates. Requires `R % mesh.shape[axis] == 0`.
    Returns a jitted step with the same signature/results as `make_step`.
    """
    R = spec.n_replicas
    Bw = int(writes_per_replica)
    nshards = mesh.shape[axis]
    if R % nshards:
        raise ValueError(f"R={R} not divisible by {nshards} shards")
    Rl = R // nshards
    span = R * Bw

    def local(log, states_l, wr_opc_l, wr_args_l, rd_opc_l, rd_args_l):
        # [Rl, Bw] local batches → [R*Bw] global span over the ICI ring.
        opc = lax.all_gather(wr_opc_l, axis, tiled=True).reshape(span)
        args = lax.all_gather(wr_args_l, axis, tiled=True).reshape(
            span, spec.arg_width
        )
        # every chip appends the identical span to its local log copy
        lanes = jnp.arange(span, dtype=jnp.int64)
        slot = ((log.tail + lanes) & spec.mask).astype(jnp.int32)
        log = log._replace(
            opcodes=log.opcodes.at[slot].set(opc),
            args=log.args.at[slot].set(args),
            tail=log.tail + span,
        )
        # replay the appended window into the local replica shard only
        states_l, resps_l, new_ltails_l = jax.vmap(
            lambda s, lt: _exec_one(spec, dispatch, log, s, lt, span)
        )(states_l, log.ltails)
        # lattice bookkeeping over the mesh axis: fetch_max(ctail),
        # min(ltails) GC — pmax/pmin ride ICI
        local_max = jnp.max(new_ltails_l)
        local_min = jnp.min(new_ltails_l)
        log = log._replace(
            ltails=new_ltails_l,
            ctail=jnp.maximum(log.ctail, lax.pmax(local_max, axis)),
            head=lax.pmin(local_min, axis),
        )
        # own responses: local replica r sits at global index
        # didx*Rl + r; its writes occupy window offsets [g*Bw, (g+1)*Bw)
        didx = lax.axis_index(axis)
        g = didx * Rl + jnp.arange(Rl, dtype=jnp.int32)[:, None]
        own = g * Bw + jnp.arange(Bw, dtype=jnp.int32)[None, :]
        wr_resps_l = jnp.take_along_axis(resps_l, own, axis=1)
        rd_resps_l = dispatch_reads(dispatch, states_l, rd_opc_l, rd_args_l)
        return log, states_l, wr_resps_l, rd_resps_l

    shardy = P(axis)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            LogState(opcodes=P(), args=P(), head=P(), tail=P(), ctail=P(),
                     ltails=shardy),
            jax.tree.map(lambda _: shardy, dispatch.init_state()),
            shardy, shardy, shardy, shardy,
        ),
        out_specs=(
            LogState(opcodes=P(), args=P(), head=P(), tail=P(), ctail=P(),
                     ltails=shardy),
            jax.tree.map(lambda _: shardy, dispatch.init_state()),
            shardy, shardy,
        ),
        check_vma=False,
    )
    return jax.jit(fn)


def _cursor_lattice_join(log, new_lt, fenced_mask, reduce_min,
                         reduce_max):
    """The cross-shard half of the exec-round cursor lattice — ONE
    definition for the shmap chain and both mesh-fused forms, so the
    GC invariant cannot drift between tiers:

    - `ctail = max(ctail, reduce_max(max new_lt))` (fetch_max,
      `nr/src/log.rs:520-523`);
    - `head` through the `_gc_head` reduction: min over UNFENCED
      cursors with the fenced mask composed via the `_FAR` sentinel
      (an all-fenced fleet holds head still), clamped monotone
      (`max(head, ...)` — a no-op for valid cursors, where the min
      already sits at/above head, but it keeps head monotone by
      construction like `core/log._gc_head`).

    `reduce_min`/`reduce_max` close over the cross-shard reduction:
    `lax.pmin`/`lax.pmax` over ICI inside a shard_map local, the
    identity for host-side joins over already-concatenated cursors
    (`MeshFusedEngine._sliced_round`). Returns `log` with
    ctail/head replaced (the caller installs `ltails`)."""
    ctail = jnp.maximum(log.ctail, reduce_max(jnp.max(new_lt)))
    if fenced_mask is None:
        head = jnp.maximum(log.head, reduce_min(jnp.min(new_lt)))
    else:
        masked = jnp.where(
            jnp.asarray(fenced_mask, bool), jnp.int64(_FAR), new_lt
        )
        gmin = reduce_min(jnp.min(masked))
        head = jnp.where(
            gmin >= jnp.int64(_FAR), log.head,
            jnp.maximum(log.head, gmin),
        )
    return log._replace(ctail=ctail, head=head)


def make_shmap_exec(
    dispatch: Dispatch,
    spec: LogSpec,
    mesh: Mesh,
    window: int,
    axis: str = "replica",
    fenced: bool = False,
    donate: bool = True,
):
    """Explicit-collective twin of `core/log.py:log_exec_all` — the
    catch-up/exec-round half of `make_shmap_step`, promoted into
    `NodeReplicated._exec_round` for mesh-sharded fleets.

    Unlike the fused step, cursors may DIVERGE: each chip replays its
    replica shard from that shard's own `ltails` (the vmapped
    `_exec_one` scan — bit-identical to every engine by the
    differential contracts), and the cursor lattice is joined over ICI:
    `ctail = max(ctail, pmax(max local ltails))` (fetch_max,
    `nr/src/log.rs:520-523`) and `head = pmin(min local ltails)`
    (`advance_head` GC, `nr/src/log.rs:536-580`). The log's ring
    arrays are replicated, so replay reads are chip-local; the only
    cross-chip traffic is the two scalar lattice reductions.

    `fenced=True` builds the quarantine-mask variant
    (`fault/health.py`): the returned fn takes an extra bool[R] mask
    sharded over `axis`; fenced replicas are frozen at their ltail
    (limits) and excluded from the GC-head reduction — the masked min
    uses the `_FAR` sentinel, and an all-fenced fleet holds `head`
    still — exactly `core/log.py:_freeze_limits`/`_gc_head` with the
    min taken over ICI instead of one device. This keeps the
    fenced-head GC mask correct when the fenced replica lives on a
    different chip than the combiner.

    Returns a jitted `exec(log, states[, fenced]) -> (log, states,
    resps)` with the `log_exec_all` response-layout contract:
    `resps[r, i]` answers logical position `old_ltails[r] + i`.
    Requires `R % mesh.shape[axis] == 0`.
    """
    R = spec.n_replicas
    nshards = mesh.shape[axis]
    if R % nshards:
        raise ValueError(f"R={R} not divisible by {nshards} shards")
    _m_engine_shmap.inc()

    def local(log, states_l, *mask):
        lt_l = log.ltails  # the LOCAL [R/nshards] cursor shard
        if fenced:
            fenced_l = mask[0]
            # _freeze_limits, shard-local: a fenced replica is frozen
            # at its own ltail; others replay to the tail
            limits_l = jnp.where(fenced_l, lt_l, jnp.int64(_FAR))
            states_l, resps_l, new_lt = jax.vmap(
                lambda s, lt, lim: _exec_one(
                    spec, dispatch, log, s, lt, window, lim
                )
            )(states_l, lt_l, limits_l)
        else:
            fenced_l = None
            states_l, resps_l, new_lt = jax.vmap(
                lambda s, lt: _exec_one(spec, dispatch, log, s, lt,
                                        window)
            )(states_l, lt_l)
        # ctail/head joined over ICI (_gc_head with the fenced mask
        # composed via _FAR — the one shared lattice-join definition)
        log = _cursor_lattice_join(
            log, new_lt, fenced_l,
            lambda v: lax.pmin(v, axis), lambda v: lax.pmax(v, axis),
        )
        log = log._replace(ltails=new_lt)
        return log, states_l, resps_l

    shardy = P(axis)
    log_specs = LogState(opcodes=P(), args=P(), head=P(), tail=P(),
                         ctail=P(), ltails=shardy)
    state_specs = jax.tree.map(lambda _: shardy, dispatch.init_state())
    in_specs = (log_specs, state_specs) + ((shardy,) if fenced else ())
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(log_specs, state_specs, shardy),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())


def make_ring_exec(
    dispatch: Dispatch,
    mesh: Mesh,
    axis: str = "replica",
):
    """Pipelined ring replay of a long, device-sharded op window.

    `ring_exec(opcodes, args, states)`:
      opcodes int32[W], args int32[W, A]  — sharded over `axis` in P chunks
      states  [R, ...] pytree            — replica shards over `axis`

    Every replica applies all W entries in log order; chunks move over ICI
    (`ppermute`), states stay resident. W and R must divide by P.
    Returns the updated states.
    """
    nshards = mesh.shape[axis]

    def apply_chunk(states_l, opc_l, args_l):
        def per_replica(state):
            def body(st, x):
                o, a = x
                st, _ = apply_write(dispatch, st, o, a)
                return st, jnp.int32(0)

            st, _ = lax.scan(body, state, (opc_l, args_l))
            return st

        return jax.vmap(per_replica)(states_l)

    def local(opc_l, args_l, states_l):
        didx = lax.axis_index(axis)
        # chunks rotate backward: chunk c sits on chip (c - t) % P at
        # round t, so chip d hosts chunk (d + t) % P
        perm = [(i, (i - 1) % nshards) for i in range(nshards)]
        opc, args = opc_l, args_l
        states = states_l
        for t in range(1, 2 * nshards):
            opc = lax.ppermute(opc, axis, perm)
            args = lax.ppermute(args, axis, perm)
            # ordered consumption: chip d applies chunks 0..P-1 during
            # rounds [P-d, 2P-d-1]; outside the window the chunk is
            # masked to NOOP (identity replay) — pipeline bubbles as
            # masked compute, no control flow
            active = (t >= nshards - didx) & (t <= 2 * nshards - didx - 1)
            masked = jnp.where(active, opc, jnp.int32(NOOP))
            states = apply_chunk(states, masked, args)
        return states

    shardy = P(axis)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(shardy, shardy,
                  jax.tree.map(lambda _: shardy, dispatch.init_state())),
        out_specs=jax.tree.map(lambda _: shardy, dispatch.init_state()),
        check_vma=False,
    )
    return jax.jit(fn)


class MeshFusedEngine(FusedEngineHost):
    """The MESH-FUSED exec tier: the fused append+replay engine's raw
    round (`ops/pallas_replay.FusedHashmapEngine` /
    `ops/pallas_vspace.FusedVspaceEngine`) embedded in a `shard_map`
    program over the replica mesh, so one combiner round on an
    N-device fleet is ONE shard_map-wrapped Pallas launch per device —
    issued as a single program — instead of the shmap tier's
    append-program → exec-program chain.

    Composition (the junction of the PR 9 and PR 10 tiers):

    - the ring planes and scalar cursors are REPLICATED (`P()`), the
      replica-axis state blocks and `ltails` ride `P('replica')` —
      exactly the shard-slice layout the fused engines' chunk calls
      already use (tests/test_pallas_fused.py pins the composability:
      a per-shard invocation of the chunk calls IS the shard-local
      program);
    - each shard runs the whole fused round locally — append DMA over
      its replicated ring copy (identical spans on every chip, zero
      communication, the `parallel/mesh.py` replicated-log economics),
      in-order replay into its `P('replica')` state blocks, response
      gather for its own lanes;
    - the cursor lattice is joined over ICI exactly like
      `make_shmap_exec`: `ctail = max(ctail, pmax(max ltails))` and
      `head` as the `_gc_head` reduction with the fenced lane mask
      composed through the `_FAR` sentinel — so fenced-head GC stays
      correct when the quarantined replica lives on another chip, and
      an all-fenced shard cannot drag `head` backwards.

    Implements the engine contract `core/replica._try_fused_round`
    routes rounds through (`supports`/`launches`/`supports_fenced`/
    `round`), so the wrapper's eligibility check, WAL journaling,
    deferred-readback split rounds (`defer=True` issues the meshed
    launch at `_begin_round`, reads back at `_finish_round` — the
    serve pipeline's overlap works meshed), and bit-identity contract
    all apply unchanged. `tier`/`devices` redirect the shared
    instrumentation: rounds count under `log.engine.mesh_fused` and
    `kernel-launch` events carry `devices=`. `launches(window)` is the
    PER-DEVICE launch count (1 unless MAX_GRID or VMEM splits a
    shard) — the number that must hold at 1 as devices scale
    (`bench.py --kernel --kernel-devices`).

    Compilation policy: on TPU `round_fn` returns the shard_map
    program and the inherited round cache jits it with log+states
    donated. In interpret mode jit is unavailable (jit + interpret +
    the package's x64 default trips the MLIR where-fn dtype clash, the
    same reason every interpret test passes jit=False) and EAGER
    shard_map costs seconds per invocation on this jax, so the
    interpret rounds run `_sliced_round` instead: the per-shard inner
    round invoked eagerly on each `P('replica')` slice with the cursor
    lattice joined host-side — by construction the exact computation
    the shard_map local performs (the chunk call IS the shard-local
    program, and the joins are the same max/min/_FAR algebra as the
    pmax/pmin reductions). `_shmap_round` stays callable either way,
    and tests/test_mesh_fleet.py pins the two paths bit-identical
    against each other so the program the TPU jits is covered by the
    CPU suite.
    """

    tier = "mesh_fused"

    def __init__(self, dispatch, spec: LogSpec, mesh: Mesh,
                 axis: str = "replica", interpret: bool | None = None):
        if dispatch.fused_factory is None:
            raise ValueError(
                f"{dispatch.name} has no fused_factory (no fused "
                f"kernel to mesh-wrap)"
            )
        nshards = mesh.shape[axis]
        if spec.n_replicas % nshards:
            raise ValueError(
                f"R={spec.n_replicas} not divisible by {nshards} "
                f"mesh shards"
            )
        # the shard-local engine: the SAME ring/capacity, the shard's
        # slice of the replica axis — the factory raising ValueError
        # means "no fused form at this config", exactly as un-meshed
        shard_spec = dataclasses.replace(
            spec, n_replicas=spec.n_replicas // nshards
        )
        self.inner = dispatch.fused_factory(shard_spec,
                                            interpret=interpret)
        self.dispatch = dispatch
        self.spec = spec
        self.mesh = mesh
        self.axis = axis
        self.devices = int(nshards)
        self.supports_fenced = type(self.inner).supports_fenced
        self.interpret = bool(self.inner.interpret)
        self._init_host()

    def supports(self, window: int) -> bool:
        return self.inner.supports(window)

    def launches(self, window: int) -> int:
        """PER-DEVICE kernel launches per round (the shards run
        concurrently inside one program)."""
        return self.inner.launches(window)

    def round_fn(self, window: int, fenced: bool = False):
        """MODEL-layout round: `(log, states, opcodes, args, count[,
        fenced_vec]) -> (log, states, resps[R, W])` with the
        `FusedEngineHost.round` entry contract (cached +
        jitted/instrumented by the base class). The shard_map program
        on TPU, the bit-identical sliced composition in interpret mode
        (see the class docstring's compilation policy)."""
        if self.interpret:
            return self._sliced_round(window, fenced)
        return self._shmap_round(window, fenced)

    def _sliced_round(self, window: int, fenced: bool = False):
        """The shard-sliced twin of `_shmap_round`: each shard's slice
        runs the inner fused round eagerly and the cursor lattice is
        joined host-side with the same max/min/_FAR algebra the
        shard_map local expresses as pmax/pmin — bit-identical by the
        shard-slice composability contract
        (tests/test_pallas_fused.py), and pinned against the real
        shard_map program in tests/test_mesh_fleet.py."""
        inner_fn = self.inner.round_fn(window, fenced)
        nsh = self.devices
        Rl = self.spec.n_replicas // nsh

        def entry(log, states, opcodes, args, count, *mask):
            fen = mask[0] if fenced else None
            lt_parts, st_parts, resp_parts = [], [], []
            out_log = None
            for s in range(nsh):
                sl = slice(s * Rl, (s + 1) * Rl)
                log_s = log._replace(ltails=log.ltails[sl])
                states_s = jax.tree.map(lambda x: x[sl], states)
                fen_s = None if fen is None else fen[sl]
                out_log, states_s, resps_s = inner_fn(
                    log_s, states_s, opcodes, args, count, fen_s
                )
                lt_parts.append(out_log.ltails)
                st_parts.append(states_s)
                resp_parts.append(resps_s)
            # every shard computed identical ring planes + tail; the
            # cross-shard lattice join runs over the concatenated
            # cursors (identity reductions — same algebra as the
            # shard_map form's pmin/pmax)
            new_lt = jnp.concatenate(lt_parts)
            out_log = _cursor_lattice_join(
                out_log._replace(ctail=log.ctail, head=log.head),
                new_lt, fen if fenced else None,
                lambda v: v, lambda v: v,
            )._replace(ltails=new_lt)
            states = jax.tree.map(
                lambda *xs: jnp.concatenate(xs, axis=0), *st_parts
            )
            resps = jnp.concatenate(resp_parts, axis=0)
            return out_log, states, resps

        return entry

    def _shmap_round(self, window: int, fenced: bool = False):
        """The shard_map program itself: per-shard inner round +
        ctail/head joined as pmax/pmin lattice reductions over ICI
        (with the fenced mask composed through the `_FAR` sentinel).
        What `round_fn` returns on TPU; callable eagerly in interpret
        mode for the sliced-vs-shmap pinning test."""
        inner_fn = self.inner.round_fn(window, fenced)
        axis = self.axis

        def local(log, states_l, opcodes, args, count, *mask):
            fen_l = mask[0] if fenced else None
            # the shard-local fused round: append DMA (replicated ring
            # copy), replay + response gather for this shard's lanes,
            # and the SHARD-LOCAL cursor lattice
            log, states_l, resps_l = inner_fn(
                log, states_l, opcodes, args, count, fen_l
            )
            # re-join ctail/head over ICI: the shard-local lattice only
            # saw this shard's cursors (a fenced lane elsewhere must
            # still hold GC, a live lane elsewhere must still advance
            # ctail) — the same shared join as make_shmap_exec
            log = _cursor_lattice_join(
                log, log.ltails, fen_l,
                lambda v: lax.pmin(v, axis),
                lambda v: lax.pmax(v, axis),
            )
            return log, states_l, resps_l

        shardy = P(axis)
        log_specs = LogState(opcodes=P(), args=P(), head=P(), tail=P(),
                             ctail=P(), ltails=shardy)
        state_specs = jax.tree.map(
            lambda _: shardy, self.dispatch.init_state()
        )
        in_specs = (log_specs, state_specs, P(), P(), P())
        if fenced:
            in_specs += (shardy,)
        fn = shard_map(
            local,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(log_specs, state_specs, shardy),
            check_vma=False,
        )

        def entry(log, states, opcodes, args, count, *mask):
            # scalar count crosses the shard_map boundary as an array
            # (eager shard_map cannot shard a Python int)
            return fn(log, states, opcodes, args,
                      jnp.asarray(count, jnp.int64), *mask)

        return entry

    # round() — the host entry with the per-(window, fenced) program
    # cache, eager-in-interpret jit policy, metrics and the
    # kernel-launch event (now devices-stamped) — is inherited from
    # FusedEngineHost (ops/pallas_ring.py)
