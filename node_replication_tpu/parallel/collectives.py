"""Explicit-collective multi-chip replay: shard_map + ICI primitives.

PRODUCTION STATUS: this module is no longer a dryrun demo. Since the
mesh-sharded-fleet work, `make_shmap_exec` IS `NodeReplicated`'s exec
round on a mesh (`NodeReplicated(mesh=..., collectives="shmap")` — the
default tier for scan-engine models), and `make_ring_exec` backs the
ring catch-up tier `NodeReplicated.sync()` takes for large uniform
backlogs. `make_shmap_step` remains the fused lock-step batch path
(`ShardedRunner`'s explicit twin and `__graft_entry__.dryrun_multichip`'s
convergence probe). Per-tier selection counters live next to the other
engine tiers (`log.engine.shmap`, `nr.exec.engine.ring`,
`nr.exec.mesh.*` — core/log.py, core/replica.py).

`parallel/mesh.py` scales by annotation (GSPMD inserts the collectives);
this module is the hand-scheduled path for the places where owning the
communication pattern matters (SURVEY.md §2.6 "TPU-native equivalent"):

1. `make_shmap_step` — the fused append→replay→read step as a `shard_map`
   program: each chip generates its replica shard's write batch, the full
   append span is assembled with an explicit `all_gather` over the ICI
   ring (the moral equivalent of the reference's cross-replica entry
   publication, `nr/src/log.rs:391-418`), every chip appends the identical
   span to its local log copy, and replays only its shard. `ctail`/`head`
   bookkeeping uses `pmax`/`pmin` over the mesh axis — `fetch_max` /
   `min(ltails)` (`nr/src/log.rs:520-523`, `536-580`) as lattice
   reductions over ICI.

2. `make_ring_exec` — sequence parallelism for the op stream: a LONG
   replay window (W entries) is sharded over P chips; chunks rotate around
   the ICI ring (`ppermute`, ring-attention style) while replica-state
   shards stay resident. Unlike attention, log replay does NOT commute
   across chunks, so each chip masks its activity window to consume chunks
   in order: chip d sees chunk `(d + t) % P` at round t and is active for
   `t ∈ [P-d, 2P-d-1]` — a software pipeline whose fill/drain bubbles are
   masked NOOP replays (padded slots replay as identity, so masking is
   free of control flow). After `2P-1` rounds every replica shard has
   applied all W entries in log order.

   This is the structural analog of CNR's "scale the stream" story
   (SURVEY.md §5 long-context): one logical op stream, sharded transport,
   per-shard compute, order restored by schedule rather than by lock.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from node_replication_tpu.core.log import (
    LogSpec,
    LogState,
    _FAR,
    _exec_one,
    _m_engine_shmap,
)
from node_replication_tpu.utils.compat import shard_map
from node_replication_tpu.ops.encoding import (
    Dispatch,
    NOOP,
    apply_write,
    dispatch_reads,
)


def make_shmap_step(
    dispatch: Dispatch,
    spec: LogSpec,
    mesh: Mesh,
    writes_per_replica: int,
    reads_per_replica: int,
    axis: str = "replica",
):
    """Explicit-collective twin of `core/step.make_step`.

    Shapes are the global ones (`[R, Bw]` etc.); states and batches shard
    over `axis`, the log replicates. Requires `R % mesh.shape[axis] == 0`.
    Returns a jitted step with the same signature/results as `make_step`.
    """
    R = spec.n_replicas
    Bw = int(writes_per_replica)
    nshards = mesh.shape[axis]
    if R % nshards:
        raise ValueError(f"R={R} not divisible by {nshards} shards")
    Rl = R // nshards
    span = R * Bw

    def local(log, states_l, wr_opc_l, wr_args_l, rd_opc_l, rd_args_l):
        # [Rl, Bw] local batches → [R*Bw] global span over the ICI ring.
        opc = lax.all_gather(wr_opc_l, axis, tiled=True).reshape(span)
        args = lax.all_gather(wr_args_l, axis, tiled=True).reshape(
            span, spec.arg_width
        )
        # every chip appends the identical span to its local log copy
        lanes = jnp.arange(span, dtype=jnp.int64)
        slot = ((log.tail + lanes) & spec.mask).astype(jnp.int32)
        log = log._replace(
            opcodes=log.opcodes.at[slot].set(opc),
            args=log.args.at[slot].set(args),
            tail=log.tail + span,
        )
        # replay the appended window into the local replica shard only
        states_l, resps_l, new_ltails_l = jax.vmap(
            lambda s, lt: _exec_one(spec, dispatch, log, s, lt, span)
        )(states_l, log.ltails)
        # lattice bookkeeping over the mesh axis: fetch_max(ctail),
        # min(ltails) GC — pmax/pmin ride ICI
        local_max = jnp.max(new_ltails_l)
        local_min = jnp.min(new_ltails_l)
        log = log._replace(
            ltails=new_ltails_l,
            ctail=jnp.maximum(log.ctail, lax.pmax(local_max, axis)),
            head=lax.pmin(local_min, axis),
        )
        # own responses: local replica r sits at global index
        # didx*Rl + r; its writes occupy window offsets [g*Bw, (g+1)*Bw)
        didx = lax.axis_index(axis)
        g = didx * Rl + jnp.arange(Rl, dtype=jnp.int32)[:, None]
        own = g * Bw + jnp.arange(Bw, dtype=jnp.int32)[None, :]
        wr_resps_l = jnp.take_along_axis(resps_l, own, axis=1)
        rd_resps_l = dispatch_reads(dispatch, states_l, rd_opc_l, rd_args_l)
        return log, states_l, wr_resps_l, rd_resps_l

    shardy = P(axis)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            LogState(opcodes=P(), args=P(), head=P(), tail=P(), ctail=P(),
                     ltails=shardy),
            jax.tree.map(lambda _: shardy, dispatch.init_state()),
            shardy, shardy, shardy, shardy,
        ),
        out_specs=(
            LogState(opcodes=P(), args=P(), head=P(), tail=P(), ctail=P(),
                     ltails=shardy),
            jax.tree.map(lambda _: shardy, dispatch.init_state()),
            shardy, shardy,
        ),
        check_vma=False,
    )
    return jax.jit(fn)


def make_shmap_exec(
    dispatch: Dispatch,
    spec: LogSpec,
    mesh: Mesh,
    window: int,
    axis: str = "replica",
    fenced: bool = False,
    donate: bool = True,
):
    """Explicit-collective twin of `core/log.py:log_exec_all` — the
    catch-up/exec-round half of `make_shmap_step`, promoted into
    `NodeReplicated._exec_round` for mesh-sharded fleets.

    Unlike the fused step, cursors may DIVERGE: each chip replays its
    replica shard from that shard's own `ltails` (the vmapped
    `_exec_one` scan — bit-identical to every engine by the
    differential contracts), and the cursor lattice is joined over ICI:
    `ctail = max(ctail, pmax(max local ltails))` (fetch_max,
    `nr/src/log.rs:520-523`) and `head = pmin(min local ltails)`
    (`advance_head` GC, `nr/src/log.rs:536-580`). The log's ring
    arrays are replicated, so replay reads are chip-local; the only
    cross-chip traffic is the two scalar lattice reductions.

    `fenced=True` builds the quarantine-mask variant
    (`fault/health.py`): the returned fn takes an extra bool[R] mask
    sharded over `axis`; fenced replicas are frozen at their ltail
    (limits) and excluded from the GC-head reduction — the masked min
    uses the `_FAR` sentinel, and an all-fenced fleet holds `head`
    still — exactly `core/log.py:_freeze_limits`/`_gc_head` with the
    min taken over ICI instead of one device. This keeps the
    fenced-head GC mask correct when the fenced replica lives on a
    different chip than the combiner.

    Returns a jitted `exec(log, states[, fenced]) -> (log, states,
    resps)` with the `log_exec_all` response-layout contract:
    `resps[r, i]` answers logical position `old_ltails[r] + i`.
    Requires `R % mesh.shape[axis] == 0`.
    """
    R = spec.n_replicas
    nshards = mesh.shape[axis]
    if R % nshards:
        raise ValueError(f"R={R} not divisible by {nshards} shards")
    # nrlint: disable=obs-in-traced — per-build tier counter by design
    _m_engine_shmap.inc()

    def local(log, states_l, *mask):
        lt_l = log.ltails  # the LOCAL [R/nshards] cursor shard
        if fenced:
            fenced_l = mask[0]
            # _freeze_limits, shard-local: a fenced replica is frozen
            # at its own ltail; others replay to the tail
            limits_l = jnp.where(fenced_l, lt_l, jnp.int64(_FAR))
            states_l, resps_l, new_lt = jax.vmap(
                lambda s, lt, lim: _exec_one(
                    spec, dispatch, log, s, lt, window, lim
                )
            )(states_l, lt_l, limits_l)
            # _gc_head over ICI: min over unfenced cursors fleet-wide;
            # all-fenced holds head still (pmin of all-_FAR detects it)
            masked = jnp.where(fenced_l, jnp.int64(_FAR), new_lt)
            gmin = lax.pmin(jnp.min(masked), axis)
            head = jnp.where(
                gmin >= jnp.int64(_FAR), log.head,
                jnp.maximum(log.head, gmin),
            )
        else:
            states_l, resps_l, new_lt = jax.vmap(
                lambda s, lt: _exec_one(spec, dispatch, log, s, lt,
                                        window)
            )(states_l, lt_l)
            head = lax.pmin(jnp.min(new_lt), axis)
        ctail = jnp.maximum(
            log.ctail, lax.pmax(jnp.max(new_lt), axis)
        )
        log = log._replace(ltails=new_lt, ctail=ctail, head=head)
        return log, states_l, resps_l

    shardy = P(axis)
    log_specs = LogState(opcodes=P(), args=P(), head=P(), tail=P(),
                         ctail=P(), ltails=shardy)
    state_specs = jax.tree.map(lambda _: shardy, dispatch.init_state())
    in_specs = (log_specs, state_specs) + ((shardy,) if fenced else ())
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(log_specs, state_specs, shardy),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())


def make_ring_exec(
    dispatch: Dispatch,
    mesh: Mesh,
    axis: str = "replica",
):
    """Pipelined ring replay of a long, device-sharded op window.

    `ring_exec(opcodes, args, states)`:
      opcodes int32[W], args int32[W, A]  — sharded over `axis` in P chunks
      states  [R, ...] pytree            — replica shards over `axis`

    Every replica applies all W entries in log order; chunks move over ICI
    (`ppermute`), states stay resident. W and R must divide by P.
    Returns the updated states.
    """
    nshards = mesh.shape[axis]

    def apply_chunk(states_l, opc_l, args_l):
        def per_replica(state):
            def body(st, x):
                o, a = x
                st, _ = apply_write(dispatch, st, o, a)
                return st, jnp.int32(0)

            st, _ = lax.scan(body, state, (opc_l, args_l))
            return st

        return jax.vmap(per_replica)(states_l)

    def local(opc_l, args_l, states_l):
        didx = lax.axis_index(axis)
        # chunks rotate backward: chunk c sits on chip (c - t) % P at
        # round t, so chip d hosts chunk (d + t) % P
        perm = [(i, (i - 1) % nshards) for i in range(nshards)]
        opc, args = opc_l, args_l
        states = states_l
        for t in range(1, 2 * nshards):
            opc = lax.ppermute(opc, axis, perm)
            args = lax.ppermute(args, axis, perm)
            # ordered consumption: chip d applies chunks 0..P-1 during
            # rounds [P-d, 2P-d-1]; outside the window the chunk is
            # masked to NOOP (identity replay) — pipeline bubbles as
            # masked compute, no control flow
            active = (t >= nshards - didx) & (t <= 2 * nshards - didx - 1)
            masked = jnp.where(active, opc, jnp.int32(NOOP))
            states = apply_chunk(states, masked, args)
        return states

    shardy = P(axis)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(shardy, shardy,
                  jax.tree.map(lambda _: shardy, dispatch.init_state())),
        out_specs=jax.tree.map(lambda _: shardy, dispatch.init_state()),
        check_vma=False,
    )
    return jax.jit(fn)
