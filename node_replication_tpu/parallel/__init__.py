from node_replication_tpu.parallel.mesh import (
    ReplicaStrategy,
    make_mesh,
    place,
    shard_step,
)
from node_replication_tpu.parallel.topology import MachineTopology

__all__ = [
    "ReplicaStrategy",
    "make_mesh",
    "place",
    "shard_step",
    "MachineTopology",
]
