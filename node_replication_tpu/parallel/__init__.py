from node_replication_tpu.parallel.collectives import (
    MeshFusedEngine,
    make_ring_exec,
    make_shmap_exec,
    make_shmap_step,
)
from node_replication_tpu.parallel.mesh import (
    ReplicaStrategy,
    make_mesh,
    place,
    replica_mesh,
    shard_step,
)
from node_replication_tpu.parallel.topology import MachineTopology

__all__ = [
    "MeshFusedEngine",
    "ReplicaStrategy",
    "make_mesh",
    "make_ring_exec",
    "make_shmap_exec",
    "make_shmap_step",
    "place",
    "replica_mesh",
    "shard_step",
    "MachineTopology",
]
