"""Pythonic wrappers over the native C ABI.

`NativeEngine` mirrors `NodeReplicated`'s surface (register / execute_mut /
execute / sync / verify-style state dump) so differential tests can drive
the JAX device path and the native CPU path from one op stream, and the
mkbench-style harness can run both under the same ReplicaTrait protocol
(`benches/mkbench.rs:77-139` capability).
"""

from __future__ import annotations

import ctypes

import numpy as np

MODEL_HASHMAP = 1
MODEL_STACK = 2
MODEL_SORTEDSET = 3


class NativeEngine:
    """N replicas of a native data structure behind shared native log(s)."""

    def __init__(
        self,
        model: int,
        model_param: int,
        n_replicas: int = 1,
        log_capacity: int = 1 << 16,
        nlogs: int = 1,
    ):
        from node_replication_tpu.native import load

        self._lib = load()
        self._h = self._lib.nr_engine_create(
            model, model_param, n_replicas, log_capacity, nlogs
        )
        if not self._h:
            raise ValueError(
                "engine creation failed (bad model id, replica count, or a "
                "non-concurrent model with nlogs > 1)"
            )
        self.model = model
        self.n_replicas = n_replicas
        self.nlogs = nlogs
        self.max_batch = int(self._lib.nr_max_batch())

    def close(self):
        if self._h:
            self._lib.nr_engine_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ----------------------------------------------------------------- API

    def register(self, rid: int = 0) -> tuple[int, int]:
        tid = self._lib.nr_register(self._h, rid)
        if tid < 0:
            raise RuntimeError(f"register failed on replica {rid}")
        return (rid, tid)

    @staticmethod
    def _argbuf(args) -> ctypes.Array:
        a = (ctypes.c_int32 * 3)()
        for i, v in enumerate(args[:3]):
            a[i] = int(v)
        return a

    def execute_mut(self, op: tuple, token: tuple[int, int]) -> int:
        rid, tid = token
        return int(
            self._lib.nr_execute_mut(
                self._h, rid, tid, int(op[0]), self._argbuf(op[1:])
            )
        )

    def execute_mut_batch(self, ops: list[tuple], token: tuple[int, int]):
        """Batched write path (flat-combining batch semantics). In CNR
        mode a batch may span logs: each op is hash-tagged with its log
        and every log's combiner collects its own sub-batch (the cnr
        hash-tagged context, `cnr/src/context.rs:18`)."""
        rid, tid = token
        out = []
        for i in range(0, len(ops), self.max_batch):
            chunk = ops[i : i + self.max_batch]
            n = len(chunk)
            opcodes = (ctypes.c_int32 * n)(*[int(o[0]) for o in chunk])
            args = (ctypes.c_int32 * (3 * n))()
            for j, o in enumerate(chunk):
                for k, v in enumerate(o[1:4]):
                    args[3 * j + k] = int(v)
            resps = (ctypes.c_int32 * n)()
            rc = self._lib.nr_execute_mut_batch(
                self._h, rid, tid, n, opcodes, args, resps
            )
            if rc != 0:
                raise ValueError(f"batch rejected (rc={rc})")
            out.extend(int(r) for r in resps)
        return out

    def execute(self, op: tuple, token: tuple[int, int]) -> int:
        rid, tid = token
        return int(
            self._lib.nr_execute(
                self._h, rid, tid, int(op[0]), self._argbuf(op[1:])
            )
        )

    def execute_batch(self, ops: list[tuple], token: tuple[int, int]):
        """Batched read path: one ctail gate + one read-lock hold per
        chunk (read-side flat combining — the wr=0 rescue, r5; see
        `nr_execute_batch` in nr_native.cpp)."""
        rid, tid = token
        out = []
        for i in range(0, len(ops), self.max_batch):
            chunk = ops[i : i + self.max_batch]
            n = len(chunk)
            opcodes = (ctypes.c_int32 * n)(*[int(o[0]) for o in chunk])
            args = (ctypes.c_int32 * (3 * n))()
            for j, o in enumerate(chunk):
                for k, v in enumerate(o[1:4]):
                    args[3 * j + k] = int(v)
            resps = (ctypes.c_int32 * n)()
            rc = self._lib.nr_execute_batch(
                self._h, rid, tid, n, opcodes, args, resps
            )
            if rc != 0:
                raise ValueError(f"read batch rejected (rc={rc})")
            out.extend(int(r) for r in resps)
        return out

    def sync(self, rid: int | None = None) -> None:
        for r in range(self.n_replicas) if rid is None else [rid]:
            self._lib.nr_sync(self._h, r)

    def sync_log(self, rid: int, log_idx: int) -> None:
        self._lib.nr_sync_log(self._h, rid, log_idx)

    def state_dump(self, rid: int = 0) -> np.ndarray:
        """Sync replica `rid` and dump its state words (the `verify` hook)."""
        n = int(self._lib.nr_state_words(self._h))
        buf = (ctypes.c_int32 * n)()
        self._lib.nr_state_dump(self._h, rid, buf)
        return np.ctypeslib.as_array(buf).copy()

    def replicas_equal(self) -> bool:
        ref = self.state_dump(0)
        return all(
            np.array_equal(ref, self.state_dump(r))
            for r in range(1, self.n_replicas)
        )

    # ------------------------------------------------------------- telemetry

    def log_tail(self, li: int = 0) -> int:
        return int(self._lib.nr_log_tail(self._h, li))

    def log_head(self, li: int = 0) -> int:
        return int(self._lib.nr_log_head(self._h, li))

    def log_ctail(self, li: int = 0) -> int:
        return int(self._lib.nr_log_ctail(self._h, li))

    def log_ltail(self, li: int, rid: int) -> int:
        return int(self._lib.nr_log_ltail(self._h, li, rid))

    def stuck_events(self) -> int:
        return int(self._lib.nr_stuck_events(self._h))

    def warn_events(self) -> int:
        return int(self._lib.nr_warn_events(self._h))

    # ---------------------------------------------------------- bench loops

    def bench_hashmap(
        self,
        threads_per_replica: int,
        write_pct: int,
        keyspace: int,
        batch: int = 32,
        duration_ms: int = 1000,
        seed: int = 1,
    ) -> tuple[int, np.ndarray, np.ndarray]:
        """In-process measured loop (threads never cross the FFI per op).
        Returns (total_ops, per_thread_ops, per_sec_ops) where
        `per_sec_ops[t, s]` is thread t's completed ops in wall-clock
        second s — real bins recorded in the loop, the reference's
        per-(thread, second) CSV granularity
        (`benches/mkbench.rs:498-552`)."""
        total_threads = self.n_replicas * threads_per_replica
        max_secs = max(1, -(-duration_ms // 1000))
        per = (ctypes.c_uint64 * total_threads)()
        per_sec = (ctypes.c_uint64 * (total_threads * max_secs))()
        total = self._lib.nr_bench_hashmap(
            self._h,
            threads_per_replica,
            write_pct,
            keyspace,
            batch,
            duration_ms,
            seed,
            per,
            per_sec,
            max_secs,
        )
        return (
            int(total),
            np.ctypeslib.as_array(per).copy(),
            np.ctypeslib.as_array(per_sec)
            .copy()
            .reshape(total_threads, max_secs),
        )


class NativeRwLock:
    """Distributed reader-writer lock (`nr/src/rwlock.rs` capability)."""

    def __init__(self, n_slots: int = 256):
        from node_replication_tpu.native import load

        self._lib = load()
        self._h = self._lib.nr_rwlock_create(n_slots)
        self.n_slots = n_slots

    def close(self):
        if self._h:
            self._lib.nr_rwlock_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def read_acquire(self, slot: int):
        self._lib.nr_rwlock_read_acquire(self._h, slot)

    def read_release(self, slot: int):
        self._lib.nr_rwlock_read_release(self._h, slot)

    def write_acquire(self):
        self._lib.nr_rwlock_write_acquire(self._h)

    def write_release(self):
        self._lib.nr_rwlock_write_release(self._h)


def bench_log_append(
    log_capacity: int, n_threads: int, batch: int, duration_ms: int
) -> int:
    from node_replication_tpu.native import load

    return int(
        load().nr_bench_log_append(log_capacity, n_threads, batch, duration_ms)
    )


def bench_rwlock(
    n_readers: int, n_writers: int, duration_ms: int
) -> tuple[int, int]:
    from node_replication_tpu.native import load

    import ctypes as c

    writes = c.c_uint64()
    total = load().nr_bench_rwlock(
        n_readers, n_writers, duration_ms, c.byref(writes)
    )
    return int(total), int(writes.value)


def bench_cmp(
    system: str,
    n_threads: int,
    write_pct: int,
    keyspace: int,
    batch: int = 32,
    duration_ms: int = 1000,
    seed: int = 1,
) -> tuple[int, np.ndarray]:
    """Non-NR comparison baselines under the same splitmix workload loop
    as `bench_hashmap` (`benches/hashmap_comparisons.rs:25-176` analog):
    'mutex' = one std::unordered_map behind a mutex; 'lockfree' = a
    shared lock-free open-addressing map (wait-free readers — the
    urcu-class competitive middle of the reference's headline graphs,
    `benches/hashmap_comparisons.rs:281-435`); 'evmap' = a left-right
    reader/writer-split map (two copies, epoch-pinned wait-free reads,
    single-writer apply-flip-drain-replay — the read-optimized
    specialist the reference's hashbench drives,
    `benches/hashbench.rs:26-105`); 'partitioned' = one private map per
    thread over its key congruence class (the no-sharing ceiling).
    Returns (total_ops, per_thread_ops)."""
    from node_replication_tpu.native import load

    if system in ("lockfree", "evmap") and keyspace > (1 << 26):
        raise ValueError(
            f"{system} cmp map caps keyspace at 2^26 (its fixed "
            "table(s) would exceed 1 GiB); shrink --keys for the "
            "comparison sweep"
        )
    lib = load()
    fn = {
        "mutex": lib.nr_bench_cmp_mutex,
        "lockfree": lib.nr_bench_cmp_lockfree,
        "evmap": lib.nr_bench_cmp_evmap,
        "partitioned": lib.nr_bench_cmp_partitioned,
    }[system]
    per = (ctypes.c_uint64 * n_threads)()
    total = fn(n_threads, write_pct, keyspace, batch, duration_ms, seed, per)
    if total == 2**64 - 1:  # FFI error sentinel (see nr_native.cpp)
        raise ValueError(f"native cmp bench '{system}' rejected the config")
    return int(total), np.ctypeslib.as_array(per).copy()
