"""Native host-side engine: build-on-demand ctypes binding.

The shared library is compiled from `nr_native.cpp` with the system g++ the
first time it is needed (and whenever the source is newer than the cached
`.so`). No pip/pybind dependency: the C ABI is consumed with ctypes.

Race detection (EXCEEDS the reference, which ships none — SURVEY.md §5
"race detection: none"): set `NR_TPU_TSAN=1` before first import to
compile with `-fsanitize=thread` and run the engine under
ThreadSanitizer; `scripts/tsan_stress.py` drives the concurrency
surfaces (flat combining, CNR per-log collection under the record
seqlock, the distributed rwlock, multikey relaxed reads) under it.
The TSAN build lands in a separate `.so` so the fast build is untouched.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "nr_native.cpp")
_TSAN = os.environ.get("NR_TPU_TSAN", "") == "1"
_SO = os.path.join(
    _DIR, "libnr_native_tsan.so" if _TSAN else "libnr_native.so"
)

_lock = threading.Lock()
_lib = None


def build(force: bool = False) -> str:
    """Compile the native library if missing/stale; return the .so path."""
    with _lock:
        if (
            not force
            and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        ):
            return _SO
        # pid-unique temp path: concurrent processes may race the build;
        # each compiles privately, then atomically publishes.
        tmp = f"{_SO}.{os.getpid()}.tmp"
        cmd = [
            "g++",
            "-std=c++17",
            "-O1" if _TSAN else "-O3",
            "-fPIC",
            "-shared",
            "-pthread",
            *(["-fsanitize=thread", "-g"] if _TSAN else []),
            "-o",
            tmp,
            _SRC,
        ]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"native build failed (g++ exit {proc.returncode}):\n"
                    f"{proc.stderr}"
                )
            os.replace(tmp, _SO)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return _SO


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native library, with signatures set."""
    global _lib
    if _lib is not None:
        return _lib
    path = build()
    lib = ctypes.CDLL(path)
    c = ctypes
    i32p = c.POINTER(c.c_int32)
    u64p = c.POINTER(c.c_uint64)

    lib.nr_engine_create.restype = c.c_void_p
    lib.nr_engine_create.argtypes = [
        c.c_int, c.c_int64, c.c_int, c.c_uint64, c.c_int,
    ]
    lib.nr_engine_destroy.argtypes = [c.c_void_p]
    lib.nr_register.restype = c.c_int
    lib.nr_register.argtypes = [c.c_void_p, c.c_int]
    lib.nr_execute_mut.restype = c.c_int32
    lib.nr_execute_mut.argtypes = [c.c_void_p, c.c_int, c.c_int, c.c_int32, i32p]
    lib.nr_execute_mut_batch.restype = c.c_int
    lib.nr_execute_mut_batch.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_int, i32p, i32p, i32p,
    ]
    lib.nr_execute.restype = c.c_int32
    lib.nr_execute.argtypes = [c.c_void_p, c.c_int, c.c_int, c.c_int32, i32p]
    lib.nr_execute_batch.restype = c.c_int
    lib.nr_execute_batch.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_int, i32p, i32p, i32p,
    ]
    lib.nr_sync.argtypes = [c.c_void_p, c.c_int]
    lib.nr_sync_log.argtypes = [c.c_void_p, c.c_int, c.c_int]
    lib.nr_state_words.restype = c.c_int64
    lib.nr_state_words.argtypes = [c.c_void_p]
    lib.nr_state_dump.argtypes = [c.c_void_p, c.c_int, i32p]
    for name in ("nr_stuck_events", "nr_warn_events"):
        fn = getattr(lib, name)
        fn.restype = c.c_uint64
        fn.argtypes = [c.c_void_p]
    lib.nr_log_tail.restype = c.c_uint64
    lib.nr_log_tail.argtypes = [c.c_void_p, c.c_int]
    lib.nr_log_head.restype = c.c_uint64
    lib.nr_log_head.argtypes = [c.c_void_p, c.c_int]
    lib.nr_log_ctail.restype = c.c_uint64
    lib.nr_log_ctail.argtypes = [c.c_void_p, c.c_int]
    lib.nr_log_ltail.restype = c.c_uint64
    lib.nr_log_ltail.argtypes = [c.c_void_p, c.c_int, c.c_int]
    lib.nr_max_batch.restype = c.c_int

    lib.nr_rwlock_create.restype = c.c_void_p
    lib.nr_rwlock_create.argtypes = [c.c_int]
    lib.nr_rwlock_destroy.argtypes = [c.c_void_p]
    lib.nr_rwlock_read_acquire.argtypes = [c.c_void_p, c.c_int]
    lib.nr_rwlock_read_release.argtypes = [c.c_void_p, c.c_int]
    lib.nr_rwlock_write_acquire.argtypes = [c.c_void_p]
    lib.nr_rwlock_write_release.argtypes = [c.c_void_p]

    lib.nr_bench_hashmap.restype = c.c_uint64
    lib.nr_bench_hashmap.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_int64, c.c_int, c.c_int,
        c.c_uint64, u64p, u64p, c.c_int,
    ]
    lib.nr_bench_log_append.restype = c.c_uint64
    lib.nr_bench_log_append.argtypes = [c.c_uint64, c.c_int, c.c_int, c.c_int]
    lib.nr_bench_rwlock.restype = c.c_uint64
    lib.nr_bench_rwlock.argtypes = [c.c_int, c.c_int, c.c_int, u64p]
    # comparison baselines (non-NR systems under the same workload loop)
    for fn in (lib.nr_bench_cmp_mutex, lib.nr_bench_cmp_partitioned,
               lib.nr_bench_cmp_lockfree, lib.nr_bench_cmp_evmap):
        fn.restype = c.c_uint64
        fn.argtypes = [
            c.c_int, c.c_int, c.c_int64, c.c_int, c.c_int, c.c_uint64, u64p,
        ]

    _lib = lib
    return lib


from node_replication_tpu.native.engine import (  # noqa: E402
    MODEL_HASHMAP,
    MODEL_SORTEDSET,
    MODEL_STACK,
    NativeEngine,
    NativeRwLock,
    bench_cmp,
)

__all__ = [
    "build",
    "load",
    "NativeEngine",
    "NativeRwLock",
    "MODEL_HASHMAP",
    "MODEL_STACK",
    "MODEL_SORTEDSET",
    "bench_cmp",
]
