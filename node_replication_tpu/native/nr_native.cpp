// Native host-side node-replication engine.
//
// The reference's library crates are native Rust built on raw atomics
// (SURVEY.md §2: log ring `nr/src/log.rs`, flat-combining replica
// `nr/src/replica.rs`, distributed RwLock `nr/src/rwlock.rs`). This file is
// the TPU framework's host-side native counterpart: the CPU reference path
// used for differential testing against the JAX/XLA device path, and the
// engine behind the hashbench/rwlockbench-style CPU benches.
//
// The algorithms are re-designed, not translated:
//  - Ring liveness uses per-entry monotone sequence numbers (Vyukov-queue
//    style: cell is live for logical position `pos` iff `seq == pos + 1`)
//    instead of the reference's wrap-parity `alivef`/`lmasks` bitmatrix
//    (`nr/src/log.rs:88-131`). Same guarantee, one atomic per cell.
//  - Flat combining uses publication records (one cache-padded record per
//    thread with an EMPTY→STAGED→DONE lifecycle) instead of the
//    reference's three-cursor TSO-dependent SPSC rings
//    (`nr/src/context.rs:43-54`); records are explicit acquire/release so
//    the engine is portable off x86.
//  - Multi-log (CNR) mode keys the combiner lock per (replica, log) and
//    maps ops to logs with a key hash, mirroring `LogMapper`
//    (`cnr/src/lib.rs:123-137`) for the key-partitioned models.
//
// Exposed as a C ABI consumed by ctypes (node_replication_tpu/native/engine.py).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>
#include <thread>
#include <vector>
#include <mutex>
#include <unordered_map>
#include <chrono>

#if defined(__x86_64__)
#include <immintrin.h>
static inline void cpu_relax() { _mm_pause(); }
#else
static inline void cpu_relax() { std::this_thread::yield(); }
#endif

extern "C" {

// ---------------------------------------------------------------- constants

// Flat-combining batch per publication record (`MAX_PENDING_OPS`,
// `nr/src/context.rs:12`).
static const int kMaxBatch = 32;
// Threads per replica (`MAX_THREADS_PER_REPLICA`, `nr/src/replica.rs:56`).
static const int kMaxThreads = 256;
// Max replicas registered on one log (`MAX_REPLICAS`, `nr/src/log.rs:26`).
static const int kMaxReplicas = 192;
// Fixed op arg width (matches ops/encoding.py arg_width<=4).
static const int kArgW = 4;
// GC slack the appender preserves (`GC_FROM_HEAD`, `nr/src/log.rs:36`).
static const uint64_t kGcSlack = 8192;
// Spin-diagnostic threshold (`WARN_THRESHOLD`, `nr/src/log.rs:43`), scaled
// down: after this many fruitless spins the stuck counter increments.
static const uint64_t kWarnSpins = 1u << 24;

// ------------------------------------------------------------- cache pad

struct alignas(64) PaddedAtomicU64 {
  std::atomic<uint64_t> v{0};
};
struct alignas(64) PaddedAtomicU32 {
  std::atomic<uint32_t> v{0};
};

// --------------------------------------------------------- distributed lock

// Reader-favoring distributed reader-writer lock: one writer flag plus one
// cache-line-padded reader count per reader slot, so read acquisition never
// bounces a shared line (the capability of `nr/src/rwlock.rs:18-42`).
struct NrRwLock {
  std::atomic<uint32_t> wlock{0};
  int n_slots;
  PaddedAtomicU32 *readers;
};

NrRwLock *nr_rwlock_create(int n_slots) {
  auto *l = new NrRwLock();
  l->n_slots = n_slots;
  l->readers = new PaddedAtomicU32[n_slots]();
  return l;
}

void nr_rwlock_destroy(NrRwLock *l) {
  delete[] l->readers;
  delete l;
}

void nr_rwlock_read_acquire(NrRwLock *l, int slot) {
  for (;;) {
    while (l->wlock.load(std::memory_order_relaxed)) cpu_relax();
    // seq_cst on the announce/check pair: reader announces (RMW) then
    // checks wlock, writer announces (CAS) then checks readers — the
    // store-buffer pattern. Weaker orderings allow both to pass on
    // non-TSO targets.
    l->readers[slot].v.fetch_add(1, std::memory_order_seq_cst);
    if (!l->wlock.load(std::memory_order_seq_cst)) return;
    // Writer raced in: back off and retry.
    l->readers[slot].v.fetch_sub(1, std::memory_order_acq_rel);
  }
}

void nr_rwlock_read_release(NrRwLock *l, int slot) {
  l->readers[slot].v.fetch_sub(1, std::memory_order_release);
}

void nr_rwlock_write_acquire(NrRwLock *l) {
  uint32_t expect = 0;
  while (!l->wlock.compare_exchange_weak(expect, 1,
                                         std::memory_order_seq_cst,
                                         std::memory_order_relaxed)) {
    expect = 0;
    cpu_relax();
  }
  for (int i = 0; i < l->n_slots; i++)
    while (l->readers[i].v.load(std::memory_order_seq_cst)) cpu_relax();
}

void nr_rwlock_write_release(NrRwLock *l) {
  l->wlock.store(0, std::memory_order_release);
}

// ------------------------------------------------------------------ models

// A model is the native `Dispatch` impl (`nr/src/lib.rs:103-125` contract):
// opaque state + pure-ish transition functions returning an int32 response.
// Semantics intentionally match the JAX models bit-for-bit so differential
// tests can drive both from one op stream.
struct Model {
  void *(*create)(int64_t param);
  void (*destroy)(void *);
  int32_t (*dispatch_mut)(void *, int32_t opcode, const int32_t *args);
  int32_t (*dispatch_rd)(void *, int32_t opcode, const int32_t *args);
  int64_t (*state_words)(void *);
  void (*state_dump)(void *, int32_t *out);
  int concurrent_ok;  // safe for CNR-mode concurrent dispatch on disjoint keys
  uint32_t multikey_rd_mask;  // read opcodes whose result spans many keys:
  // in CNR mode they conflict with writes on every log, so the read path
  // syncs ALL logs first (LogMapper contract, cnr/src/lib.rs:123-137).
  // SEMANTICS (relaxed snapshot, ADVICE r2): the sync-then-scan is NOT a
  // linearizable multi-key snapshot — combiners on other threads may
  // replay new writes into this replica's data mid-scan, so an ascending
  // scan can include a later write while missing an earlier one on an
  // already-passed key. Guarantees: (a) every op completed before the
  // read began is included; (b) every value observed was current at some
  // instant during the scan (no torn per-key values: single-word reads);
  // (c) the result is bounded by [state at scan start, state at scan
  // end]. This matches the reference skiplist's relaxed concurrent range
  // ops rather than a stop-the-world snapshot; a linearizable variant
  // would append the scan to EVERY log and complete when all logs reach
  // it, which the lock-step JAX path gets for free (reads run between
  // steps) — tests/test_native.py pins the bounds contract.
};

// --- model 1: dense hashmap (mirrors models/hashmap.py: HM_PUT=1 k,v;
// HM_REMOVE=2 k; read HM_GET=1 k → value or -1).
//
// Each key is one atomic 64-bit cell packing (present << 32 | value):
// CNR-mode reads run lock-free concurrently with the per-log combiners'
// dispatch_mut, so per-key state must be observable atomically — a split
// values/present pair could expose present=1 with a torn value.
struct HashmapState {
  int64_t n_keys;
  std::atomic<uint64_t> *cells;
};

static const uint64_t kHmPresent = 1ull << 32;

static void *hm_create(int64_t n_keys) {
  auto *s = new HashmapState();
  s->n_keys = n_keys;
  s->cells = new std::atomic<uint64_t>[n_keys]();
  return s;
}
static void hm_destroy(void *p) {
  auto *s = static_cast<HashmapState *>(p);
  delete[] s->cells;
  delete s;
}
static int32_t hm_mut(void *p, int32_t opcode, const int32_t *args) {
  auto *s = static_cast<HashmapState *>(p);
  int64_t k = ((int64_t)args[0] % s->n_keys + s->n_keys) % s->n_keys;
  if (opcode == 1) {  // put
    s->cells[k].store(kHmPresent | (uint32_t)args[1],
                      std::memory_order_release);
    return 0;
  }
  if (opcode == 2) {  // remove
    uint64_t old = s->cells[k].exchange(0, std::memory_order_acq_rel);
    return (old & kHmPresent) ? 1 : 0;
  }
  return 0;  // NOOP
}
static int32_t hm_rd(void *p, int32_t opcode, const int32_t *args) {
  auto *s = static_cast<HashmapState *>(p);
  int64_t k = ((int64_t)args[0] % s->n_keys + s->n_keys) % s->n_keys;
  if (opcode == 1) {
    uint64_t c = s->cells[k].load(std::memory_order_acquire);
    return (c & kHmPresent) ? (int32_t)(uint32_t)c : -1;
  }
  return 0;
}
static int64_t hm_words(void *p) {
  return 2 * static_cast<HashmapState *>(p)->n_keys;
}
static void hm_dump(void *p, int32_t *out) {
  auto *s = static_cast<HashmapState *>(p);
  for (int64_t i = 0; i < s->n_keys; i++) {
    uint64_t c = s->cells[i].load(std::memory_order_acquire);
    out[i] = (int32_t)(uint32_t)c;
    out[s->n_keys + i] = (c & kHmPresent) ? 1 : 0;
  }
}

// --- model 2: bounded stack (mirrors models/stack.py: ST_PUSH=1 v →
// depth or -1; ST_POP=2 → value or -1; reads ST_PEEK=1, ST_LEN=2).
struct StackState {
  int64_t capacity;
  int32_t top;
  int32_t *buf;
};

static void *st_create(int64_t capacity) {
  auto *s = new StackState();
  s->capacity = capacity;
  s->top = 0;
  s->buf = static_cast<int32_t *>(calloc(capacity, sizeof(int32_t)));
  return s;
}
static void st_destroy(void *p) {
  auto *s = static_cast<StackState *>(p);
  free(s->buf);
  delete s;
}
static int32_t st_mut(void *p, int32_t opcode, const int32_t *args) {
  auto *s = static_cast<StackState *>(p);
  if (opcode == 1) {  // push
    if (s->top >= s->capacity) return -1;
    s->buf[s->top++] = args[0];
    return s->top;
  }
  if (opcode == 2) {  // pop
    if (s->top == 0) return -1;
    return s->buf[--s->top];
  }
  return 0;
}
static int32_t st_rd(void *p, int32_t opcode, const int32_t *args) {
  auto *s = static_cast<StackState *>(p);
  if (opcode == 1) return s->top > 0 ? s->buf[s->top - 1] : -1;
  if (opcode == 2) return s->top;
  return 0;
}
static int64_t st_words(void *p) {
  return 1 + static_cast<StackState *>(p)->capacity;
}
static void st_dump(void *p, int32_t *out) {
  auto *s = static_cast<StackState *>(p);
  out[0] = s->top;
  for (int64_t i = 0; i < s->capacity; i++) out[1 + i] = s->buf[i];
}

// --- model 3: sorted set over a bounded keyspace (mirrors
// models/sortedset.py: SS_INSERT=1 k → newly-inserted; SS_REMOVE=2 k →
// was-present; reads SS_CONTAINS=1 k, SS_RANGE_COUNT=2 (lo, hi),
// SS_RANK=3 k). Per-key atomic flags: inserts/removes on distinct keys
// commute, so the model is CNR-safe; ordered reads are relaxed scans
// (aggregate reads over a concurrently-mutating set are not atomic
// snapshots in the reference's skiplist either).
struct SortedSetState {
  int64_t n_keys;
  std::atomic<uint8_t> *present;
};

static void *ss_create(int64_t n_keys) {
  auto *s = new SortedSetState();
  s->n_keys = n_keys;
  s->present = new std::atomic<uint8_t>[n_keys]();
  return s;
}
static void ss_destroy(void *p) {
  auto *s = static_cast<SortedSetState *>(p);
  delete[] s->present;
  delete s;
}
static int32_t ss_mut(void *p, int32_t opcode, const int32_t *args) {
  auto *s = static_cast<SortedSetState *>(p);
  int64_t k = ((int64_t)args[0] % s->n_keys + s->n_keys) % s->n_keys;
  if (opcode == 1)
    return s->present[k].exchange(1, std::memory_order_acq_rel) ? 0 : 1;
  if (opcode == 2)
    return s->present[k].exchange(0, std::memory_order_acq_rel) ? 1 : 0;
  return 0;
}
static int32_t ss_rd(void *p, int32_t opcode, const int32_t *args) {
  auto *s = static_cast<SortedSetState *>(p);
  if (opcode == 1) {
    int64_t k = ((int64_t)args[0] % s->n_keys + s->n_keys) % s->n_keys;
    return s->present[k].load(std::memory_order_acquire);
  }
  if (opcode == 2) {  // range_count [lo, hi)
    int64_t lo = args[0] < 0 ? 0 : args[0];
    int64_t hi = args[1] > s->n_keys ? s->n_keys : args[1];
    int32_t n = 0;
    for (int64_t i = lo; i < hi; i++)
      n += s->present[i].load(std::memory_order_relaxed);
    return n;
  }
  if (opcode == 3) {  // rank: #elements < k
    int64_t hi = args[0] > s->n_keys ? s->n_keys : args[0];
    int32_t n = 0;
    for (int64_t i = 0; i < hi; i++)
      n += s->present[i].load(std::memory_order_relaxed);
    return n;
  }
  return 0;
}
static int64_t ss_words(void *p) {
  return static_cast<SortedSetState *>(p)->n_keys;
}
static void ss_dump(void *p, int32_t *out) {
  auto *s = static_cast<SortedSetState *>(p);
  for (int64_t i = 0; i < s->n_keys; i++)
    out[i] = s->present[i].load(std::memory_order_acquire);
}

static const Model kModels[] = {
    {nullptr, nullptr, nullptr, nullptr, nullptr, nullptr, 0, 0},  // 0 unused
    {hm_create, hm_destroy, hm_mut, hm_rd, hm_words, hm_dump, 1, 0},
    {st_create, st_destroy, st_mut, st_rd, st_words, st_dump, 0, 0},
    // sorted set: SS_RANGE_COUNT=2 / SS_RANK=3 aggregate over many keys
    {ss_create, ss_destroy, ss_mut, ss_rd, ss_words, ss_dump, 1,
     (1u << 2) | (1u << 3)},
};
static const int kNumModels = 4;

// ------------------------------------------------------------------- log

// One MPMC ring. Liveness: cell at physical slot `pos & mask` is published
// for logical position `pos` when `seq == pos + 1`. Producers CAS-reserve
// `[tail, tail+n)`; head only advances to min(ltails) (GC,
// `nr/src/log.rs:536-580` capability).
struct alignas(64) Entry {
  std::atomic<uint64_t> seq;
  int32_t opcode;
  uint32_t rid;
  int32_t args[kArgW];
};

struct Log {
  uint64_t capacity;
  uint64_t mask;
  Entry *ring;
  alignas(64) std::atomic<uint64_t> tail{0};
  alignas(64) std::atomic<uint64_t> head{0};
  alignas(64) std::atomic<uint64_t> ctail{0};
  PaddedAtomicU64 *ltails;  // one per replica
  int n_replicas;

  void init(uint64_t cap, int n_reps) {
    capacity = 1;
    while (capacity < cap) capacity <<= 1;
    mask = capacity - 1;
    ring = static_cast<Entry *>(
        aligned_alloc(64, capacity * sizeof(Entry)));
    for (uint64_t i = 0; i < capacity; i++) {
      new (&ring[i]) Entry();
      // Cell i is first written for logical position i; seq==i means
      // "awaiting lap-0 publication".
      ring[i].seq.store(i, std::memory_order_relaxed);
    }
    n_replicas = n_reps;
    ltails = new PaddedAtomicU64[n_reps]();
  }
  void destroy() {
    free(ring);
    delete[] ltails;
  }
  uint64_t min_ltail() const {
    uint64_t m = UINT64_MAX;
    for (int r = 0; r < n_replicas; r++) {
      uint64_t v = ltails[r].v.load(std::memory_order_acquire);
      if (v < m) m = v;
    }
    return m;
  }
};

// ------------------------------------------------------------------ engine

// Publication record: one per (replica, thread). EMPTY → STAGED (owner
// publishes a batch) → DONE (combiner delivered responses) → EMPTY.
enum RecState : uint32_t { REC_EMPTY = 0, REC_STAGED = 1, REC_DONE = 2 };

struct alignas(64) PubRecord {
  std::atomic<uint32_t> state{REC_EMPTY};
  // Seqlock for re-stage detection: odd while the owner is publishing a
  // new batch. A combiner snapshots seq, scans, and re-validates before
  // committing — a record whose batch completed and was re-staged
  // mid-scan is discarded instead of collected half-published (the
  // validate-then-commit is safe because the ops a combiner is about to
  // commit can ONLY be collected under its own (rid, log) combiner lock,
  // so the record cannot complete — and thus cannot be re-staged —
  // between a successful validation and the commit).
  std::atomic<uint32_t> seq{0};
  int32_t count{0};
  // Per-op log tag (the cnr context's hash-tagged slots,
  // `cnr/src/context.rs:18`): a batch may span logs; each log's combiner
  // collects only its own ops (set to -1 once collected). Responses
  // arrive out of order across logs, so completion is counted by
  // `remaining`, not by the last slot. Atomic (relaxed) because
  // combiners of different logs read the array concurrently with the
  // collected-marker writes.
  std::atomic<int32_t> op_log[kMaxBatch];
  std::atomic<int32_t> remaining{0};
  int32_t opcodes[kMaxBatch];
  int32_t args[kMaxBatch][kArgW];
  int32_t resps[kMaxBatch];
};

struct Replica {
  void *data;
  NrRwLock *rwlock;                 // guards data in single-log mode
  std::atomic<uint32_t> *combiner;  // one lock per log
  PubRecord *records;               // kMaxThreads records
  std::atomic<int32_t> n_threads{0};
};

struct Engine {
  const Model *model;
  int model_id;
  int64_t model_param;
  int n_replicas;
  int nlogs;
  Log *logs;          // nlogs (atomics: not vector-movable)
  Replica *replicas;  // n_replicas
  std::atomic<uint64_t> stuck_events{0};  // GC-starvation counter (the
  // CNR gc-callback analog, `cnr/src/log.rs:135-142`)
  std::atomic<uint64_t> warn_events{0};
};

Engine *nr_engine_create(int model_id, int64_t model_param, int n_replicas,
                         uint64_t log_capacity, int nlogs) {
  if (model_id <= 0 || model_id >= kNumModels) return nullptr;
  if (n_replicas < 1 || n_replicas > kMaxReplicas) return nullptr;
  if (model_param < 1) return nullptr;  // zero-size models div-by-zero
  // A combiner batch (up to kMaxBatch*8 ops) must always fit under the GC
  // slack reserve or log_append can never succeed.
  if (log_capacity < 1024) return nullptr;
  const Model *m = &kModels[model_id];
  if (nlogs > 1 && !m->concurrent_ok) return nullptr;
  auto *e = new Engine();
  e->model = m;
  e->model_id = model_id;
  e->model_param = model_param;
  e->n_replicas = n_replicas;
  e->nlogs = nlogs < 1 ? 1 : nlogs;
  e->logs = new Log[e->nlogs]();
  for (int i = 0; i < e->nlogs; i++) e->logs[i].init(log_capacity, n_replicas);
  e->replicas = new Replica[n_replicas]();
  for (int i = 0; i < n_replicas; i++) {
    Replica &r = e->replicas[i];
    r.data = m->create(model_param);
    r.rwlock = nr_rwlock_create(kMaxThreads);
    r.combiner = new std::atomic<uint32_t>[e->nlogs]();
    r.records = new PubRecord[kMaxThreads]();
  }
  return e;
}

void nr_engine_destroy(Engine *e) {
  for (int i = 0; i < e->n_replicas; i++) {
    Replica &r = e->replicas[i];
    e->model->destroy(r.data);
    nr_rwlock_destroy(r.rwlock);
    delete[] r.combiner;
    delete[] r.records;
  }
  for (int i = 0; i < e->nlogs; i++) e->logs[i].destroy();
  delete[] e->logs;
  delete[] e->replicas;
  delete e;
}

// Register a thread on replica rid (`Replica::register`,
// `nr/src/replica.rs:279-298`); returns tid or -1.
int nr_register(Engine *e, int rid) {
  if (rid < 0 || rid >= e->n_replicas) return -1;
  int tid = e->replicas[rid].n_threads.fetch_add(1);
  if (tid >= kMaxThreads) return -1;
  return tid;
}

// Replay `[ltails[rid], tail)` of log `li` into replica rid's data.
// Caller must hold the (rid, li) combiner lock. In single-log mode the
// data write-lock is taken (readers use the distributed rwlock); in CNR
// mode dispatch is lock-free by the commutativity contract.
static void log_exec(Engine *e, int rid, int li) {
  Log &lg = e->logs[li];
  Replica &rep = e->replicas[rid];
  uint64_t t = lg.tail.load(std::memory_order_acquire);
  uint64_t lt = lg.ltails[rid].v.load(std::memory_order_relaxed);
  if (lt >= t) return;
  bool lock_data = e->nlogs == 1;
  if (lock_data) nr_rwlock_write_acquire(rep.rwlock);
  for (uint64_t pos = lt; pos < t; pos++) {
    Entry &cell = lg.ring[pos & lg.mask];
    uint64_t spins = 0;
    while (cell.seq.load(std::memory_order_acquire) != pos + 1) {
      cpu_relax();
      if (++spins == kWarnSpins) e->warn_events.fetch_add(1);
    }
    int32_t resp = e->model->dispatch_mut(rep.data, cell.opcode, cell.args);
    if (cell.rid == (uint32_t)rid) {
      // Deliver the response to the issuing record: args[kArgW-1] slot of
      // the entry carries (tid << 8 | batch_index) routing.
      uint32_t route = (uint32_t)cell.args[kArgW - 1];
      int tid = (int)(route >> 8);
      int slot = (int)(route & 0xff);
      PubRecord &rec = rep.records[tid];
      rec.resps[slot] = resp;
      // last response (across ALL logs the batch spans) completes the
      // record; per-log replay order means slots complete out of order
      if (rec.remaining.fetch_sub(1, std::memory_order_acq_rel) == 1)
        rec.state.store(REC_DONE, std::memory_order_release);
    }
  }
  lg.ltails[rid].v.store(t, std::memory_order_release);
  if (lock_data) nr_rwlock_write_release(rep.rwlock);
  // ctail = fetch_max(t) (`nr/src/log.rs:520-523`).
  uint64_t c = lg.ctail.load(std::memory_order_relaxed);
  while (c < t && !lg.ctail.compare_exchange_weak(c, t)) {
  }
}

// Append n ops for replica rid to log li. Caller holds the combiner lock.
// Helps GC (replays its own replica) when space is short, then counts a
// stuck event if other replicas still pin the head — the reference's
// "appenders must help" + starvation-callback semantics
// (`nr/src/log.rs:364-387`, `cnr/src/log.rs:505-515`).
static uint64_t log_append(Engine *e, int rid, int li, int n,
                           const int32_t *opcodes,
                           const int32_t (*args)[kArgW]) {
  Log &lg = e->logs[li];
  uint64_t spins = 0;
  for (;;) {
    uint64_t t = lg.tail.load(std::memory_order_relaxed);
    uint64_t h = lg.head.load(std::memory_order_relaxed);
    uint64_t slack = lg.capacity > 2 * kGcSlack ? kGcSlack : lg.capacity / 4;
    if (t + n > h + lg.capacity - slack) {
      // advance_head = min(ltails) (`nr/src/log.rs:536-580`).
      uint64_t m = lg.min_ltail();
      while (h < m && !lg.head.compare_exchange_weak(h, m)) {
      }
      if (t + n > m + lg.capacity - slack) {
        log_exec(e, rid, li);  // help with our own replica
        if (lg.min_ltail() + lg.capacity < t + n + slack)
          if (++spins == 4) e->stuck_events.fetch_add(1);
        cpu_relax();
        continue;
      }
    }
    if (lg.tail.compare_exchange_weak(t, t + n,
                                      std::memory_order_acq_rel)) {
      for (int i = 0; i < n; i++) {
        uint64_t pos = t + i;
        Entry &cell = lg.ring[pos & lg.mask];
        cell.opcode = opcodes[i];
        cell.rid = (uint32_t)rid;
        std::memcpy(cell.args, args[i], sizeof(cell.args));
        cell.seq.store(pos + 1, std::memory_order_release);
      }
      return t;
    }
  }
}

// Flat-combining pass for (rid, li): collect STAGED records mapped to this
// log, append their ops, replay (`Replica::combine`,
// `nr/src/replica.rs:543-595`; per-log variant `cnr/src/replica.rs:673-720`).
// Speculative seqlock reads: a combiner reads a record's plain fields
// BEFORE validating seq, and discards the copy on mismatch — the
// standard seqlock pattern, formally a data race on the publication
// writes. These two helpers carry exactly those reads un-instrumented
// under -fsanitize=thread (NR_TPU_TSAN=1 build) so ThreadSanitizer stays
// meaningful for everything else (ring cells, cursors, response slots).
__attribute__((no_sanitize("thread"))) static inline int32_t
spec_read_i32(const int32_t *p) {
  return *p;
}
__attribute__((no_sanitize("thread"))) static inline void
spec_copy(void *dst, const void *src, size_t bytes) {
  // hand-rolled: a memcpy call would route through TSAN's interposed
  // libc memcpy, which reports regardless of this function's attribute
  auto *d = static_cast<char *>(dst);
  auto *s = static_cast<const char *>(src);
  for (size_t i = 0; i < bytes; i++) d[i] = s[i];
}

static void combine(Engine *e, int rid, int li) {
  Replica &rep = e->replicas[rid];
  int nt = rep.n_threads.load(std::memory_order_acquire);
  if (nt > kMaxThreads) nt = kMaxThreads;
  int32_t opcodes[kMaxBatch * 8];
  int32_t args[kMaxBatch * 8][kArgW];
  int n = 0;
  for (int tid = 0; tid < nt; tid++) {
    PubRecord &rec = rep.records[tid];
    // Seqlock-validated collection: snapshot seq, skip records mid-
    // publication, scan, then re-validate before committing. Without
    // this, a combiner that stalled after loading state==STAGED could
    // watch the batch complete, the owner re-stage, and then collect a
    // HALF-PUBLISHED new batch (torn args, lost remaining decrements).
    uint32_t s1 = rec.seq.load(std::memory_order_acquire);
    if (s1 & 1u) continue;  // owner mid-publication
    if (rec.state.load(std::memory_order_acquire) != REC_STAGED) continue;
    int cnt = spec_read_i32(&rec.count);
    if (cnt < 0) cnt = 0;
    if (cnt > kMaxBatch) cnt = kMaxBatch;  // torn read guard (validated)
    int cand[kMaxBatch];
    int nc = 0;
    int base = n;
    for (int j = 0; j < cnt && n < kMaxBatch * 8; j++) {
      // collect only this log's ops (per-op hash tags, the cnr context
      // filter `cnr/src/context.rs:138-167`); -1 marks already-collected.
      // Disjoint logs' combiners touch disjoint j's; the (rid, li)
      // combiner lock orders successive combiners of the SAME log.
      if (rec.op_log[j].load(std::memory_order_relaxed) != li) continue;
      cand[nc++] = j;
      opcodes[n] = spec_read_i32(&rec.opcodes[j]);
      spec_copy(args[n], rec.args[j], sizeof(args[n]));
      // Response routing rides the last arg lane (tid<<8 | slot).
      args[n][kArgW - 1] = (int32_t)(((uint32_t)tid << 8) | (uint32_t)j);
      n++;
    }
    // Canonical seqlock reader: an acquire fence orders the speculative
    // plain reads above BEFORE the validating seq load — an acquire load
    // alone does not order preceding reads (ADVICE r3; benign on
    // x86-TSO, required by the C++ memory model).
    std::atomic_thread_fence(std::memory_order_acquire);
    if (rec.seq.load(std::memory_order_relaxed) != s1) {
      n = base;  // re-staged mid-scan: discard; a later pass collects it
      continue;
    }
    // Validation pinned the record: its li-tagged ops can only be
    // collected by us (we hold the (rid, li) lock), so it cannot
    // complete — nor be re-staged — before we append. Commit.
    for (int m = 0; m < nc; m++)
      rec.op_log[cand[m]].store(-1, std::memory_order_relaxed);
    // The record stays STAGED until every op's response has landed
    // (remaining-counted in log_exec); other logs' combiners still see
    // and collect their slots meanwhile.
  }
  if (n > 0) log_append(e, rid, li, n, opcodes, args);
  log_exec(e, rid, li);
}

static bool try_combine(Engine *e, int rid, int li) {
  Replica &rep = e->replicas[rid];
  uint32_t expect = 0;
  if (!rep.combiner[li].compare_exchange_strong(
          expect, 1, std::memory_order_acq_rel, std::memory_order_relaxed))
    return false;
  combine(e, rid, li);
  rep.combiner[li].store(0, std::memory_order_release);
  return true;
}

static inline int map_log(Engine *e, const int32_t *args) {
  // Native LogMapper: key-partitioned (`hash % nlogs`,
  // `cnr/src/replica.rs:435`). The key must be canonicalized exactly as
  // the model canonicalizes it (mod model_param): two raw keys that alias
  // the same cell conflict, so they MUST map to the same log
  // (`cnr/src/lib.rs:123-137`).
  if (e->nlogs == 1) return 0;
  int64_t k = ((int64_t)args[0] % e->model_param + e->model_param) %
              e->model_param;
  return (int)((uint64_t)k % (uint64_t)e->nlogs);
}

// Batched write path: stage up to kMaxBatch ops and wait for responses
// (`Replica::execute_mut`, `nr/src/replica.rs:345-356`, batch form).
int nr_execute_mut_batch(Engine *e, int rid, int tid, int n,
                         const int32_t *opcodes, const int32_t *args_flat,
                         int32_t *resps_out) {
  if (n < 1 || n > kMaxBatch) return -1;
  Replica &rep = e->replicas[rid];
  PubRecord &rec = rep.records[tid];
  // Publish under the record seqlock: seq odd while fields are being
  // written, even + STAGED once stable (see PubRecord).
  rec.seq.fetch_add(1, std::memory_order_relaxed);
  rec.count = n;
  // A batch may span logs: tag each op with its LogMapper hash (the
  // cnr hash-tagged context slots, `cnr/src/context.rs:18`); per-log
  // combiners each collect their own sub-batch in one pass — CNR writes
  // are batched per log, not issued per op.
  int involved[kMaxBatch];
  int n_involved = 0;
  for (int j = 0; j < n; j++) {
    rec.opcodes[j] = opcodes[j];
    const int32_t *a = args_flat + j * (kArgW - 1);
    rec.args[j][0] = a[0];
    rec.args[j][1] = a[1];
    rec.args[j][2] = a[2];
    rec.args[j][kArgW - 1] = 0;
    int li = map_log(e, rec.args[j]);
    rec.op_log[j].store(li, std::memory_order_relaxed);
    bool seen = false;
    for (int m = 0; m < n_involved; m++) seen |= involved[m] == li;
    if (!seen) involved[n_involved++] = li;
  }
  rec.remaining.store(n, std::memory_order_relaxed);
  rec.seq.fetch_add(1, std::memory_order_release);
  rec.state.store(REC_STAGED, std::memory_order_release);
  uint64_t spins = 0;
  while (rec.state.load(std::memory_order_acquire) != REC_DONE) {
    bool helped = false;
    for (int m = 0; m < n_involved; m++)
      helped |= try_combine(e, rid, involved[m]);
    if (!helped) cpu_relax();
    if (rec.state.load(std::memory_order_acquire) == REC_DONE) break;
    if (++spins == kWarnSpins) e->warn_events.fetch_add(1);
  }
  rec.state.store(REC_EMPTY, std::memory_order_relaxed);
  for (int j = 0; j < n; j++) resps_out[j] = rec.resps[j];
  return 0;
}

int32_t nr_execute_mut(Engine *e, int rid, int tid, int32_t opcode,
                       const int32_t *args) {
  int32_t resp = INT32_MIN;
  int rc = nr_execute_mut_batch(e, rid, tid, 1, &opcode, args, &resp);
  return rc == 0 ? resp : INT32_MIN;
}

void nr_sync(Engine *e, int rid);

// Read path (`read_only`, `nr/src/replica.rs:483-497`): wait until this
// replica has replayed to the completed tail of the mapped log (helping
// combine while waiting), then dispatch locally under the read lock.
int32_t nr_execute(Engine *e, int rid, int tid, int32_t opcode,
                   const int32_t *args) {
  if (e->nlogs > 1 && opcode >= 0 && opcode < 32 &&
      (e->model->multikey_rd_mask >> opcode) & 1u) {
    // Multi-key aggregate read: it conflicts with writes on every log, so
    // a single-log ctail gate cannot linearize it. Catch this replica up
    // on ALL logs first (the cross-log read barrier the LogMapper
    // contract demands, cnr/src/lib.rs:123-137).
    nr_sync(e, rid);
    int32_t a[kArgW] = {args[0], args[1], args[2], 0};
    return e->model->dispatch_rd(e->replicas[rid].data, opcode, a);
  }
  int li = map_log(e, args);
  Log &lg = e->logs[li];
  Replica &rep = e->replicas[rid];
  uint64_t c = lg.ctail.load(std::memory_order_acquire);
  uint64_t spins = 0;
  while (lg.ltails[rid].v.load(std::memory_order_acquire) < c) {
    if (!try_combine(e, rid, li)) cpu_relax();
    if (++spins == kWarnSpins) e->warn_events.fetch_add(1);
  }
  int32_t a[kArgW] = {args[0], args[1], args[2], 0};
  int32_t resp;
  if (e->nlogs == 1) {
    nr_rwlock_read_acquire(rep.rwlock, tid);
    resp = e->model->dispatch_rd(rep.data, opcode, a);
    nr_rwlock_read_release(rep.rwlock, tid);
  } else {
    resp = e->model->dispatch_rd(rep.data, opcode, a);
  }
  return resp;
}

// Batched read path: flat combining applied to the READ side. One ctail
// gate and one read-lock hold cover n local dispatches. The reference's
// readers scale because its per-slot reader lock is nearly free on a big
// NUMA box (`nr/src/rwlock.rs:148-179`); on a small host the per-op cost
// is dominated by the seq_cst announce/check pair in read_acquire plus
// the ctail/ltail acquire loads of the gate (r4 measured NR wr=0 LOSING
// 2x to a contended global mutex) — so amortize them per batch, exactly
// as nr_execute_mut_batch amortizes the log reservation per 32 writes.
// Linearization: the lock is held across all n dispatches, so no
// combiner can apply between them — the whole batch reads ONE state that
// is >= every op completed before the call (the same `ltail >= ctail`
// guarantee as the per-op path, `nr/src/replica.rs:483-497`).
int32_t nr_execute_batch(Engine *e, int rid, int tid, int n,
                         const int32_t *opcodes, const int32_t *args_flat,
                         int32_t *resps_out) {
  if (n <= 0) return 0;
  Replica &rep = e->replicas[rid];
  if (e->nlogs > 1) {
    // multi-log reads gate per op (each key maps to its own log's
    // ctail; multikey reads sync all logs) — no shared gate to amortize
    for (int j = 0; j < n; j++)
      resps_out[j] = nr_execute(e, rid, tid, opcodes[j],
                                args_flat + j * (kArgW - 1));
    return 0;
  }
  Log &lg = e->logs[0];
  uint64_t c = lg.ctail.load(std::memory_order_acquire);
  uint64_t spins = 0;
  while (lg.ltails[rid].v.load(std::memory_order_acquire) < c) {
    if (!try_combine(e, rid, 0)) cpu_relax();
    if (++spins == kWarnSpins) e->warn_events.fetch_add(1);
  }
  nr_rwlock_read_acquire(rep.rwlock, tid);
  for (int j = 0; j < n; j++) {
    const int32_t *a = args_flat + j * (kArgW - 1);
    int32_t aa[kArgW] = {a[0], a[1], a[2], 0};
    resps_out[j] = e->model->dispatch_rd(rep.data, opcodes[j], aa);
  }
  nr_rwlock_read_release(rep.rwlock, tid);
  return 0;
}

// Catch replica rid up on every log (`Replica::sync`,
// `nr/src/replica.rs:469-479`; all-logs loop `cnr/src/replica.rs:579-597`).
void nr_sync(Engine *e, int rid) {
  for (int li = 0; li < e->nlogs; li++) {
    for (;;) {
      Log &lg = e->logs[li];
      if (lg.ltails[rid].v.load(std::memory_order_acquire) >=
          lg.tail.load(std::memory_order_acquire))
        break;
      if (!try_combine(e, rid, li)) cpu_relax();
    }
  }
}

// Targeted single-log sync (`sync_log`, `cnr/src/replica.rs:579-597`).
void nr_sync_log(Engine *e, int rid, int li) {
  for (;;) {
    Log &lg = e->logs[li];
    if (lg.ltails[rid].v.load(std::memory_order_acquire) >=
        lg.tail.load(std::memory_order_acquire))
      break;
    if (!try_combine(e, rid, li)) cpu_relax();
  }
}

// verify() test hook (`Replica::verify`, `nr/src/replica.rs:443-467`):
// sync, then dump replica state for host-side assertions.
int64_t nr_state_words(Engine *e) {
  return e->model->state_words(e->replicas[0].data);
}
void nr_state_dump(Engine *e, int rid, int32_t *out) {
  nr_sync(e, rid);
  e->model->state_dump(e->replicas[rid].data, out);
}

uint64_t nr_stuck_events(Engine *e) { return e->stuck_events.load(); }
uint64_t nr_warn_events(Engine *e) { return e->warn_events.load(); }
uint64_t nr_log_tail(Engine *e, int li) { return e->logs[li].tail.load(); }
uint64_t nr_log_head(Engine *e, int li) { return e->logs[li].head.load(); }
uint64_t nr_log_ctail(Engine *e, int li) { return e->logs[li].ctail.load(); }
uint64_t nr_log_ltail(Engine *e, int li, int rid) {
  return e->logs[li].ltails[rid].v.load();
}
int nr_max_batch() { return kMaxBatch; }

// -------------------------------------------------------------- bench loops

// Measured in-process so thread loops never cross the FFI per op. A splitmix
// PRNG picks keys/ops; write ratio in percent. Returns total completed ops;
// per-thread counts land in out_per_thread (reference prints aggregate +
// min/max per core, `benches/mkbench.rs:592-604`).
static inline uint64_t splitmix(uint64_t &x) {
  x += 0x9e3779b97f4a7c15ull;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

uint64_t nr_bench_hashmap(Engine *e, int threads_per_replica, int write_pct,
                          int64_t keyspace, int batch, int duration_ms,
                          uint64_t seed, uint64_t *out_per_thread,
                          uint64_t *out_per_sec, int max_secs) {
  // out_per_sec (nullable): [total_threads, max_secs] row-major bins of
  // completed ops by elapsed wall-clock second per thread — the real
  // per-(thread, second) records the reference CSV captures
  // (`benches/mkbench.rs:498-552`), not a post-hoc division.
  int total_threads = e->n_replicas * threads_per_replica;
  std::vector<std::thread> ts;
  std::vector<uint64_t> counts(total_threads, 0);
  std::vector<uint64_t> sec_bins(
      out_per_sec ? (size_t)total_threads * max_secs : 0, 0);
  std::atomic<int> ready{0};
  std::atomic<bool> go{false}, stop{false};
  if (batch < 1) batch = 1;
  if (batch > kMaxBatch) batch = kMaxBatch;
  for (int g = 0; g < total_threads; g++) {
    ts.emplace_back([&, g]() {
      int rid = g % e->n_replicas;
      int tid = nr_register(e, rid);
      uint64_t rng = seed + 0x1000 * g + 1;
      ready.fetch_add(1);
      if (tid < 0) return;  // registration slots exhausted: sit out
      while (!go.load(std::memory_order_acquire)) cpu_relax();
      auto t0 = std::chrono::steady_clock::now();
      uint64_t done = 0, batch_start = 0;
      int32_t opcodes[kMaxBatch];
      int32_t args[kMaxBatch][3];
      int32_t resps[kMaxBatch];
      int32_t r_opcodes[kMaxBatch];
      int32_t r_args[kMaxBatch][3];
      int32_t r_resps[kMaxBatch];
      while (!stop.load(std::memory_order_relaxed)) {
        batch_start = done;
        int nw = 0, nrd = 0;
        for (int j = 0; j < batch; j++) {
          uint64_t r = splitmix(rng);
          int32_t key = (int32_t)(r % (uint64_t)keyspace);
          // Op-type decision from the high bits so it stays independent of
          // the key when gcd(keyspace, 100) > 1.
          if ((int)((r >> 40) % 100) < write_pct) {
            opcodes[nw] = 1;  // put
            args[nw][0] = key;
            args[nw][1] = (int32_t)(r >> 33);
            args[nw][2] = 0;
            nw++;
          } else {
            r_opcodes[nrd] = 1;  // get
            r_args[nrd][0] = key;
            r_args[nrd][1] = 0;
            r_args[nrd][2] = 0;
            nrd++;
          }
        }
        if (nrd > 0) {
          // reads ride the batched read path: one ctail gate + one
          // read-lock hold for the whole run (the read-side flat
          // combining that rescued wr=0 on this host, r5)
          nr_execute_batch(e, rid, tid, nrd, r_opcodes, &r_args[0][0],
                           r_resps);
          done += nrd;
        }
        if (nw > 0) {
          // one flat-combining batch either way: in CNR mode the record's
          // per-op log tags let each log's combiner collect its own
          // sub-batch, so multi-log runs keep the 32x batching instead of
          // degrading to per-op calls (VERDICT r2 weak #5)
          nr_execute_mut_batch(e, rid, tid, nw, opcodes, &args[0][0],
                               resps);
          done += nw;
        }
        if (out_per_sec) {
          // one clock read per batch, not per op
          int64_t sec = std::chrono::duration_cast<std::chrono::seconds>(
                            std::chrono::steady_clock::now() - t0)
                            .count();
          if (sec >= max_secs) sec = max_secs - 1;
          sec_bins[(size_t)g * max_secs + sec] += done - batch_start;
        }
      }
      counts[g] = done;
      // Keep replaying until everyone is done so no replica pins the head
      // (end-of-run protocol, `benches/mkbench.rs:799-821`).
      nr_sync(e, rid);
    });
  }
  while (ready.load() != total_threads) cpu_relax();
  go.store(true, std::memory_order_release);
  std::this_thread::sleep_for(std::chrono::milliseconds(duration_ms));
  stop.store(true);
  for (auto &t : ts) t.join();
  uint64_t total = 0;
  for (int g = 0; g < total_threads; g++) {
    total += counts[g];
    if (out_per_thread) out_per_thread[g] = counts[g];
  }
  if (out_per_sec)
    std::copy(sec_bins.begin(), sec_bins.end(), out_per_sec);
  return total;
}

// Raw append throughput, no replay (`benches/log.rs:48-79` analog).
uint64_t nr_bench_log_append(uint64_t log_capacity, int n_threads, int batch,
                             int duration_ms) {
  Log lg;
  lg.init(log_capacity, 1);
  // Keep the single replica's ltail pinned to tail so GC never blocks
  // (the reference disables GC by resetting, `benches/log.rs:60-66`):
  // mark it caught-up from a chaser thread.
  std::atomic<bool> stop{false};
  std::thread chaser([&]() {
    while (!stop.load(std::memory_order_relaxed)) {
      uint64_t t = lg.tail.load(std::memory_order_acquire);
      lg.ltails[0].v.store(t, std::memory_order_release);
      std::this_thread::yield();
    }
  });
  std::vector<std::thread> ts;
  std::vector<uint64_t> counts(n_threads, 0);
  std::atomic<bool> go{false};
  if (batch < 1) batch = 1;
  for (int g = 0; g < n_threads; g++) {
    ts.emplace_back([&, g]() {
      while (!go.load(std::memory_order_acquire)) cpu_relax();
      uint64_t done = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        for (;;) {
          uint64_t t = lg.tail.load(std::memory_order_relaxed);
          uint64_t h = lg.ltails[0].v.load(std::memory_order_relaxed);
          if (t + batch > h + lg.capacity) {
            // Ring full: space only appears when the chaser advances
            // ltails, and the chaser exits as soon as `stop` is set —
            // without this check an appender caught here at stop time
            // spins forever and join() hangs (observed as a rare
            // full-suite livelock under CPU load; the inner loop
            // otherwise never reads `stop`).
            if (stop.load(std::memory_order_relaxed)) break;
            cpu_relax();
            continue;
          }
          if (lg.tail.compare_exchange_weak(t, t + batch)) {
            for (int i = 0; i < batch; i++) {
              Entry &cell = lg.ring[(t + i) & lg.mask];
              cell.opcode = 1;
              cell.rid = 0;
              cell.seq.store(t + i + 1, std::memory_order_release);
            }
            done += batch;
            break;
          }
        }
      }
      counts[g] = done;
    });
  }
  go.store(true, std::memory_order_release);
  std::this_thread::sleep_for(std::chrono::milliseconds(duration_ms));
  stop.store(true);
  for (auto &t : ts) t.join();
  chaser.join();
  lg.destroy();
  uint64_t total = 0;
  for (auto c : counts) total += c;
  return total;
}

// RwLock bench: r readers + w writers hammering one lock for duration_ms
// (`benches/rwlockbench.rs` analog). Returns ops; writer ops via out_writes.
uint64_t nr_bench_rwlock(int n_readers, int n_writers, int duration_ms,
                         uint64_t *out_writes) {
  NrRwLock *l = nr_rwlock_create(kMaxThreads);
  std::atomic<bool> go{false}, stop{false};
  std::vector<std::thread> ts;
  std::vector<uint64_t> rc(n_readers, 0), wc(n_writers, 0);
  volatile uint64_t shared = 0;
  for (int g = 0; g < n_readers; g++) {
    ts.emplace_back([&, g]() {
      while (!go.load()) cpu_relax();
      uint64_t n = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        nr_rwlock_read_acquire(l, g);
        uint64_t v = shared;
        (void)v;
        nr_rwlock_read_release(l, g);
        n++;
      }
      rc[g] = n;
    });
  }
  for (int g = 0; g < n_writers; g++) {
    ts.emplace_back([&, g]() {
      while (!go.load()) cpu_relax();
      uint64_t n = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        nr_rwlock_write_acquire(l);
        shared = shared + 1;
        nr_rwlock_write_release(l);
        n++;
      }
      wc[g] = n;
    });
  }
  go.store(true);
  std::this_thread::sleep_for(std::chrono::milliseconds(duration_ms));
  stop.store(true);
  for (auto &t : ts) t.join();
  uint64_t reads = 0, writes = 0;
  for (auto c : rc) reads += c;
  for (auto c : wc) writes += c;
  if (out_writes) *out_writes = writes;
  nr_rwlock_destroy(l);
  return reads + writes;
}

// ------------------------------------------- comparison baselines (non-NR)
//
// The reference's headline artifact is NR *versus other systems*
// (`benches/hashmap_comparisons.rs:25-435`: chashmap/std+RwLock/flurry/
// dash/urcu). These are the zero-dependency equivalents behind the same
// splitmix workload loop as nr_bench_hashmap, so hashbench can print
// NR-vs-non-NR lines (VERDICT r1 #6 / missing #2).

// A single std::unordered_map guarded by one mutex: the `std` wrapper of
// `benches/hashmap_comparisons.rs:144-176` (theirs uses an RwLock; a
// mutex is the conservative floor every system must beat).
uint64_t nr_bench_cmp_mutex(int n_threads, int write_pct, int64_t keyspace,
                            int batch, int duration_ms, uint64_t seed,
                            uint64_t *out_per_thread) {
  std::unordered_map<int64_t, int64_t> map;
  std::mutex mu;
  std::vector<std::thread> ts;
  std::vector<uint64_t> counts(n_threads, 0);
  std::atomic<bool> go{false}, stop{false};
  if (batch < 1) batch = 1;
  for (int g = 0; g < n_threads; g++) {
    ts.emplace_back([&, g]() {
      uint64_t rng = seed + 0x1000 * g + 1;
      while (!go.load(std::memory_order_acquire)) cpu_relax();
      uint64_t done = 0;
      volatile int64_t sink = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        for (int j = 0; j < batch; j++) {
          uint64_t r = splitmix(rng);
          int64_t key = (int64_t)(r % (uint64_t)keyspace);
          std::lock_guard<std::mutex> lk(mu);
          if ((int)((r >> 40) % 100) < write_pct) {
            map[key] = (int64_t)(r >> 33);
          } else {
            auto it = map.find(key);
            sink = it == map.end() ? -1 : it->second;
          }
          done++;
        }
      }
      (void)sink;
      counts[g] = done;
    });
  }
  go.store(true, std::memory_order_release);
  std::this_thread::sleep_for(std::chrono::milliseconds(duration_ms));
  stop.store(true);
  for (auto &t : ts) t.join();
  uint64_t total = 0;
  for (int g = 0; g < n_threads; g++) {
    total += counts[g];
    if (out_per_thread) out_per_thread[g] = counts[g];
  }
  return total;
}

// One private std::unordered_map per thread over a key congruence class:
// the `Partitioner<T>` upper bound (`benches/hashmap_comparisons.rs:
// 25-84` — no sharing, no coordination, perfect write scaling).
uint64_t nr_bench_cmp_partitioned(int n_threads, int write_pct,
                                  int64_t keyspace, int batch,
                                  int duration_ms, uint64_t seed,
                                  uint64_t *out_per_thread) {
  std::vector<std::thread> ts;
  std::vector<uint64_t> counts(n_threads, 0);
  std::atomic<bool> go{false}, stop{false};
  if (batch < 1) batch = 1;
  for (int g = 0; g < n_threads; g++) {
    ts.emplace_back([&, g]() {
      std::unordered_map<int64_t, int64_t> shard;  // thread-private
      uint64_t rng = seed + 0x1000 * g + 1;
      while (!go.load(std::memory_order_acquire)) cpu_relax();
      uint64_t done = 0;
      volatile int64_t sink = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        for (int j = 0; j < batch; j++) {
          uint64_t r = splitmix(rng);
          // keys in this thread's congruence class only (the partitioner
          // contract: ops are pre-routed to their shard's owner). Draw
          // from the keyspace truncated to a multiple of n_threads so the
          // rounding never produces key >= keyspace (ADVICE r2).
          int64_t k_eff = keyspace / n_threads * n_threads;
          if (k_eff < n_threads) k_eff = n_threads;
          int64_t key =
              (int64_t)(r % (uint64_t)k_eff) / n_threads * n_threads + g;
          if ((int)((r >> 40) % 100) < write_pct) {
            shard[key] = (int64_t)(r >> 33);
          } else {
            auto it = shard.find(key);
            sink = it == shard.end() ? -1 : it->second;
          }
          done++;
        }
      }
      (void)sink;
      counts[g] = done;
    });
  }
  go.store(true, std::memory_order_release);
  std::this_thread::sleep_for(std::chrono::milliseconds(duration_ms));
  stop.store(true);
  for (auto &t : ts) t.join();
  uint64_t total = 0;
  for (int g = 0; g < n_threads; g++) {
    total += counts[g];
    if (out_per_thread) out_per_thread[g] = counts[g];
  }
  return total;
}

// The READ-OPTIMIZED comparison class: a left-right (evmap-style)
// reader/writer-split map — the specialist the reference brackets NR
// against on read-mostly mixes (`benches/hashbench.rs:26-105` drives
// evmap; its README graphs lead with it). Two dense table copies;
// readers pin the active copy by announcing an epoch in a padded
// per-thread slot (one release store + one acquire load per BATCH of
// reads — wait-free, no RMW on the read path at all, cheaper than the
// lock-free map's CAS-free-but-atomic probe loop); the writer (one
// mutex among writers, as evmap serializes via its WriteHandle) applies
// a batch to the standby copy, flips `active`, waits for readers still
// pinned to the old epoch to drain, then replays the same batch onto
// the other copy so both stay converged. Strongest at wr=0 (reads never
// see a writer's cache line); collapses under writes (every write is
// applied twice + an epoch drain) — exactly the trade the reference's
// evmap rows show.
uint64_t nr_bench_cmp_evmap(int n_threads, int write_pct, int64_t keyspace,
                            int batch, int duration_ms, uint64_t seed,
                            uint64_t *out_per_thread) {
  if (keyspace < 1) keyspace = 1;
  // the SAME open-addressing layout as the lockfree map (power-of-two
  // table, 2x keyspace, mixed hash, (key+1)<<32|value packing) so the
  // bracket isolates the sync protocol — left-right copies vs per-op
  // atomics — instead of rewarding a degenerate direct-mapped array
  // (the r4-review rule applied to this system)
  if (keyspace > (int64_t)1 << 26) return UINT64_MAX;  // 2x1 GiB cap
  uint64_t cap = 1;
  while (cap < (uint64_t)keyspace * 2) cap <<= 1;
  const uint64_t mask = cap - 1;
  std::vector<uint64_t> tbl[2];
  tbl[0].assign(cap, 0);
  tbl[1].assign(cap, 0);
  std::atomic<int> active{0};
  // per-thread epoch pin: -1 = not reading; else the copy index pinned
  static_assert(sizeof(PaddedAtomicU64) == 64, "padding");
  std::vector<PaddedAtomicU64> pins(n_threads);
  for (auto &p : pins) p.v.store((uint64_t)-1, std::memory_order_relaxed);
  std::mutex wmu;
  std::vector<std::thread> ts;
  std::vector<uint64_t> counts(n_threads, 0);
  std::atomic<bool> go{false}, stop{false};
  if (batch < 1) batch = 1;
  for (int g = 0; g < n_threads; g++) {
    ts.emplace_back([&, g]() {
      uint64_t rng = seed + 0x1000 * g + 1;
      std::vector<std::pair<int64_t, int64_t>> wbuf;
      std::vector<int64_t> rkeys(batch);
      wbuf.reserve(batch);
      while (!go.load(std::memory_order_acquire)) cpu_relax();
      uint64_t done = 0;
      volatile int64_t sink = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        wbuf.clear();
        int nrd = 0;
        for (int j = 0; j < batch; j++) {
          uint64_t r = splitmix(rng);
          int64_t key = (int64_t)(r % (uint64_t)keyspace);
          if ((int)((r >> 40) % 100) < write_pct)
            wbuf.emplace_back(key, (int64_t)(r >> 33));
          else
            rkeys[nrd++] = key;
        }
        if (nrd > 0) {
          // pin the active copy once per read batch (seq_cst on the
          // pin/check pair: the writer's flip-then-scan must not pass
          // our pin-then-read on non-TSO targets). Pin-then-VERIFY must
          // LOOP: each lost race re-pins, and only an unchanged
          // re-read of `active` proves the writer's drain will see this
          // pin before replaying onto the pinned copy.
          int a = active.load(std::memory_order_seq_cst);
          pins[g].v.store((uint64_t)a, std::memory_order_seq_cst);
          for (;;) {
            int a2 = active.load(std::memory_order_seq_cst);
            if (a2 == a) break;
            a = a2;
            pins[g].v.store((uint64_t)a, std::memory_order_seq_cst);
          }
          const uint64_t *t = tbl[a].data();
          for (int j = 0; j < nrd; j++) {
            uint64_t key = (uint64_t)rkeys[j];
            uint64_t tag = (key + 1) << 32;
            uint64_t h = key * 0x9e3779b97f4a7c15ull;
            h ^= h >> 29;
            sink = -1;
            for (uint64_t probe = 0;; probe++) {
              uint64_t cur = t[(h + probe) & mask];
              if ((cur & ~0xffffffffull) == tag) {
                sink = (int64_t)(cur & 0xffffffff);
                break;
              }
              if (cur == 0) break;  // empty slot ends the chain
            }
          }
          pins[g].v.store((uint64_t)-1, std::memory_order_release);
          done += nrd;
        }
        if (!wbuf.empty()) {
          std::lock_guard<std::mutex> lk(wmu);
          int a = active.load(std::memory_order_relaxed);
          auto apply = [&](std::vector<uint64_t> &t) {
            for (auto &kv : wbuf) {
              uint64_t key = (uint64_t)kv.first;
              uint64_t tag = (key + 1) << 32;
              uint64_t h = key * 0x9e3779b97f4a7c15ull;
              h ^= h >> 29;
              uint64_t packed = tag | (uint32_t)kv.second;
              for (uint64_t probe = 0;; probe++) {
                uint64_t &slot = t[(h + probe) & mask];
                if (slot == 0 || (slot & ~0xffffffffull) == tag) {
                  slot = packed;
                  break;
                }
              }
            }
          };
          apply(tbl[1 - a]);
          active.store(1 - a, std::memory_order_seq_cst);
          // drain readers still pinned to the old copy, then replay the
          // batch there so the copies reconverge
          for (int t2 = 0; t2 < n_threads; t2++)
            while (pins[t2].v.load(std::memory_order_seq_cst) ==
                   (uint64_t)a)
              cpu_relax();
          apply(tbl[a]);
          done += wbuf.size();
        }
      }
      (void)sink;
      counts[g] = done;
    });
  }
  go.store(true, std::memory_order_release);
  std::this_thread::sleep_for(std::chrono::milliseconds(duration_ms));
  stop.store(true);
  for (auto &t : ts) t.join();
  uint64_t total = 0;
  for (int g = 0; g < n_threads; g++) {
    total += counts[g];
    if (out_per_thread) out_per_thread[g] = counts[g];
  }
  return total;
}

// A LOCK-FREE open-addressing concurrent map: the competitive middle the
// reference's headline graphs lead with (urcu gets within ~2x of NR on
// read-heavy loads, `benches/hashmap_comparisons.rs:281-435`;
// `nr/README.md:85-96`). Design: power-of-two table of single
// std::atomic<uint64_t> slots packing (key+1) << 32 | value32 — a slot
// is CLAIMED and PUBLISHED in one CAS, updated with one store, and read
// with one load, so readers are WAIT-FREE and can never observe a torn
// (key, value) pair; writers are lock-free (the only loop is the probe,
// and a lost CAS means another thread made progress). No deletion — the
// bench workload is put/get, as in the reference's urcu comparison.
// Capacity 2x the keyspace keeps probes short (load factor <= 50%).
uint64_t nr_bench_cmp_lockfree(int n_threads, int write_pct,
                               int64_t keyspace, int batch,
                               int duration_ms, uint64_t seed,
                               uint64_t *out_per_thread) {
  if (keyspace < 1) keyspace = 1;
  // table capacity is bounded (2^27 slots = 1 GiB); oversized keyspaces
  // return UINT64_MAX as an unmistakable error sentinel (a zero would
  // read as a real 0-ops measurement to any caller that skips the
  // Python wrapper's pre-check)
  if (keyspace > (int64_t)1 << 26) return UINT64_MAX;
  uint64_t cap = 1;
  while (cap < (uint64_t)keyspace * 2) cap <<= 1;
  const uint64_t mask = cap - 1;
  std::vector<std::atomic<uint64_t>> table(cap);
  for (auto &s : table) s.store(0, std::memory_order_relaxed);
  std::vector<std::thread> ts;
  std::vector<uint64_t> counts(n_threads, 0);
  std::atomic<bool> go{false}, stop{false};
  if (batch < 1) batch = 1;
  for (int g = 0; g < n_threads; g++) {
    ts.emplace_back([&, g]() {
      uint64_t rng = seed + 0x1000 * g + 1;
      while (!go.load(std::memory_order_acquire)) cpu_relax();
      uint64_t done = 0;
      volatile int64_t sink = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        for (int j = 0; j < batch; j++) {
          uint64_t r = splitmix(rng);
          uint64_t key = r % (uint64_t)keyspace;
          uint64_t tag = (key + 1) << 32;
          // real hash mixing: without it, cap >= 2x keyspace gives every
          // key a private home slot and the "map" degenerates into a
          // direct-mapped atomic array (r4 review)
          uint64_t h = key * 0x9e3779b97f4a7c15ull;
          h ^= h >> 29;
          bool is_write = (int)((r >> 40) % 100) < write_pct;
          uint64_t packed = tag | (uint32_t)(r >> 33);
          for (uint64_t probe = 0;; probe++) {
            uint64_t idx = (h + probe) & mask;
            uint64_t cur = table[idx].load(std::memory_order_acquire);
            if ((cur & ~0xffffffffull) == tag) {  // key present
              if (is_write)
                table[idx].store(packed, std::memory_order_release);
              else
                sink = (int64_t)(cur & 0xffffffff);
              break;
            }
            if (cur == 0) {  // empty slot ends the probe chain
              if (!is_write) { sink = -1; break; }
              uint64_t expect = 0;
              if (table[idx].compare_exchange_strong(
                      expect, packed, std::memory_order_acq_rel,
                      std::memory_order_acquire))
                break;
              // lost the claim: re-examine this slot (expect holds it)
              probe--;
              continue;
            }
            // occupied by another key: keep probing (cap >= 2x keys, so
            // a free slot always exists)
          }
          done++;
        }
      }
      (void)sink;
      counts[g] = done;
    });
  }
  go.store(true, std::memory_order_release);
  std::this_thread::sleep_for(std::chrono::milliseconds(duration_ms));
  stop.store(true);
  for (auto &t : ts) t.join();
  uint64_t total = 0;
  for (int g = 0; g < n_threads; g++) {
    total += counts[g];
    if (out_per_thread) out_per_thread[g] = counts[g];
  }
  return total;
}

}  // extern "C"
