"""`MultiLogReplicated`: the stateful CNR surface (the `cnr` crate's
`Replica` API, per-op form).

Mirrors `cnr/src/replica.rs`: every op is routed to a log by the user's
`LogMapper` (`hash % nlogs`, `cnr/src/replica.rs:435`); writes stage in the
issuing thread's context tagged with their log and combine per log
(`cnr/src/replica.rs:673-720`); reads sync only their mapped log
(`cnr/src/replica.rs:599-617`); `sync()` loops all logs and `sync_log`
targets one (`cnr/src/replica.rs:579-597`). The per-log GC-starvation
callback (`cnr/src/log.rs:135-142`) fires as `gc_callback(log_idx,
dormant_replica)` from the host-side watchdog when a log's replay stalls.

The jit-hot batch path is `core/multilog.make_multilog_step`; this wrapper
is the per-op convenience with the same replay kernels underneath.
"""

from __future__ import annotations

import logging
import threading

from node_replication_tpu.analysis.locks import make_rlock
import time
from collections import deque
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from node_replication_tpu.core.log import LogSpec, LogState, WARN_ROUNDS
from node_replication_tpu.core.multilog import (
    LogMapper,
    MultiLogSpec,
    _exec_one_log,
    multilog_init,
)
from node_replication_tpu.core.replica import (
    BATCH_TID,
    MAX_THREADS_PER_REPLICA,
    LogTooSmallError,
    ReplicaToken,
    _FusedTier,
    _locked,
    _PendingRound,
    replicate_state,
    states_equal,
)
from node_replication_tpu.fault.inject import fault_hook
from node_replication_tpu.obs.metrics import COUNT_BUCKETS, get_registry
from node_replication_tpu.ops.encoding import Dispatch, apply_read, encode_ops
from node_replication_tpu.utils.trace import get_tracer, span

logger = logging.getLogger("node_replication_tpu")


class _PendingCnrBatch:
    """One `begin_mut_batch` batch between begin and finish: the
    per-log `_PendingRound` sub-rounds (in the log order they were
    appended) plus the scatter map back to submission indices. The
    CNR face of the split-round protocol (`core/replica.py:
    _PendingRound`); NOT atomic across logs, like the serial path."""

    __slots__ = ("rid", "n", "subs")

    def __init__(self, rid: int, n: int):
        self.rid = rid
        self.n = n
        #: list of (log_idx, submission_indices, _PendingRound)
        self.subs: list[tuple[int, list[int], _PendingRound]] = []


class MultiLogReplicated(_FusedTier):
    """N replicas of one `Dispatch` behind L commutativity-partitioned logs."""

    def __init__(
        self,
        dispatch: Dispatch,
        log_mapper: LogMapper,
        nlogs: int,
        n_replicas: int = 1,
        log_entries: int = 1 << 12,
        gc_slack: int = 128,
        exec_window: int = 128,
        gc_callback: Callable[[int, int], None] | None = None,
        mesh=None,
        engine: str = "auto",
    ):
        self.spec = MultiLogSpec(
            nlogs=nlogs,
            capacity=log_entries,
            n_replicas=n_replicas,
            arg_width=dispatch.arg_width,
            gc_slack=gc_slack,
        )
        self.dispatch = dispatch
        self.log_mapper = log_mapper
        self.exec_window = int(exec_window)
        self.gc_callback = gc_callback

        self.ml = multilog_init(self.spec)
        self.states = replicate_state(dispatch.init_state(), n_replicas)

        # mesh placement (the NodeReplicated(mesh=) twin): the stacked
        # log rings shard over the mesh 'log' axis, replica states (and
        # the [L, R] ltails' replica dimension) over 'replica'
        # (`parallel/mesh.py:place` handles MultiLogState). Exec/append
        # jits are unchanged — GSPMD propagates the placed inputs'
        # shardings and inserts the cross-column collectives (the
        # annotation tier; the ShardedCnrRunner proves the placement on
        # the fused step, this wires it into the stateful wrapper).
        self.mesh = None
        self._mesh_shards = 0
        self._mesh_rep_shards = 1
        if mesh is not None:
            from jax.sharding import Mesh

            from node_replication_tpu.parallel.mesh import (
                announce_placement,
                place,
            )

            if not isinstance(mesh, Mesh) or not {
                "replica", "log"
            } <= set(mesh.axis_names):
                # the placement spec trees name both axes — a partial
                # mesh would die inside NamedSharding with an opaque
                # resource-axis error instead of this
                raise ValueError(
                    f"MultiLogReplicated needs a ('replica', 'log') "
                    f"Mesh (parallel/mesh.py:make_mesh); got "
                    f"{mesh!r}"
                )
            shape = dict(mesh.shape)
            if n_replicas % shape["replica"]:
                raise ValueError(
                    f"R={n_replicas} replicas cannot shard over "
                    f"{shape['replica']} mesh rows"
                )
            if nlogs % shape["log"]:
                raise ValueError(
                    f"L={nlogs} logs cannot shard over "
                    f"{shape['log']} mesh columns"
                )
            self.mesh = mesh
            self._mesh_shards = int(np.prod(mesh.devices.shape))
            self._mesh_rep_shards = shape["replica"]
            announce_placement(mesh, n_replicas, "MultiLogReplicated",
                               "gspmd")
            self.ml, self.states = place(self.ml, self.states, mesh)

        # Combiner lock (`replica._locked`): one combiner pass at a
        # time across all logs; reentrant so watchdog gc_callbacks can
        # re-enter sync_log on the same thread.
        self._lock = make_rlock("MultiLogReplicated._lock")
        self._threads_per_replica = [0] * n_replicas
        # staged ops: (rid, tid) -> deque[(log, opcode, args)]
        self._pending: dict[tuple[int, int], deque] = {}
        # appended-but-unanswered: (rid, log) -> deque[(pos, tid)]
        self._inflight: dict[tuple[int, int], deque] = {}
        # delivered responses per thread, in enqueue order per log
        self._resps: dict[tuple[int, int], deque] = {}
        # split-round registry (`begin_mut_batch`): at most ONE
        # begun-but-unfinished batch per replica (the NodeReplicated
        # invariant, here spanning the batch's per-log sub-rounds)
        self._pending_batch: dict[int, "_PendingCnrBatch"] = {}
        # per-log observability: LogMapper routing counts, combiner
        # passes, replay rounds (+ idle skips) per log
        self._log_selected = [0] * nlogs
        self._combine_rounds = [0] * nlogs
        self._exec_rounds = 0
        self._idle_rounds = 0
        reg = get_registry()
        self._m_rounds = reg.counter("cnr.exec.rounds")
        self._m_idle = reg.counter("cnr.exec.idle_rounds")
        self._m_combine = reg.counter("cnr.combine.rounds")
        self._m_batch = reg.histogram("cnr.combine.batch_size",
                                      buckets=COUNT_BUCKETS)
        self._m_stalls = reg.counter("cnr.watchdog.stalls")

        # ---- fused pallas per-log combiner tier (the NodeReplicated
        # twin, `core/replica._FusedTier`): a per-log sub-batch whose
        # log is lock-step eligible appends+replays+answers as ONE
        # kernel launch. engine='pallas' forces it, 'auto' calibrates
        # on TPU (NR_TPU_FUSED_CAL=1 is the CPU-test hook), 'scan'
        # keeps the chain. CNR has no fencing, so the fenced kernel
        # variant never builds here.
        if engine not in ("auto", "scan", "pallas"):
            raise ValueError(f"unknown engine {engine!r}")
        self._fused_cnr_cache: dict = {}
        self._init_fused_tier(engine, dispatch, mesh, reg, "cnr")
        if self.mesh is not None:
            self._m_mesh_round = reg.counter("cnr.exec.mesh.gspmd")
            self._m_mesh_sync_bytes = reg.counter("mesh.sync_bytes")

        spec, d = self.spec, dispatch

        def exec_round(ml, states, log_idx: int, window: int):
            states, resps, lt = jax.vmap(
                lambda s, t: _exec_one_log(
                    spec, d, ml.opcodes[log_idx], ml.args[log_idx],
                    ml.tail[log_idx], s, t, window,
                )
            )(states, ml.ltails[log_idx])
            ml = ml._replace(
                ltails=ml.ltails.at[log_idx].set(lt),
                ctail=ml.ctail.at[log_idx].set(
                    jnp.maximum(ml.ctail[log_idx], jnp.max(lt))
                ),
                head=ml.head.at[log_idx].set(jnp.min(lt)),
            )
            return ml, states, resps

        self._exec_jit = jax.jit(
            exec_round, static_argnames=("log_idx", "window"),
            donate_argnums=(0, 1),
        )

        def append_one(ml, log_idx: int, opcodes, args, count):
            B = opcodes.shape[0]
            lanes = jnp.arange(B, dtype=jnp.int64)
            valid = lanes < count
            slot = jnp.where(
                valid, (ml.tail[log_idx] + lanes) & spec.mask, spec.capacity
            ).astype(jnp.int32)
            return ml._replace(
                opcodes=ml.opcodes.at[log_idx, slot].set(
                    opcodes, mode="drop"
                ),
                args=ml.args.at[log_idx, slot].set(args, mode="drop"),
                tail=ml.tail.at[log_idx].add(count),
            )

        self._append_jit = jax.jit(
            append_one, static_argnames=("log_idx",), donate_argnums=(0,)
        )

        def read_one(states, rid, opcode, args):
            state = jax.tree.map(lambda a: a[rid], states)
            return apply_read(d, state, opcode, args)

        self._read_jit = jax.jit(read_one)

    # ------------------------------------------------------------------ API

    @property
    def n_replicas(self) -> int:
        return self.spec.n_replicas

    @property
    def nlogs(self) -> int:
        return self.spec.nlogs

    def replica_device(self, rid: int):
        """First device of the mesh row hosting replica `rid`'s state
        shard (None when un-meshed) — the NodeReplicated twin the
        serve frontend's worker→device map consumes. A CNR replica's
        state lives on one 'replica' row but its per-log ring columns
        span that row, so the row's first device stands for the
        shard's home."""
        if self.mesh is None:
            return None
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        row = rid // (self.n_replicas // self._mesh_rep_shards)
        return self.mesh.devices[row].flat[0]

    @_locked
    def register(self, rid: int = 0) -> ReplicaToken:
        """Register a logical thread on replica `rid` — registration spans
        every log, as `cnr`'s replica registers with each
        (`cnr/src/replica.rs:209-281`)."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        tid = self._threads_per_replica[rid]
        if tid >= MAX_THREADS_PER_REPLICA:
            raise RuntimeError(f"replica {rid} thread limit reached")
        self._threads_per_replica[rid] = tid + 1
        self._pending[(rid, tid)] = deque()
        self._resps[(rid, tid)] = deque()
        return ReplicaToken(rid, tid)

    @_locked
    def _map(self, op: tuple) -> int:
        h = self.log_mapper(op[0], tuple(op[1:])) % self.nlogs
        self._log_selected[h] += 1
        return h

    @_locked
    def execute_mut(self, op: tuple, token: ReplicaToken):
        """Route the write to its log, combine that log, return its
        response (`cnr/src/replica.rs:430-445`)."""
        h = self.enqueue_mut(op, token)
        self.combine(token.rid, h)
        # The combine delivered this op's response last (it is the
        # thread's newest append on its log); pop it from the tail so
        # earlier enqueued-but-unread responses stay for `responses()`.
        q = self._resps[(token.rid, token.tid)]
        return q.pop() if q else None

    @_locked
    def enqueue_mut(self, op: tuple, token: ReplicaToken) -> int:
        """Stage a write without combining (explicit batch building, the
        NodeReplicated twin). Its response arrives via `responses()`
        after a later combine of its mapped log. Returns the mapped log
        index (the staging path `execute_mut` shares)."""
        h = self._map(op)
        self._pending[(token.rid, token.tid)].append(
            (h, op[0], tuple(op[1:]))
        )
        return h

    @_locked
    def flush(self, rid: int | None = None) -> None:
        """Combine every log with staged ops (all replicas by default)."""
        for r in range(self.n_replicas) if rid is None else [rid]:
            logs = {
                h
                for tid in range(self._threads_per_replica[r])
                for (h, _, _) in self._pending[(r, tid)]
            }
            for h in sorted(logs):
                self.combine(r, h)

    @_locked
    def responses(self, token: ReplicaToken) -> list:
        """Drain delivered responses for this thread (enqueue order per
        log; delivery order across logs follows combine order)."""
        q = self._resps[(token.rid, token.tid)]
        out = list(q)
        q.clear()
        return out

    @_locked
    def execute(self, op: tuple, token: ReplicaToken):
        """Read path: sync only the mapped log, then dispatch locally
        (`cnr/src/replica.rs:599-617`)."""
        h = self._map(op)
        rid = token.rid
        fault_hook("read-sync", rid, self)
        ctail = int(np.asarray(self.ml.ctail)[h])
        rounds = 0
        while int(np.asarray(self.ml.ltails)[h, rid]) < ctail:
            self._exec_round(h)
            rounds = self._watchdog(rounds, h, "read-sync")
        args = np.zeros((self.spec.arg_width,), np.int32)
        args[: len(op) - 1] = op[1:]
        return int(
            self._read_jit(
                self.states, jnp.int32(rid), jnp.int32(op[0]),
                jnp.asarray(args),
            )
        )

    @_locked
    def combine(self, rid: int, log_idx: int) -> None:
        """Drain replica `rid`'s staged ops for `log_idx` (thread order),
        append them to that log, and replay it until `rid` has applied its
        own ops — one log's combiner pass (`cnr/src/replica.rs:673-720`)."""
        ops: list[tuple] = []  # (opcode, *args)
        tids: list[int] = []
        for tid in range(self._threads_per_replica[rid]):
            q = self._pending[(rid, tid)]
            keep = deque()
            while q:
                h, opcode, args = q.popleft()
                if h == log_idx:
                    ops.append((opcode, *args))
                    tids.append(tid)
                else:
                    keep.append((h, opcode, args))
            q.extend(keep)
        if not ops:
            self._exec_round(log_idx)
            return
        self._append_and_replay_log(log_idx, rid, ops, tids)

    def _fused_log_spec(self) -> LogSpec:
        """The single-log `LogSpec` the fused engine is built against —
        every CNR log shares it (same capacity/slack), so ONE engine
        serves all per-log rounds."""
        return LogSpec(
            capacity=self.spec.capacity,
            n_replicas=self.spec.n_replicas,
            arg_width=self.spec.arg_width,
            gc_slack=self.spec.gc_slack,
        )

    @_locked
    def _fused_cnr_round(self, eng, window: int):
        """Per-window fused round over ONE mapped log: view the log's
        column of the stacked `MultiLogState` as a `LogState`, run the
        engine's model-layout round, write the column back. `log_idx`
        is a traced operand so one program serves every log."""
        fn = self._fused_cnr_cache.get(window)
        if fn is None:
            inner = eng.round_fn(window, fenced=False)

            def cnr_round(ml, states, log_idx, opcodes, args, count):
                log = LogState(
                    opcodes=ml.opcodes[log_idx],
                    args=ml.args[log_idx],
                    head=ml.head[log_idx],
                    tail=ml.tail[log_idx],
                    ctail=ml.ctail[log_idx],
                    ltails=ml.ltails[log_idx],
                )
                log, states, resps = inner(
                    log, states, opcodes, args, count
                )
                ml = ml._replace(
                    opcodes=ml.opcodes.at[log_idx].set(log.opcodes),
                    args=ml.args.at[log_idx].set(log.args),
                    head=ml.head.at[log_idx].set(log.head),
                    tail=ml.tail.at[log_idx].set(log.tail),
                    ctail=ml.ctail.at[log_idx].set(log.ctail),
                    ltails=ml.ltails.at[log_idx].set(log.ltails),
                )
                return ml, states, resps

            # interpret mode runs eagerly (jit+interpret+x64 trips the
            # MLIR dtype mismatch — see FusedHashmapEngine.round)
            fn = (
                cnr_round if eng.interpret
                else jax.jit(cnr_round, donate_argnums=(0, 1))
            )
            self._fused_cnr_cache[window] = fn
        return fn

    @_locked
    def _try_fused_round_log(self, log_idx: int, rid: int, ops, tids,
                             n: int, pos0: int, pad: int,
                             opcodes, args, pending=None) -> bool:
        """Route one per-log combiner pass through the fused engine
        when the log is lock-step eligible (the NodeReplicated
        `_try_fused_round` twin, minus fencing/WAL, which CNR does not
        carry). With `pending` (the split-round path) the kernel is
        launched here and the response readback deferred to
        `_finish_round_log`."""
        eng = self._fused_tier_wanted(pad)
        if eng is None:
            return False
        if not eng.supports(pad):
            self._m_fused_fallback.inc()
            return False
        if any(self._inflight.get((r, log_idx))
               for r in range(self.n_replicas)):
            self._m_fused_fallback.inc()
            return False
        cur = np.asarray(
            jnp.concatenate(
                [self.ml.ltails[log_idx], self.ml.tail[log_idx][None]]
            )
        ).copy()
        lts, tail = cur[:-1], int(cur[-1])
        if not (int(lts.min()) == tail == int(lts.max())):
            self._m_fused_fallback.inc()
            return False
        timing = self._fused_calibrating()
        t0 = time.perf_counter()
        fn = self._fused_cnr_round(eng, pad)
        extra = {"deferred": True} if pending is not None else {}
        with span("fused-round", log=log_idx, rid=rid, n=n, pos0=pos0,
                  window=pad, **extra) as sp:
            self.ml, self.states, resps = fn(
                self.ml, self.states, jnp.int32(log_idx), opcodes,
                args, n,
            )
            if pending is None:
                resps_np = np.asarray(resps)
                sp.fence(self.ml, self.states)
        dt = time.perf_counter() - t0
        if timing:
            self._note_fused_sample("pallas_fused", pad, dt)
        # the CNR path embeds the raw round_fn in its own program, so
        # the engine's round() wrapper never runs — report through the
        # same instrumentation hook (tier counter + kernel.* metrics +
        # kernel-launch event; one contract, never two)
        eng.note_round(pad, n, dt)
        self._fused_rounds += 1
        self._m_engine_fused.inc()
        if pending is not None:
            pending.fused_resps = resps
            return True
        for j, tid in enumerate(tids):
            self._resps[(rid, tid)].append(int(resps_np[rid, j]))
        self.last_round_tier = "pallas_fused"
        self._tier_by_rid[rid] = "pallas_fused"
        self._pos_by_rid[rid] = pos0
        return True

    @_locked
    def _begin_round_log(self, log_idx: int, rid: int,
                         ops: list[tuple], tids: list[int],
                         batch: bool = False,
                         defer: bool = False) -> _PendingRound:
        """First half of the per-log combiner pass (the NodeReplicated
        `_begin_round` twin): wait for ring space on this log, encode
        + append, record each op's in-flight response destination.
        `defer=True` leaves the replay-to-target (or the fused
        launch's readback) for `_finish_round_log`; calibration rounds
        ignore `defer` (honest tier timing needs the round
        back-to-back). The lock is reentrant: callers already hold
        it."""
        fault_hook("append", rid, self)
        n = len(ops)
        self._combine_rounds[log_idx] += 1
        self._m_combine.inc()
        self._m_batch.observe(n)
        rounds = 0
        while (
            self.spec.capacity - self.spec.gc_slack
            - int(np.asarray(self.ml.tail - self.ml.head)[log_idx])
        ) < n:
            self._exec_round(log_idx)
            rounds = self._watchdog(rounds, log_idx, "append-gc")
        pos0 = int(np.asarray(self.ml.tail)[log_idx])
        pad = 1 << (max(n, 1) - 1).bit_length()
        opcodes, args, _ = encode_ops(
            ops, self.spec.arg_width, pad_to=pad
        )
        timing = self._fused_calibrating()
        defer = defer and not timing
        pending = _PendingRound(rid, list(tids), n, pos0, batch=batch,
                                log_idx=log_idx)
        pending.pad = pad
        if self._try_fused_round_log(log_idx, rid, ops, tids, n, pos0,
                                     pad, opcodes, args,
                                     pending if defer else None):
            if pending.fused_resps is None:
                pending.done = True  # ran eagerly end-to-end
            return pending
        if timing:
            pending.t_chain = time.perf_counter()
        extra = {"batch": True} if batch else {}
        with span("append", log=log_idx, rid=rid, n=n, pos0=pos0,
                  **extra) as sp:
            self.ml = self._append_jit(
                self.ml, log_idx, opcodes, args, jnp.int64(n)
            )
            sp.fence(self.ml)
        infl = self._inflight.setdefault((rid, log_idx), deque())
        for j, tid in enumerate(tids):
            infl.append((pos0 + j, tid))
        return pending

    @_locked
    def _finish_round_log(self, pending: _PendingRound) -> None:
        """Second half of the per-log combiner pass: replay this log
        until replica `rid` has applied its own ops, or read back and
        deliver the fused launch's responses."""
        if pending.done:
            return
        pending.done = True
        rid, log_idx = pending.rid, pending.log_idx
        if pending.fused_resps is not None:
            resps_np = np.asarray(pending.fused_resps)
            pending.fused_resps = None
            for j, tid in enumerate(pending.tids):
                self._resps[(rid, tid)].append(int(resps_np[rid, j]))
            self.last_round_tier = "pallas_fused"
            self._tier_by_rid[rid] = "pallas_fused"
            self._pos_by_rid[rid] = pending.pos0
            return
        target = pending.target
        rounds = 0
        with span("combine-replay", log=log_idx, rid=rid,
                  target=target) as sp:
            while int(np.asarray(self.ml.ltails)[log_idx, rid]) < target:
                self._exec_round(log_idx)
                rounds = self._watchdog(rounds, log_idx, "combine-replay")
            sp.fence(self.ml, self.states)
        self.last_round_tier = "scan"
        self._tier_by_rid[rid] = "scan"
        self._pos_by_rid[rid] = pending.pos0
        if pending.t_chain is not None:
            self._note_fused_sample("chain", pending.pad,
                                    time.perf_counter()
                                    - pending.t_chain)

    @_locked
    def _append_and_replay_log(self, log_idx: int, rid: int,
                               ops: list[tuple], tids: list[int],
                               batch: bool = False) -> None:
        """Shared per-log combiner-pass tail (`combine` and
        `execute_mut_batch`'s sub-batches — one protocol, never two):
        `_begin_round_log` + `_finish_round_log` back-to-back; the
        split-round path (`begin_mut_batch`) runs the same halves
        spread across the serve pipeline's stages. Lock-step-eligible
        passes route through the fused pallas tier when selected
        (`_try_fused_round_log`) — one kernel launch per sub-batch."""
        self._finish_round_log(
            self._begin_round_log(log_idx, rid, ops, tids, batch=batch)
        )

    @_locked
    def _drop_batch_inflight(self, rid: int) -> None:
        """Failed-batch hygiene (the NodeReplicated twin): drop every
        pending BATCH_TID delivery for this replica on every log and
        clear the sink, so the next batch cannot inherit stale replies
        (and a short sink cannot wedge every later batch on this
        replica)."""
        for key in [(rid, h) for h in range(self.nlogs)
                    if (rid, h) in self._inflight]:
            self._inflight[key] = deque(
                (p, t) for p, t in self._inflight[key]
                if t != BATCH_TID
            )
        sink = self._resps.get((rid, BATCH_TID))
        if sink is not None:
            sink.clear()

    @_locked
    def begin_mut_batch(self, ops: list[tuple],
                        rid: int = 0) -> "_PendingCnrBatch":
        """Split-round batch entry, first half (the
        `NodeReplicated.begin_mut_batch` twin): route each op through
        the `LogMapper`, then append + journal every per-log sub-batch
        in log order, deferring each log's replay-to-target to
        `finish_mut_batch`. At most ONE begun-but-unfinished batch per
        replica (`RuntimeError` otherwise). NOT atomic across logs —
        the same per-log contract as `execute_mut_batch`."""
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        if self._pending_batch.get(rid) is not None:
            raise RuntimeError(
                f"replica {rid} already has a batch in flight; "
                f"finish_mut_batch it before beginning another "
                f"(at most one split round per replica)"
            )
        n = len(ops)
        sink = self._resps.get((rid, BATCH_TID))
        if sink is None:
            sink = deque()
            self._resps[(rid, BATCH_TID)] = sink
        groups: dict[int, list[int]] = {}
        for i, op in enumerate(ops):
            groups.setdefault(self._map(op), []).append(i)
        max_batch = self.spec.capacity - self.spec.gc_slack
        for h, idxs in groups.items():
            if len(idxs) > max_batch:
                raise LogTooSmallError(
                    f"log {h}: sub-batch of {len(idxs)} exceeds "
                    f"appendable capacity {max_batch}"
                )
        pend = _PendingCnrBatch(rid, n)
        try:
            for h in sorted(groups):
                idxs = groups[h]
                sub = self._begin_round_log(
                    h, rid, [ops[i] for i in idxs],
                    [BATCH_TID] * len(idxs), batch=True, defer=True,
                )
                pend.subs.append((h, idxs, sub))
        except BaseException:
            self._drop_batch_inflight(rid)
            raise
        self._pending_batch[rid] = pend
        return pend

    @_locked
    def finish_mut_batch(self, pend: "_PendingCnrBatch") -> list:
        """Split-round batch entry, second half: replay every per-log
        sub-round to its target (in the same log order `begin`
        appended), scatter responses back to submission indices,
        release the replica's in-flight slot."""
        rid = pend.rid
        if self._pending_batch.get(rid) is not pend:
            raise RuntimeError(
                f"pending batch for replica {rid} is not this "
                f"replica's in-flight batch (already finished?)"
            )
        sink = self._resps[(rid, BATCH_TID)]
        out: list = [None] * pend.n
        try:
            for h, idxs, sub in pend.subs:
                self._finish_round_log(sub)
                assert len(sink) == len(idxs), (len(sink), len(idxs))
                for i in idxs:
                    out[i] = sink.popleft()
            return out
        except BaseException:
            self._drop_batch_inflight(rid)
            raise
        finally:
            self._pending_batch.pop(rid, None)

    @_locked
    def abort_mut_batch(self, pend: "_PendingCnrBatch") -> None:
        """Abandon a begun-but-unfinished split batch (the
        `NodeReplicated.abort_mut_batch` twin): every appended sub-
        batch WILL replay; only response delivery drops. Idempotent."""
        rid = pend.rid
        if self._pending_batch.get(rid) is not pend:
            return
        self._pending_batch.pop(rid, None)
        for _, _, sub in pend.subs:
            sub.done = True
            sub.fused_resps = None
        self._drop_batch_inflight(rid)

    @_locked
    def execute_mut_batch(self, ops: list[tuple],
                          rid: int = 0) -> list:
        """Execute a caller-assembled batch as one combiner pass PER
        MAPPED LOG and return responses in submission order — the CNR
        twin of `NodeReplicated.execute_mut_batch` (the serve
        frontend's serial entry point).

        Each op routes through the `LogMapper` exactly as `execute_mut`
        would (`cnr/src/replica.rs:435`); the batch then splits into
        per-log sub-batches that append and replay one log at a time,
        in log order (each pass is `_begin_round_log` +
        `_finish_round_log` back-to-back, the same halves the
        split-round path runs). A failure during log `h`'s pass
        therefore leaves later logs' sub-batches UNappended — the
        historical serial contract — whereas the split path
        (`begin_mut_batch`) appends every sub-batch up front so the
        whole batch shares one post-append failure class. Responses
        come back through a dedicated deque sink keyed
        `(rid, BATCH_TID)` and are scattered back to the callers'
        submission indices, so interleaving with per-thread
        `execute_mut` traffic on the same replica stays ordered.
        """
        if not 0 <= rid < self.n_replicas:
            raise ValueError(f"replica {rid} out of range")
        if self._pending_batch.get(rid) is not None:
            # the NodeReplicated guard (there via begin_mut_batch): a
            # serial batch interleaved with a begun split batch would
            # deliver the split batch's appended entries into the
            # shared BATCH_TID sink and scatter wrong responses
            raise RuntimeError(
                f"replica {rid} already has a batch in flight; "
                f"finish_mut_batch it before executing another"
            )
        n = len(ops)
        if n == 0:
            return []
        sink = self._resps.get((rid, BATCH_TID))
        if sink is None:
            sink = deque()
            self._resps[(rid, BATCH_TID)] = sink
        groups: dict[int, list[int]] = {}
        for i, op in enumerate(ops):
            groups.setdefault(self._map(op), []).append(i)
        max_batch = self.spec.capacity - self.spec.gc_slack
        for h, idxs in groups.items():
            if len(idxs) > max_batch:
                raise LogTooSmallError(
                    f"log {h}: sub-batch of {len(idxs)} exceeds "
                    f"appendable capacity {max_batch}"
                )
        out: list = [None] * n
        try:
            for h in sorted(groups):
                idxs = groups[h]
                m = len(idxs)
                self._append_and_replay_log(
                    h, rid, [ops[i] for i in idxs],
                    [BATCH_TID] * m, batch=True,
                )
                assert len(sink) == m, (len(sink), m)
                for i in idxs:
                    out[i] = sink.popleft()
            return out
        except BaseException:
            self._drop_batch_inflight(rid)
            raise

    @_locked
    def sync(self, rid: int | None = None) -> None:
        """Catch up on every log (`cnr/src/replica.rs:579-597`)."""
        for l in range(self.nlogs):
            self.sync_log(rid, l)

    @_locked
    def sync_log(self, rid: int | None, log_idx: int) -> None:
        """Targeted single-log sync (`sync_log`,
        `cnr/src/replica.rs:579-597`). The harness wires the GC callback
        to this, answering starvation reports (`benches/mkbench.rs:
        763-772`)."""
        rounds = 0
        while True:
            lt = np.asarray(self.ml.ltails)[log_idx]
            tail = int(np.asarray(self.ml.tail)[log_idx])
            done = (
                all(int(x) >= tail for x in lt)
                if rid is None
                else int(lt[rid]) >= tail
            )
            if done:
                return
            self._exec_round(log_idx)
            rounds = self._watchdog(rounds, log_idx, "sync")

    @_locked
    def verify(self, fn: Callable[[Any], Any], rid: int = 0):
        self.sync()
        state = jax.tree.map(lambda a: np.asarray(a[rid]), self.states)
        return fn(state)

    @_locked
    def replicas_equal(self) -> bool:
        return states_equal(self.states)

    @_locked
    def stats(self) -> dict:
        """Flat per-log counters (original three keys stable);
        `snapshot()` is the structured superset."""
        return {
            "tails": [int(t) for t in np.asarray(self.ml.tail)],
            "ctails": [int(t) for t in np.asarray(self.ml.ctail)],
            "heads": [int(t) for t in np.asarray(self.ml.head)],
            "log_selected": list(self._log_selected),
            "combine_rounds": list(self._combine_rounds),
            "exec_rounds": self._exec_rounds,
            "idle_rounds": self._idle_rounds,
            "fused_rounds": self._fused_rounds,
            "fused_tier": self._fused_tier_state(),
        }

    @_locked
    def snapshot(self) -> dict:
        """Structured observability snapshot (JSON-safe), the CNR twin of
        `NodeReplicated.snapshot()`: per-log cursors and per-(log,
        replica) lag, LogMapper routing counts (skew at a glance),
        combiner passes and replay rounds per log, plus the process-wide
        metrics view when enabled."""
        tails = np.asarray(self.ml.tail)
        heads = np.asarray(self.ml.head)
        ctails = np.asarray(self.ml.ctail)
        ltails = np.asarray(self.ml.ltails)
        logs = []
        for l in range(self.nlogs):
            lag = [int(tails[l] - lt) for lt in ltails[l]]
            logs.append({
                "tail": int(tails[l]),
                "head": int(heads[l]),
                "ctail": int(ctails[l]),
                "lag": lag,
                "max_lag": max(lag) if lag else 0,
                "selected": self._log_selected[l],
                "combine_rounds": self._combine_rounds[l],
                "occupancy": (int(tails[l]) - int(heads[l]))
                / self.spec.capacity,
            })
        total_sel = sum(self._log_selected)
        return {
            "nlogs": self.nlogs,
            "capacity": self.spec.capacity,
            "logs": logs,
            # routing imbalance: max over mean selections (1.0 = even)
            "selection_imbalance": (
                max(self._log_selected) * self.nlogs / total_sel
                if total_sel else 0.0
            ),
            "replicas": {
                "n": self.n_replicas,
                "threads": list(self._threads_per_replica),
            },
            "exec": {
                "window": self.exec_window,
                "rounds": self._exec_rounds,
                "idle_rounds": self._idle_rounds,
                "fused_rounds": self._fused_rounds,
                "fused_tier": self._fused_tier_state(),
            },
            "mesh": (
                None if self.mesh is None else {
                    "devices": self._mesh_shards,
                    "tier": "gspmd",
                    "shape": dict(self.mesh.shape),
                }
            ),
            "metrics": get_registry().snapshot(),
        }

    # ------------------------------------------------------------ internals

    @_locked
    def _exec_round(self, log_idx: int) -> None:
        fault_hook("replay", -1, self)
        # one fused cursor readback per round (see the
        # NodeReplicated._exec_round note on tunnel D2H RTTs)
        cur = np.asarray(
            jnp.concatenate(
                [self.ml.ltails[log_idx], self.ml.tail[log_idx][None]]
            )
        ).copy()
        lt_before, tail = cur[:-1], int(cur[-1])
        # idle short-circuit (the NodeReplicated._exec_round twin): all
        # replicas at this log's tail → nothing to replay, skip the
        # device round; every caller loops on a cursor condition already
        # satisfied, so skipping cannot livelock
        if int(lt_before.min()) >= tail and int(lt_before.max()) <= tail:
            self._idle_rounds += 1
            self._m_idle.inc()
            return
        self._exec_rounds += 1
        self._m_rounds.inc()
        self.ml, self.states, resps = self._exec_jit(
            self.ml, self.states, log_idx=log_idx, window=self.exec_window
        )
        lt_after = np.asarray(self.ml.ltails)[log_idx]
        resps_np = np.asarray(resps)
        if self.mesh is not None:
            self._m_mesh_round.inc()
            self._m_mesh_sync_bytes.inc(resps_np.nbytes + cur.nbytes
                                        + lt_after.nbytes)
        for r in range(self.n_replicas):
            q = self._inflight.get((r, log_idx))
            if not q:
                continue
            while q and q[0][0] < int(lt_after[r]):
                pos, tid = q.popleft()
                self._resps[(r, tid)].append(
                    int(resps_np[r, pos - int(lt_before[r])])
                )

    def _watchdog(self, rounds: int, log_idx: int, where: str) -> int:
        rounds += 1
        # Re-warn every WARN_ROUNDS forever, like the reference's per-log
        # GC starvation callback (`cnr/src/log.rs:505-515`).
        if rounds % WARN_ROUNDS == 0:
            self._m_stalls.inc()
            lt = np.asarray(self.ml.ltails)[log_idx]
            dormant = int(np.argmin(lt))
            tail = int(np.asarray(self.ml.tail)[log_idx])
            logger.warning(
                "cnr replay stalled in %s on log %d after %d rounds; "
                "dormant replica=%d (ltail=%d, tail=%d)",
                where, log_idx, rounds, dormant, int(lt[dormant]), tail,
            )
            get_tracer().emit(
                "watchdog", where=where, log=log_idx, rounds=rounds,
                dormant=dormant, ltail=int(lt[dormant]), tail=tail,
            )
            if self.gc_callback is not None:
                self.gc_callback(log_idx, dormant)
        return rounds
