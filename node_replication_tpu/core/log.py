"""The shared operation log as a device-resident ring buffer.

TPU-native re-design of the reference's lock-free MPMC ring
(`nr/src/log.rs`). The mapping (SURVEY.md §2.6, §7):

- `Entry<T>` cells with `alivef` liveness bits (`nr/src/log.rs:51-65`) become
  a struct-of-arrays ring `(opcodes: int32[L], args: int32[L, A])`. Liveness
  parity (`lmasks`) disappears entirely: within a lock-step append→replay
  step, append happens-before replay by data dependence, so an entry is live
  iff its logical position is `< tail`.
- The CAS tail-reservation loop (`nr/src/log.rs:391-399`) becomes a batched
  reserve-then-write: the caller presents a fixed-shape batch plus a valid
  count; slots `[tail, tail+count)` are filled with one masked scatter and
  `tail` advances once. Cross-replica batches are concatenated by the step
  builder (`core/step.py`) with prefix-sum offsets — the whole-fleet append
  is one scatter, no contention point at all.
- `exec` (`nr/src/log.rs:473-524`) becomes `log_exec_all`: a `lax.scan` over
  a static replay window, vmapped over replicas, each starting from its own
  `ltails[r]` with per-position `pos < tail` masking (per-replica divergent
  progress, SURVEY.md §7 "hard parts").
- `advance_head` GC (`nr/src/log.rs:536-580`) is the reduction
  `head = min(ltails)`, folded into `log_exec_all`. "Help replay before
  appending when full" (`nr/src/log.rs:364-387`) becomes the host-side rule:
  if `log_space` cannot fit the batch, run replay windows first
  (`core/replica.py`).
- `ctail` (completed tail, `nr/src/log.rs:520-523` fetch_max) is
  `max(ctail, max(new ltails))`.

Logical positions (`head`/`tail`/`ctail`/`ltails`) are monotonically
increasing int64 scalars; the physical slot is `pos & (L-1)` with L a power
of two (`nr/src/log.rs:194-196`, `527-530`).

Mesh placement: every function here is sharding-agnostic — under the
canonical mesh placement (`parallel/mesh.py:place`: ring arrays and
scalar cursors replicated, `ltails` and the replica axis of `states`
sharded over 'replica') the same programs run across a TPU mesh with
GSPMD inserting the collectives, and `parallel/collectives.py:
make_shmap_exec` is the explicit-collective twin of `log_exec_all`
(same lattice bookkeeping as `pmax`/`pmin` over ICI). The sharded and
unsharded programs are differentially pinned bit-identical in
tests/test_mesh_fleet.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from node_replication_tpu.obs.metrics import get_registry
from node_replication_tpu.ops.encoding import Dispatch, NOOP, apply_write
from node_replication_tpu.utils.checks import check

PyTree = Any

# Engine-dispatch counters: which replay tier `log_catchup_all` routed a
# call to (scan / per-replica window_apply / union-window plan; the
# pallas tier is counted at its construction site, ops/pallas_replay via
# bench.py). These increment on the HOST side of the tier decision, so
# under jit they count per trace/compile; eager callers (and the
# recovery loop's first call, re-trace after fleet growth, …) count per
# invocation. Per-round engine usage of the stateful wrappers is the
# separate `nr.exec.engine.*` / `cnr.exec.rounds` family.
_m_engine_scan = get_registry().counter("log.engine.scan")
_m_engine_window = get_registry().counter("log.engine.window_apply")
_m_engine_union = get_registry().counter("log.engine.union_plan")
_m_idle_skips = get_registry().counter("log.engine.idle_skip")
# mesh tier: shard_map exec programs built by parallel/collectives.py
# (make_shmap_exec — counted per build, like the per-trace counters
# above; per-ROUND mesh usage is the wrapper's nr.exec.mesh.* family)
_m_engine_shmap = get_registry().counter("log.engine.shmap")
# fused pallas tier: whole combiner rounds (append + replay + response
# gather) executed as one kernel launch (`ops/pallas_replay.py:
# FusedHashmapEngine`, routed by `core/replica._try_fused_round` /
# the CNR twin). Counted per ROUND on the host side of the jit
# boundary — fused rounds are host-invoked, so unlike the per-trace
# counters above this one is an exact round count.
_m_engine_pallas_fused = get_registry().counter("log.engine.pallas_fused")
# mesh-fused tier: the same one-launch fused round embedded in a
# shard_map program over the replica mesh
# (`parallel/collectives.py:MeshFusedEngine`) — exact per-round host
# count, like the pallas_fused counter above.
_m_engine_mesh_fused = get_registry().counter("log.engine.mesh_fused")

# Default number of log entries. The reference defaults to 32 MiB of 64-byte
# entries = 2^19 slots "based on the ASPLOS 2017 paper" (`nr/src/log.rs:19-22`);
# device HBM is more precious than DRAM, and a 2^16-entry ring already covers
# the largest single-step replay window we schedule.
DEFAULT_LOG_ENTRIES = 1 << 16

# GC slack: an appender must leave this many slots between tail and head so
# laggards can catch up before slots are overwritten. The reference uses
# MAX_PENDING_OPS * MAX_THREADS_PER_REPLICA = 8192 (`nr/src/log.rs:36`).
GC_FROM_HEAD = 8192

# Spin-diagnostic threshold analog: after this many fruitless host-side
# replay rounds the watchdog warns (`nr/src/log.rs:43` WARN_THRESHOLD).
WARN_ROUNDS = 64


@dataclasses.dataclass(frozen=True)
class LogSpec:
    """Static log configuration (hashable: used as a jit static argument).

    `capacity` is rounded up to a power of two with a floor of
    `2 * gc_slack`, mirroring `Log::new` (`nr/src/log.rs:184-196`).
    """

    capacity: int = DEFAULT_LOG_ENTRIES
    n_replicas: int = 1
    arg_width: int = 3
    gc_slack: int = GC_FROM_HEAD

    def __post_init__(self):
        cap = max(int(self.capacity), 2 * self.gc_slack)
        cap = 1 << (cap - 1).bit_length()  # next power of two
        object.__setattr__(self, "capacity", cap)
        if self.n_replicas < 1:
            raise ValueError("need at least one replica")

    @property
    def mask(self) -> int:
        return self.capacity - 1


class LogState(NamedTuple):
    """Device-resident log: ring arrays + monotone int64 cursors."""

    opcodes: jax.Array  # int32[L]
    args: jax.Array  # int32[L, A]
    head: jax.Array  # int64 scalar
    tail: jax.Array  # int64 scalar
    ctail: jax.Array  # int64 scalar (completed tail)
    ltails: jax.Array  # int64[R] (per-replica local tails)


def log_init(spec: LogSpec) -> LogState:
    """Allocate an empty log (`Log::new`, `nr/src/log.rs:179-241`)."""
    return LogState(
        opcodes=jnp.full((spec.capacity,), NOOP, jnp.int32),
        args=jnp.zeros((spec.capacity, spec.arg_width), jnp.int32),
        head=jnp.zeros((), jnp.int64),
        tail=jnp.zeros((), jnp.int64),
        ctail=jnp.zeros((), jnp.int64),
        ltails=jnp.zeros((spec.n_replicas,), jnp.int64),
    )


def log_reset(spec: LogSpec, log: LogState) -> LogState:
    """Zero the log for bench reuse (`Log::reset`, `nr/src/log.rs:593-611`)."""
    del log
    return log_init(spec)


def log_space(spec: LogSpec, log: LogState) -> jax.Array:
    """Free slots an append may consume while preserving the GC slack
    (`nr/src/log.rs:364-387`)."""
    used = log.tail - log.head
    return jnp.maximum(spec.capacity - spec.gc_slack - used, 0)


def log_append(
    spec: LogSpec,
    log: LogState,
    opcodes: jax.Array,
    args: jax.Array,
    count: jax.Array | int,
) -> LogState:
    """Batched reserve-then-write of `count` valid slots from a fixed-shape
    batch (`Log::append`, `nr/src/log.rs:343-427`, minus the CAS loop).

    Capacity is NOT checked here (jit-hot path); callers go through
    `log_space` / the replica layer's help-first rule, exactly as reference
    appenders must help GC before appending.
    """
    batch = opcodes.shape[0]
    count = jnp.asarray(count, jnp.int64)
    # Debug invariant (the panic the reference compiles in at
    # `nr/src/log.rs:487-489`'s append-side dual): an append that runs
    # past `head + capacity` overwrites entries some replica has not yet
    # replayed — silent data loss in release, an error under
    # NR_TPU_DEBUG (utils/checks.py).
    check(
        log.tail + count <= log.head + spec.capacity,
        "log_append overwrites unconsumed entries: tail {t} + count {c} "
        "> head {h} + capacity " + str(spec.capacity),
        t=log.tail, c=count, h=log.head,
    )
    lanes = jnp.arange(batch, dtype=jnp.int64)
    valid = lanes < count
    # Invalid lanes scatter to index L, which mode="drop" discards: the
    # fixed-shape equivalent of only publishing `count` entries.
    slot = jnp.where(
        valid, (log.tail + lanes) & spec.mask, spec.capacity
    ).astype(jnp.int32)
    return log._replace(
        opcodes=log.opcodes.at[slot].set(opcodes, mode="drop"),
        args=log.args.at[slot].set(args, mode="drop"),
        tail=log.tail + count,
    )


# Far-future sentinel for fenced-cursor masking: past any reachable
# logical position (int64 cursors; 2^60 leaves headroom for cursor
# arithmetic without overflow), so masked mins ignore fenced replicas.
_FAR = 1 << 60


def _freeze_limits(log: LogState, limits, fenced):
    """Fold a fenced mask into the per-replica replay `limits`: a fenced
    replica is frozen at its own ltail (no replay progress — its state
    may be corrupt and its cursor must hold still for repair), others
    keep their caller limit (or no limit)."""
    fenced = jnp.asarray(fenced, bool)
    frozen = jnp.where(fenced, log.ltails, jnp.int64(_FAR))
    if limits is None:
        return frozen
    return jnp.minimum(jnp.asarray(limits, jnp.int64), frozen)


def _gc_head(log: LogState, new_ltails, fenced):
    """The GC reduction `head = min(ltails)` with quarantined replicas
    fenced OUT of the min (`fault/health.py`): one dead replica's
    frozen cursor must not stall log GC for the fleet. Monotone
    (clamped at the old head) so a later unfence — repair re-seats the
    cursor at a healthy donor's ltail, which is >= head — can never
    move head backwards. `fenced=None` is the exact pre-fault
    reduction, bit-for-bit."""
    if fenced is None:
        return jnp.min(new_ltails)
    fenced = jnp.asarray(fenced, bool)
    masked = jnp.where(fenced, jnp.int64(_FAR), new_ltails)
    # all-fenced degenerate fleet: hold head still rather than min(FAR)
    return jnp.where(
        jnp.all(fenced), log.head,
        jnp.maximum(log.head, jnp.min(masked)),
    )


def gather_window(spec, opcodes_ring, args_ring, start, tail, window: int):
    """Gather `window` ring entries from logical position `start`, masking
    positions at or past `tail` to NOOP (positional liveness — the shared
    read side of every combined-replay engine; keep the masking rule in
    ONE place so the engines cannot desynchronize)."""
    lanes = jnp.arange(window, dtype=jnp.int64)
    pos = start + lanes
    idx = (pos & spec.mask).astype(jnp.int32)
    opcodes = jnp.where(pos < tail, opcodes_ring[idx], NOOP)
    return opcodes, args_ring[idx]


def _exec_one(
    spec: LogSpec,
    d: Dispatch,
    log: LogState,
    state: PyTree,
    ltail: jax.Array,
    window: int,
    limit: jax.Array | None = None,
):
    """Replay up to `window` entries of `[ltail, tail)` into one replica.

    The reference's hot replay loop (`nr/src/log.rs:473-524`): per entry,
    spin on `alivef` then `dispatch_mut`. Here the spin is gone (liveness is
    `pos < tail`) and the loop is a `lax.scan` whose body is one masked
    `apply_write`.

    `limit` (optional) caps how far this replica replays: the effective
    tail is `min(tail, limit)`. A limited replica is a *dormant* one — it
    stops consuming the log early, its `ltail` lags, and GC (`head =
    min(ltails)`) stalls on it exactly as a slow reference replica stalls
    `advance_head` (`nr/src/log.rs:536-539`).
    """
    eff_tail = log.tail if limit is None else jnp.minimum(log.tail, limit)
    # Debug invariants (`nr/src/log.rs:487-489` panics on a local tail
    # past the global tail; replaying below `head` reads slots GC may
    # have handed to appenders — both silently clamp in release):
    check(ltail <= log.tail,
          "replica ltail {lt} ahead of log tail {t}",
          lt=ltail, t=log.tail)
    check(ltail >= log.head,
          "replay window starts at {lt}, behind GC head {h}: entries "
          "already overwritten",
          lt=ltail, h=log.head)

    def body(state, j):
        pos = ltail + j
        active = pos < eff_tail
        idx = (pos & spec.mask).astype(jnp.int32)
        opcode = jnp.where(active, log.opcodes[idx], NOOP)
        state, resp = apply_write(d, state, opcode, log.args[idx])
        return state, resp

    state, resps = lax.scan(body, state, jnp.arange(window, dtype=jnp.int64))
    new_ltail = jnp.minimum(ltail + window, eff_tail)
    new_ltail = jnp.maximum(new_ltail, ltail)  # limit below ltail: no-op
    return state, resps, new_ltail


def log_exec_all(
    spec: LogSpec,
    d: Dispatch,
    log: LogState,
    states: PyTree,
    window: int,
    limits: jax.Array | None = None,
    fenced: jax.Array | None = None,
):
    """Replay a static `window` of pending entries into every replica in
    lock-step (vmapped `_exec_one`), then fold in progress bookkeeping:

    - `ltails[r] = min(ltails[r] + window, tail)`,
    - `ctail = max(ctail, max(ltails))`   (fetch_max, `nr/src/log.rs:520-523`),
    - `head  = min(ltails)`               (GC, `nr/src/log.rs:536-580`).

    `limits` (optional, int64[R]) caps each replica's replay at
    `min(tail, limits[r])` — simulated dormant replicas: laggards hold GC
    back (`head` stalls at their ltail) until a later un-limited call lets
    them catch up, mirroring `Replica::sync` (`nr/src/replica.rs:469-479`).

    `fenced` (optional, bool[R]) marks QUARANTINED replicas
    (`fault/health.py`): a fenced replica is frozen at its ltail (its
    state may be corrupt; repair will discard it) AND excluded from the
    `head = min(ltails)` GC reduction, so a dead replica cannot stall
    log GC for the fleet — the runtime difference between a dormant
    laggard (`limits`) and a quarantined casualty.

    Returns `(log, states, resps)` with `resps: int32[R, window]`;
    `resps[r, i]` answers the entry at logical position `old_ltails[r] + i`.
    """
    if fenced is not None:
        limits = _freeze_limits(log, limits, fenced)
    if limits is None:
        states, resps, new_ltails = jax.vmap(
            lambda s, lt: _exec_one(spec, d, log, s, lt, window)
        )(states, log.ltails)
    else:
        states, resps, new_ltails = jax.vmap(
            lambda s, lt, lim: _exec_one(spec, d, log, s, lt, window, lim)
        )(states, log.ltails, jnp.asarray(limits, jnp.int64))
    log = log._replace(
        ltails=new_ltails,
        ctail=jnp.maximum(log.ctail, jnp.max(new_ltails)),
        head=_gc_head(log, new_ltails, fenced),
    )
    return log, states, resps


def log_catchup_all(
    spec: LogSpec,
    d: Dispatch,
    log: LogState,
    states: PyTree,
    window: int,
    limits: jax.Array | None = None,
    need_resps: bool = True,
    on_trajectory: bool = True,
    union: bool | None = None,
    fenced: jax.Array | None = None,
):
    """Combined catch-up: `log_exec_all` semantics at combined speed.

    `fenced` (optional, bool[R]) carries the quarantine mask
    (`fault/health.py`) through every tier: fenced replicas are frozen
    at their ltail, excluded from the GC head reduction, and — on the
    union-plan tier — excluded from BOTH the plan-donor election and
    the merge mask (a quarantined replica's state may be corrupt; a
    plan computed from it, or a merge into it, would be garbage).

    `on_trajectory=False` opts OUT of the union-plan tier for hand-built
    fleets whose states are NOT folds of the shared log (tier 1's
    soundness argument needs the trajectory property); such fleets take
    the per-replica `window_apply` tier, which is correct for arbitrary
    state. Every log-driven fleet (NodeReplicated, the runners, recovery,
    grow_fleet) is on-trajectory by construction.

    `union` selects the union-plan tier: None (default) takes it only
    for models that declare `Dispatch.window_canonical=True` — the
    explicit opt-in to the prefix-absorbing/canonical-responses
    contract (ADVICE r5: presence of window_plan alone only claims the
    lock-step contract and must not route a third-party model through
    the stronger-contract engine). True FORCES the tier (the
    `engine='combined'` caller asserting the contract); False never
    takes it.

    `need_resps=False` (pure recovery: checkpoint replay, crash
    rebuild, the catch-up bench) skips the per-replica response
    re-indexing — on the union-plan path that is an O(R x window)
    random gather that dominates fleet-scale rounds (measured 840 ms of
    an 874 ms round at R=4096) — and returns zeros; the reference's
    catch-up likewise applies other replicas' entries without
    delivering their responses (`nr/src/log.rs:473-524` hands resps
    only to the calling combiner's own batch).

    In the reference, catch-up IS the hot loop — a lagging replica replays
    through the same `exec` everyone uses (`nr/src/log.rs:473-524`). The
    fused step's plan/merge split can't serve that role directly (it
    needs the lock-step precondition, `core/step.py`), so this runs one
    of three engines, fastest applicable first:

    1. **union-window plan** (model provides `window_plan`/`window_merge`
       and no `limits`): every replica of a log-driven fleet lies on the
       SAME replay trajectory — `states[r]` is the fold of
       `[0, ltails[r])` from common init — so the plan of the union
       window `[min(ltails), min(ltails)+window)`, computed ONCE from the
       most-lagging replica's state, merges correctly into every replica
       inside the window: cells the window touches take the plan's final
       value (identical no matter how much of the window a replica
       already applied — deterministic replay), untouched cells keep the
       replica's own (already-canonical) value. Replicas past the window
       end are left untouched. ONE sort serves the fleet — the same
       economics as the lock-step fast path, now for divergent cursors.
       NOT valid for hand-built fleets with off-trajectory states; those
       use `window_apply` (`combined=...` paths) or the scan.
    2. **per-replica `window_apply`** (arbitrary state; also the `limits`
       path — a limit truncates a replica's window individually, so no
       shared plan exists): each replica gathers and combines its own
       window; pays R sorts.
    3. **`log_exec_all` scan** when the model has no combined form.

    Cursor lattice updates match `log_exec_all` except that the
    union-window engine advances every lagging replica to the SAME
    position (the window end) — a faster join of the same lattice.
    Response layout is preserved: `resps[r, i]` answers logical position
    `old_ltails[r] + i` (0 past the replica's advancement), which is
    exactly what response delivery consumes. Differentially tested in
    `tests/test_window.py::TestCombinedCatchup`.
    """
    if d.window_apply is None and d.window_plan is None:
        # nrlint: disable=obs-in-traced — per-trace tier counter by design
        _m_engine_scan.inc()
        return log_exec_all(spec, d, log, states, window, limits,
                            fenced=fenced)
    take_union = (
        d.window_canonical if union is None else union
    ) and d.window_plan is not None
    if take_union and limits is None and on_trajectory:
        return _catchup_union_plan(spec, d, log, states, window,
                                   need_resps, fenced=fenced)
    if d.window_apply is None:
        # nrlint: disable=obs-in-traced — per-trace tier counter by design
        _m_engine_scan.inc()
        return log_exec_all(spec, d, log, states, window, limits,
                            fenced=fenced)
    # nrlint: disable=obs-in-traced — per-trace tier counter by design
    _m_engine_window.inc()
    if fenced is not None:
        limits = _freeze_limits(log, limits, fenced)

    def one(state, ltail, limit=None):
        eff_tail = (
            log.tail if limit is None else jnp.minimum(log.tail, limit)
        )
        check(ltail <= log.tail,
              "replica ltail {lt} ahead of log tail {t}",
              lt=ltail, t=log.tail)
        check(ltail >= log.head,
              "catch-up window starts at {lt}, behind GC head {h}: "
              "entries already overwritten",
              lt=ltail, h=log.head)
        opcodes, args = gather_window(
            spec, log.opcodes, log.args, ltail, eff_tail, window
        )
        state, resps = d.window_apply(state, opcodes, args)
        new_ltail = jnp.minimum(ltail + window, eff_tail)
        new_ltail = jnp.maximum(new_ltail, ltail)  # limit below ltail
        return state, resps, new_ltail

    if limits is None:
        states, resps, new_ltails = jax.vmap(
            lambda s, lt: one(s, lt)
        )(states, log.ltails)
    else:
        states, resps, new_ltails = jax.vmap(one)(
            states, log.ltails, jnp.asarray(limits, jnp.int64)
        )
    log = log._replace(
        ltails=new_ltails,
        ctail=jnp.maximum(log.ctail, jnp.max(new_ltails)),
        head=_gc_head(log, new_ltails, fenced),
    )
    return log, states, resps


def _catchup_union_plan(
    spec: LogSpec,
    d: Dispatch,
    log: LogState,
    states: PyTree,
    window: int,
    need_resps: bool = True,
    fenced: jax.Array | None = None,
):
    """Union-window catch-up (see `log_catchup_all` engine 1).

    Soundness: with deterministic replay from common init, `states[r]`
    is the fold of `[0, ltails[r])`, so for any position p in
    `[m, end]`, `window_merge(state(p), window_plan(state(m), W_m))`
    equals `state(end)` — cells the window `W_m = [m, end)` touches take
    the plan's final value (independent of how much of `W_m` the replica
    already applied: replay of the shared log is deterministic, so the
    replica's own application of a prefix wrote exactly the values the
    plan's events record), untouched cells keep the replica's value,
    which equals the canonical one. Replicas whose cursor is PAST the
    window end must not merge (the plan's final values could rewind
    them); they are masked out and keep their state and cursor.

    `fenced` (bool[R], optional — the quarantine mask, `fault/`): a
    fenced replica is OFF the shared trajectory by assumption (that is
    why it was quarantined), so it is excluded from the plan-donor
    election (`argmin` over unfenced ltails — a corrupt donor would
    poison the whole fleet's merge), from the union-window start
    (`m = min` over unfenced), from the merge mask, and from the GC
    head reduction; its state and cursor hold still for repair.
    """
    if fenced is not None:
        fenced = jnp.asarray(fenced, bool)
    masked_lt = (
        log.ltails if fenced is None
        else jnp.where(fenced, jnp.int64(_FAR), log.ltails)
    )
    # Idle short-circuit (ADVICE r5): when even the most-lagging replica
    # is at the tail there is nothing to replay, and the full
    # plan-sort + vmapped merge below would run for nothing. Host-side
    # check, so it only triggers for EAGER callers whose cursors are
    # concrete; under jit the cursors are tracers and the caller is
    # responsible for the skip (NodeReplicated._exec_round holds the
    # jit-hot equivalent).
    if (
        not isinstance(log.tail, jax.core.Tracer)
        and not isinstance(log.ltails, jax.core.Tracer)
        and not isinstance(fenced, jax.core.Tracer)
    ):
        lt = np.asarray(log.ltails)
        # every LIVE cursor exactly at tail (the max bound lets
        # corrupted ltails > tail fall through to the debug-mode
        # checks below); fenced cursors are frozen and don't count
        live = lt if fenced is None else lt[~np.asarray(fenced)]
        idle = bool(
            live.size
            and int(live.min()) >= int(log.tail) >= int(live.max())
        )
        if idle and fenced is not None:
            # a freshly fenced laggard may still pin head below the
            # live min: one device round must run to advance GC
            idle = int(np.asarray(log.head)) >= int(live.min())
        if idle:
            _m_idle_skips.inc()
            R = log.ltails.shape[0]
            return log, states, jnp.zeros((R, window), jnp.int32)
    # nrlint: disable=obs-in-traced — per-trace tier counter by design
    _m_engine_union.inc()
    m = jnp.min(masked_lt)
    end = jnp.minimum(m + window, log.tail)
    check(m >= log.head,
          "catch-up window starts at {m}, behind GC head {h}: entries "
          "already overwritten",
          m=m, h=log.head)
    check(jnp.max(log.ltails) <= log.tail,
          "replica ltail {lt} ahead of log tail {t}",
          lt=jnp.max(log.ltails), t=log.tail)
    opcodes, args = gather_window(
        spec, log.opcodes, log.args, m, end, window
    )
    donor = jnp.argmin(masked_lt)
    donor_state = jax.tree.map(lambda x: x[donor], states)
    plan = d.window_plan(donor_state, opcodes, args)
    merged, presps = jax.vmap(lambda s: d.window_merge(s, plan))(states)
    take = log.ltails < end
    if fenced is not None:
        take = take & ~fenced
    states = jax.tree.map(
        lambda a, b: jnp.where(
            take.reshape((-1,) + (1,) * (a.ndim - 1)), b, a
        ),
        states, merged,
    )
    if need_resps:
        # response layout contract: resps[r, i] answers logical position
        # old_ltails[r] + i — gathered from the canonical per-position
        # plan responses; positions at/past the replica's new cursor are
        # 0 (never consumed by delivery)
        offs = (log.ltails - m)[:, None] + jnp.arange(
            window, dtype=jnp.int64
        )[None, :]
        resps = jnp.take_along_axis(
            presps, jnp.clip(offs, 0, window - 1).astype(jnp.int32),
            axis=1,
        )
        # fenced cursors can sit BELOW the (live-min) window start, so
        # their offsets go negative — mask those rows to 0 alongside
        # the past-window positions (delivery never consumes a fenced
        # replica's row anyway: its ltail does not advance)
        resps = jnp.where((offs >= 0) & (offs < (end - m)), resps, 0)
    else:
        resps = jnp.zeros_like(presps)
    new_ltails = jnp.maximum(log.ltails, end)
    if fenced is not None:
        new_ltails = jnp.where(fenced, log.ltails, new_ltails)
    log = log._replace(
        ltails=new_ltails,
        ctail=jnp.maximum(log.ctail, jnp.max(new_ltails)),
        head=_gc_head(log, new_ltails, fenced),
    )
    return log, states, resps


def ring_slice(
    spec: LogSpec, log: LogState, start: int, stop: int
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side readback of ring entries `[start, stop)` as numpy
    `(opcodes int32[n], args int32[n, A])`.

    The durability plane's bridge out of device memory
    (`durable/wal.py`): `NodeReplicated.attach_wal(backfill=True)`
    persists entries that were appended BEFORE the WAL attached, and
    they are only readable while the ring still physically holds them —
    `start >= tail - capacity` (a wrapped slot has been overwritten; a
    WAL attached that late needs a snapshot instead). The durable-tail
    cursor itself lives host-side on the WAL (`WriteAheadLog.
    durable_tail`), not in `LogState`: fsync progress is host truth and
    must never enter the compiled step. Positions at/past `tail` raise
    — they are not live entries.
    """
    start, stop = int(start), int(stop)
    tail = int(log.tail)
    if stop > tail:
        raise ValueError(
            f"ring_slice [{start}, {stop}) runs past tail {tail}"
        )
    if start < tail - spec.capacity:
        raise ValueError(
            f"ring_slice [{start}, {stop}) starts below "
            f"tail - capacity = {tail - spec.capacity}: entries "
            f"already overwritten by ring wrap"
        )
    if stop < start:
        raise ValueError(f"ring_slice [{start}, {stop}) is negative")
    idx = (np.arange(start, stop, dtype=np.int64)
           & spec.mask).astype(np.int32)
    opcodes = np.asarray(log.opcodes)[idx]
    args = np.asarray(log.args)[idx]
    return opcodes, args


def is_replica_synced_for_reads(
    log: LogState, ridx: int, ctail: jax.Array
) -> jax.Array:
    """`nr/src/log.rs:671-675`: may replica `ridx` serve reads issued when
    the completed tail was `ctail`?"""
    return log.ltails[ridx] >= ctail


def get_ctail(log: LogState) -> jax.Array:
    """`nr/src/log.rs:677-679`."""
    return log.ctail
